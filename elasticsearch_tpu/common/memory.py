"""Device-memory accounting: the HBM ledger + circuit breaker.

Reference analogs: HierarchyCircuitBreakerService (parent + child
breakers; CircuitBreakingException → HTTP 429) and the fielddata /
request breakers (SURVEY.md §2.1 Memory management row). The TPU-native
resource is HBM: device-resident postings tiles, doc-value columns,
vectors, norm caches, and dense hot-term rows all charge the ledger at
upload. When a WOULD-BE upload cannot fit, the allocator either
degrades (dense hot rows are an optimization — the chunked scorer path
covers correctness without them) or trips the breaker.

Categories in use: `postings`/`doc_values`/`vectors`/`norms`/`dense`
(index-resident uploads), `query_cache` (device filter bitsets, own
LRU budget), `serving` — the serving pipeline's persistent padded
staging slabs (executor_jax.staging_slab: fixed-size rings of reusable
query-operand buffers, sized to workers × (pipeline_depth + 1), charged
once at first use and released with the executor) — and `mesh`, the
mesh-parallel serving stacks (parallel/mesh_executor.py: per-snapshot
device views of an index's live (shard, segment) entries, charged at
build and released on generation rebuild/close; a stack that cannot fit
DEGRADES the request to the single-device path instead of tripping the
breaker). `rerank` holds the second-stage reranker's shard-level
`rank_vectors` token columns (search/rescorer.py; a column that cannot
fit DEGRADES TO SKIP — the request keeps its first-stage ranking).
`impacts` holds the learned-sparse impact-tile columns
(executor_jax.impact_scorer: per-(segment, field, storage-mode) uploads
of the impact-ordered doc/value planes, int8 or fp32; a column that
cannot fit DEGRADES to the dense fp32 host oracle — exact answers,
just not device-served). Per-category bytes surface as child breakers
in `_nodes/stats` (child_breakers())."""

from __future__ import annotations

import os
import threading
from typing import Dict


class CircuitBreakingException(Exception):
    """es analog: circuit_breaking_exception, HTTP 429."""

    def __init__(self, reason: str, bytes_wanted: int, limit: int):
        super().__init__(reason)
        self.reason = reason
        self.bytes_wanted = bytes_wanted
        self.limit = limit
        self.status = 429
        self.err_type = "circuit_breaking_exception"


def _default_budget() -> int:
    # v5e has 16 GiB HBM; leave headroom for XLA scratch + accumulators.
    # Overridable for tests and other parts.
    env = os.environ.get("ES_TPU_HBM_BUDGET_BYTES")
    if env:
        return int(env)
    return 12 * 1024**3


class HbmLedger:
    """Byte accounting per category with a hard budget.

    Not a malloc hook — JAX owns real allocation. This tracks the
    framework's OWN resident uploads (the analog of ES accounting its
    own BigArrays rather than the JVM heap) so admission control can
    refuse or degrade before the device OOMs.
    """

    def __init__(self, budget: int | None = None):
        self.budget = budget if budget is not None else _default_budget()
        self._lock = threading.Lock()
        self._by_category: Dict[str, int] = {}
        self.stats_counters = {"tripped": 0, "degraded": 0}

    @property
    def used(self) -> int:
        with self._lock:
            return sum(self._by_category.values())

    def would_fit(self, nbytes: int) -> bool:
        return self.used + nbytes <= self.budget

    def add(self, category: str, nbytes: int, breaker: bool = True) -> None:
        """Charges the ledger; raises CircuitBreakingException when the
        budget would be exceeded and `breaker` is set (non-breaker adds
        record overage instead — better a tracked overage than a lying
        ledger)."""
        with self._lock:
            used = sum(self._by_category.values())
            if breaker and used + nbytes > self.budget:
                self.stats_counters["tripped"] += 1
                raise CircuitBreakingException(
                    f"[hbm] Data too large: would use "
                    f"{used + nbytes} bytes, limit {self.budget}",
                    bytes_wanted=nbytes,
                    limit=self.budget,
                )
            self._by_category[category] = (
                self._by_category.get(category, 0) + nbytes
            )

    def release(self, category: str, nbytes: int) -> None:
        with self._lock:
            left = self._by_category.get(category, 0) - nbytes
            if left <= 0:
                self._by_category.pop(category, None)
            else:
                self._by_category[category] = left

    def note_degraded(self) -> None:
        with self._lock:
            self.stats_counters["degraded"] += 1

    def stats(self) -> dict:
        with self._lock:
            used = sum(self._by_category.values())
            return {
                "limit_size_in_bytes": self.budget,
                "estimated_size_in_bytes": used,
                "by_category": dict(self._by_category),
                "tripped": self.stats_counters["tripped"],
                "degraded_allocations": self.stats_counters["degraded"],
            }

    def child_breakers(self) -> Dict[str, dict]:
        """ES-style child-breaker entries, one per ledger category
        (postings tiles, norms, dense rows, query_cache bitsets, …) —
        the per-category byte usage the `_nodes/stats` breakers section
        surfaces next to the `hbm` parent."""
        with self._lock:
            return {
                f"hbm.{cat}": {
                    "limit_size_in_bytes": self.budget,
                    "estimated_size_in_bytes": nbytes,
                }
                for cat, nbytes in sorted(self._by_category.items())
            }


# process-wide ledger (one device per process in this deployment shape)
hbm_ledger = HbmLedger()


def array_nbytes(a) -> int:
    try:
        return int(a.nbytes)
    except AttributeError:
        return 0
