"""Deterministic fault-injection harness for the serving path.

Reference analog: org.elasticsearch.test.transport.MockTransportService
+ the DisruptionScheme family (NetworkDisruption, SlowClusterStateProcessing)
— ES's integration suites wrap the real transport/search services with
rule-driven fault injectors so failure-handling code is exercised
deterministically in CI. Here the production code itself carries named
injection *sites* (`faults.check(site, **ctx)` — a no-op when no
schedule is armed) and a process-wide registry holds the armed rules.

Schedule shape (env `ES_TPU_FAULTS`, or `POST /_internal/faults`):

    {"seed": 42, "rules": [
        {"site": "shard.search", "match": {"index": "books", "shard": 1},
         "kind": "error", "prob": 1.0, "times": 1},
        {"site": "shard.search", "kind": "stall", "delay_ms": 2000,
         "match": {"shard": 3}},
        {"site": "transport.send", "kind": "drop", "prob": 0.1}
    ]}

* ``site``: fnmatch pattern over the site name. Known sites:
  - ``transport.send``      (every outbound transport request)
  - ``shard.search``        (per-shard query-phase call in the fan-out)
  - ``shard.count``         (per-shard count call)
  - ``batcher.dispatch``    (QueryBatcher device-dispatch of one group)
  - ``batcher.collect``     (QueryBatcher host-collect of one group)
  - ``knn.collect``         (kNN group device→host collect)
  - ``admission.acquire``   (per-request admission gate)
  - ``aggs.collect``        (device-aggregation plan dispatch — ctx
    carries index/shard; an injected error here exercises the
    device→host AggCollector fallback deterministically)
  - ``ann.probe``           (IVF ANN probe path, per segment — ctx
    carries field/segment; error kind proves the deterministic
    IVF→exact brute-force fallback, delay kind the slow-not-wrong
    contract)
  - ``rerank.score``        (second-stage maxsim rescore dispatch —
    ctx carries field (+ mesh=1 on the SPMD path); error kind proves
    the deterministic rerank→first-stage-order fallback (the request
    keeps its first-stage ranking bit-for-bit and the `fallbacks`
    counter increments), delay kind the slow-not-wrong contract)
  - ``sparse.score``        (learned-sparse impact-tile scoring — per
    segment on the batcher path with ctx field/segment, mesh=1 on the
    SPMD path; error kind proves the deterministic impact→dense-host-
    oracle fallback (exact answers, `fallbacks` bump), delay kind the
    slow-not-wrong contract — the ann.probe recipe for the third
    retrieval family)

  Write-path sites (the durability mirror of the read-path list; the
  crash-matrix harness in index/crashpoints.py + tests/test_durability.py
  drives every one of them with the ``crash`` kind):
  - ``translog.append``     (per WAL record, BEFORE the bytes reach the
    log — ctx carries shard/gen/seq_no/op; a ``crash`` rule here with
    ``"torn": true`` leaves a PARTIAL record on disk, the torn-tail
    shape recovery must truncate)
  - ``translog.fsync``      (inside Translog.sync, BEFORE the pending
    tail is written+fsynced — a crash here loses exactly the
    acked-but-unsynced window of `async` durability)
  - ``engine.refresh``      (segment build from the indexing buffer —
    fires at refresh BEGIN, before any state moves; on the
    double-buffered path (ShardEngine.refresh_concurrent) an error
    keeps the old generation serving and the ops buffered)
  - ``build.device``        (device segment-build dispatch,
    index/segment_build.py — ctx carries shard; an injected error
    proves the deterministic device→host-build fallback (same
    bit-identical columns, counted `fallbacks`), delay the
    slow-not-wrong contract, ``crash`` a power loss mid-build)
  - ``engine.flush``        (durable commit — ctx carries shard and a
    ``stage`` of start | pre_manifest | post_manifest, bracketing the
    segment-persist / manifest-replace / translog-trim windows)
  - ``engine.merge``        (segment-count merge rebuild)
  - ``replica.replicate``   (primary→replica write fan-out, per target —
    ctx index/shard/target; error kind proves the failed copy leaves
    the in-sync set instead of silently diverging)
  - ``recovery.transfer``   (peer-recovery phase 1 file copy, target
    side — ctx index/shard/node)
  - ``recovery.finalize``   (peer-recovery phase 2 ops replay, target
    side — ctx index/shard/node)
  - ``relocation.start``    (shard relocation kicking off — fires on
    BOTH endpoints with ctx index/shard/node/role: role=target before
    the target's peer recovery begins, role=source when the source
    receives the recovery/start request for its relocation target;
    error/crash abort the attempt cleanly — the source keeps serving,
    the recovery retry loop or a fresh reroute re-runs the move)
  - ``relocation.transfer`` (the bulk transfer leg — role=target after
    phase 1 returns, role=source inside recovery/finalize when the
    requester is the relocation target; the same
    abort-and-retry-cleanly contract as recovery.transfer)
  - ``relocation.handoff``  (the cutover handoff — role=target before
    the target asks the source to drain, role=source at the top of the
    drain handler BEFORE any permit state changes, so an injected
    error/crash leaves the source still serving writes; tests drive
    error + crash + delay at every site × both roles)
* ``match``: exact-equality filters over the ctx kwargs the site passes
  (string-compared, so {"shard": 1} matches shard=1).
* ``kind``: ``error`` (raise InjectedFault, 500-shaped), ``drop``
  (raise InjectedFault shaped like a connect_transport_exception),
  ``delay`` / ``stall`` (sleep ``delay_ms`` then proceed — ``stall``
  is the slow-kernel simulation; both behave identically, the name
  documents intent), ``load`` (no sleep, no raise: ``delay_ms`` is
  returned to the caller as a SYNTHETIC queue-pressure sample —
  `check` returns ``{"load_ms": N}`` — so overload schedules replay
  deterministically without real queue contention; only the
  admission site consumes it today), ``crash`` (raise SimulatedCrash —
  a BaseException, so no production `except Exception` handler can
  "handle" a power loss; the harness catches it, tears the
  engine/node down WITHOUT running close/flush paths, and reopens
  from disk. ``"torn": true`` on the rule additionally asks the site
  to leave a partial write of the in-flight record behind — only
  ``translog.append`` honors it today).
* ``prob``: trip probability (default 1.0). Draws are a pure hash of
  (seed, rule index, site, ctx, per-ctx attempt counter) — NOT a
  sequential RNG — so the schedule is deterministic regardless of
  thread interleaving across the fan-out, and a replica retry of the
  same shard re-draws with attempt+1 instead of being auto-doomed.
* ``times``: cap on total trips for the rule (unlimited when absent).
* ``skip``: deterministic onset — the first N matching draws do not
  trip (with ``times: 1`` this reads "crash exactly at the (N+1)th
  append/fsync/flush", the lever the crash matrix steers with).

The registry is intentionally process-global (like the settings
registries): tests and the `/_internal/faults` hook arm/clear it.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

FAULTS_ENV = "ES_TPU_FAULTS"


class InjectedFault(Exception):
    """A fault raised by the harness. Carries a REST-ish status/err_type
    so failure accounting can report it like a real exception class."""

    def __init__(
        self,
        reason: str,
        err_type: str = "injected_fault_exception",
        status: int = 500,
    ):
        super().__init__(reason)
        self.reason = reason
        self.err_type = err_type
        self.status = status


class SimulatedCrash(BaseException):
    """Deterministic power loss injected by a ``crash`` rule.

    Deliberately a BaseException: production code paths catch Exception
    liberally (fallbacks, retries, recovery loops) and none of them may
    "survive" a power loss — the crash must unwind all the way to the
    harness, which tears the engine/node down without running any
    close/flush path and then reopens from disk. ``torn`` asks the
    injection site to leave a partial write of the in-flight record
    behind (a torn tail) before unwinding."""

    def __init__(self, reason: str, torn: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.torn = torn


class _Rule:
    __slots__ = (
        "index", "site", "match", "kind", "prob", "times", "delay_ms",
        "torn", "skip", "trips", "attempts",
    )

    def __init__(self, index: int, spec: dict):
        self.index = index
        self.site = str(spec.get("site", "*"))
        self.match = {
            str(k): str(v) for k, v in (spec.get("match") or {}).items()
        }
        kind = str(spec.get("kind", "error"))
        if kind not in ("error", "drop", "delay", "stall", "load", "crash"):
            raise ValueError(f"unknown fault kind [{kind}]")
        self.kind = kind
        self.prob = float(spec.get("prob", 1.0))
        self.times = spec.get("times")
        if self.times is not None:
            self.times = int(self.times)
        self.delay_ms = float(spec.get("delay_ms", 100.0))
        self.torn = bool(spec.get("torn", False))
        # deterministic onset: the first `skip` matching (and
        # probability-passing) draws do NOT trip — "crash at the Nth
        # append", the lever the write-path crash matrix steers with
        self.skip = int(spec.get("skip", 0))
        self.trips = 0
        self.attempts = 0

    def matches(self, site: str, ctx: Dict[str, Any]) -> bool:
        if not fnmatch.fnmatch(site, self.site):
            return False
        for k, v in self.match.items():
            if str(ctx.get(k)) != v:
                return False
        return True

    def info(self) -> dict:
        return {
            "site": self.site,
            "match": dict(self.match),
            "kind": self.kind,
            "prob": self.prob,
            "times": self.times,
            "delay_ms": self.delay_ms,
            "torn": self.torn,
            "skip": self.skip,
            "trips": self.trips,
            "attempts": self.attempts,
        }


def _ctx_sig(ctx: Dict[str, Any]) -> str:
    return "|".join(f"{k}={ctx[k]}" for k in sorted(ctx))


class FaultRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[_Rule] = []
        self._seed = 0
        # per-(rule, ctx) attempt counters: a retry of the same shard on
        # another copy draws independently from the first attempt
        self._attempts: Dict[tuple, int] = {}

    @property
    def active(self) -> bool:
        return bool(self._rules)

    def configure(self, config: Optional[dict]) -> dict:
        """Replaces the schedule atomically; None/{} clears it."""
        config = config or {}
        rules = [
            _Rule(i, spec) for i, spec in enumerate(config.get("rules") or [])
        ]
        with self._lock:
            self._seed = int(config.get("seed", 0))
            self._rules = rules
            self._attempts.clear()
        return self.describe()

    def clear(self) -> None:
        with self._lock:
            self._rules = []
            self._attempts.clear()

    def describe(self) -> dict:
        with self._lock:
            return {
                "active": bool(self._rules),
                "seed": self._seed,
                "rules": [r.info() for r in self._rules],
            }

    def _draw(self, rule: _Rule, site: str, sig: str, attempt: int) -> float:
        key = f"{self._seed}|{rule.index}|{site}|{sig}|{attempt}"
        h = hashlib.sha256(key.encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def check(self, site: str, **ctx) -> Optional[dict]:
        """Injection point. Raises InjectedFault (error/drop rules),
        sleeps (delay/stall rules), or returns an effects dict (load
        rules: ``{"load_ms": N}`` — a synthetic queue-pressure sample
        the admission site feeds into its congestion signal); a no-op
        returning None when nothing is armed."""
        if not self._rules:  # fast path: unarmed in production
            return None
        sleep_ms = 0.0
        load_ms = 0.0
        boom: Optional[BaseException] = None
        with self._lock:
            sig = _ctx_sig(ctx)
            for rule in self._rules:
                if not rule.matches(site, ctx):
                    continue
                if rule.times is not None and rule.trips >= rule.times:
                    continue
                akey = (rule.index, sig)
                attempt = self._attempts.get(akey, 0)
                self._attempts[akey] = attempt + 1
                rule.attempts += 1
                if rule.prob < 1.0 and (
                    self._draw(rule, site, sig, attempt) >= rule.prob
                ):
                    continue
                if rule.skip > 0:
                    rule.skip -= 1
                    continue
                rule.trips += 1
                if rule.kind in ("delay", "stall"):
                    sleep_ms = max(sleep_ms, rule.delay_ms)
                elif rule.kind == "load":
                    load_ms = max(load_ms, rule.delay_ms)
                elif rule.kind == "crash":
                    boom = SimulatedCrash(
                        f"simulated crash at [{site}] ({sig})",
                        torn=rule.torn,
                    )
                    break
                elif rule.kind == "drop":
                    boom = InjectedFault(
                        f"injected connection drop at [{site}] ({sig})",
                        err_type="connect_transport_exception",
                    )
                    break
                else:
                    boom = InjectedFault(
                        f"injected error at [{site}] ({sig})"
                    )
                    break
        if sleep_ms > 0.0:
            time.sleep(sleep_ms / 1000.0)
        if boom is not None:
            raise boom
        return {"load_ms": load_ms} if load_ms > 0.0 else None


faults = FaultRegistry()

# env-armed schedule (read once at import, like the other ES_TPU_* knobs)
_raw = os.environ.get(FAULTS_ENV, "")
if _raw:
    try:
        faults.configure(json.loads(_raw))
    except (ValueError, TypeError):
        pass  # a malformed schedule must never take the node down
