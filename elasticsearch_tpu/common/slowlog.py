"""Per-index search slow logs.

Reference analog: `index.search.slowlog.threshold.{query,fetch}.{warn,
info,debug,trace}` in org.elasticsearch.index.SearchSlowLog — dynamic
per-index thresholds, one structured single-line record per offending
phase, emitted through a per-index logger so operators can route/filter
by index name.

Here each index owns a `SearchSlowLog` bound to the stdlib logger
`index.search.slowlog.<index>`; records are one-line JSON (took,
shards, truncated source, X-Opaque-Id, profile summary when the request
was profiled). Counters per level feed `{index}/_stats` so tests and
dashboards can assert firing without scraping log output.

`FETCH_ACC` is the fetch-phase accumulator: `IndexService.search()`
arms it with a mutable dict, shard fetch loops add their nanoseconds
(the dict object is shared across fan-out threads via copied
contexts), and the coordinator reads the total for the fetch-phase
threshold check. It is always-on and costs one contextvar read plus an
int add per shard.
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
from typing import Any, Dict, Optional

# fetch-phase time accumulator for the current request:
# {"fetch_ns": int} or None outside a search
FETCH_ACC: contextvars.ContextVar = contextvars.ContextVar(
    "fetch_acc", default=None
)

LEVELS = ("warn", "info", "debug", "trace")

_LOG_LEVELS = {
    "warn": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG,
}

_SOURCE_CAP = 1000  # chars of serialized source kept per record


def parse_threshold_ms(value) -> float:
    """Setting value -> threshold in fractional ms. "-1" (or any
    negative) disables; "0" fires on every request; otherwise accepts
    bare numbers (ms) or the suffixed forms the settings parser emits
    (ns/micros/ms/s/m/h)."""
    if value is None:
        return -1.0
    s = str(value).strip().lower()
    if not s:
        return -1.0
    mult = 1.0  # -> ms
    for suffix, m in (
        ("micros", 1e-3), ("nanos", 1e-6), ("ns", 1e-6),
        ("ms", 1.0), ("s", 1000.0), ("m", 60000.0), ("h", 3600000.0),
    ):
        if s.endswith(suffix):
            s = s[: -len(suffix)]
            mult = m
            break
    try:
        v = float(s)
    except ValueError:
        return -1.0
    if v < 0:
        return -1.0
    return v * mult


def pick_level(took_ms: float, thresholds: Dict[str, float]) -> Optional[str]:
    """Most severe level whose enabled threshold the took meets.
    Severity order is warn > info > debug > trace, so scanning in
    LEVELS order returns the right record level when several match."""
    for lvl in LEVELS:
        t = thresholds.get(lvl, -1.0)
        if t >= 0 and took_ms >= t:
            return lvl
    return None


class SearchSlowLog:
    """Per-index slow-log emitter with dynamic thresholds."""

    def __init__(self, index_name: str):
        self.index = index_name
        self._logger = logging.getLogger(f"index.search.slowlog.{index_name}")
        self._lock = threading.Lock()
        # phase -> level -> threshold in ms (-1 disabled)
        self._thresholds: Dict[str, Dict[str, float]] = {
            "query": {lvl: -1.0 for lvl in LEVELS},
            "fetch": {lvl: -1.0 for lvl in LEVELS},
        }
        self.counters: Dict[str, int] = {
            f"{phase}_{lvl}": 0
            for phase in ("query", "fetch") for lvl in LEVELS
        }

    # ---- configuration ----

    def configure(self, settings: Dict[str, Any]) -> None:
        """Reads the flat `search.slowlog.threshold.*` keys from an
        index settings dict (values as stored by the settings layer)."""
        with self._lock:
            for phase in ("query", "fetch"):
                for lvl in LEVELS:
                    key = f"search.slowlog.threshold.{phase}.{lvl}"
                    if key in settings:
                        self._thresholds[phase][lvl] = parse_threshold_ms(
                            settings[key]
                        )

    def thresholds(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {p: dict(t) for p, t in self._thresholds.items()}

    def enabled(self) -> bool:
        with self._lock:
            return any(
                t >= 0
                for phase in self._thresholds.values()
                for t in phase.values()
            )

    # ---- emission ----

    def on_search(
        self,
        took_ms: float,
        fetch_ms: float,
        *,
        shards: int = 1,
        source: Optional[dict] = None,
        opaque_id: Optional[str] = None,
        profile_summary: Optional[dict] = None,
    ) -> Dict[str, Optional[str]]:
        """Called once per completed coordinator search. Returns the
        levels that fired per phase (for tests); emits at most one
        record per phase."""
        with self._lock:
            q_lvl = pick_level(took_ms, self._thresholds["query"])
            f_lvl = pick_level(fetch_ms, self._thresholds["fetch"])
            if q_lvl:
                self.counters[f"query_{q_lvl}"] += 1
            if f_lvl:
                self.counters[f"fetch_{f_lvl}"] += 1
        if q_lvl:
            self._emit("query", q_lvl, took_ms, shards, source,
                       opaque_id, profile_summary)
        if f_lvl:
            self._emit("fetch", f_lvl, fetch_ms, shards, source,
                       opaque_id, profile_summary)
        return {"query": q_lvl, "fetch": f_lvl}

    def _emit(self, phase, level, took_ms, shards, source, opaque_id,
              profile_summary) -> None:
        record = {
            "type": "index_search_slowlog",
            "level": level,
            "phase": phase,
            "index": self.index,
            "took_ms": round(float(took_ms), 3),
            "shards": int(shards),
            "source": _truncate_source(source),
            "opaque_id": opaque_id,
        }
        if profile_summary:
            record["profile"] = profile_summary
        try:
            self._logger.log(
                _LOG_LEVELS[level], "%s",
                json.dumps(record, default=str, separators=(",", ":")),
            )
        except Exception:  # logging must never fail a search
            pass

    # ---- stats ----

    def stats(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "thresholds_ms": {
                    p: dict(t) for p, t in self._thresholds.items()
                },
            }


def _truncate_source(source: Optional[dict]) -> Optional[str]:
    if source is None:
        return None
    try:
        s = json.dumps(source, default=str, separators=(",", ":"))
    except Exception:
        s = str(source)
    if len(s) > _SOURCE_CAP:
        s = s[:_SOURCE_CAP] + "...(truncated)"
    return s
