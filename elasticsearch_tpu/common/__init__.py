"""Shared infrastructure (settings registry, stats).

Reference analog: org.elasticsearch.common.** leaf utilities.
"""
