"""Shared infrastructure (settings registry, small utilities).

Reference analog: org.elasticsearch.common.** leaf utilities.
"""

from typing import Any, Dict


def deep_merge(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge, non-mutating; override wins on conflicts
    (XContentHelper.mergeDefaults inverted: used for template application
    and _update partial-doc merges)."""
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out
