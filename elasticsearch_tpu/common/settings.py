"""Typed settings registry with ES scope semantics.

Reference analog: org.elasticsearch.common.settings — `Setting<T>` with
`Property.{Dynamic,NodeScope,IndexScope,Final}` registered in
`ClusterSettings` / `IndexScopedSettings`; dynamic updates dispatch to
registered consumers (`addSettingsUpdateConsumer`), final settings
reject updates, unknown settings are rejected on write (SURVEY.md §5
"Config / flag system"). The north-star selector
``index.search.backend`` is exactly an index-scoped static setting here.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

NODE_SCOPE = "node"
CLUSTER_SCOPE = "cluster"
INDEX_SCOPE = "index"

# ---- env-backed node-scope serving knobs (read at process start like
# ES's jvm.options / system properties; not dynamically updatable) ----

# Batches a dispatcher worker keeps in flight on device before blocking
# on a collect: 1 reproduces the pre-pipeline dispatch→collect loop
# bit-for-bit, 2 double-buffers (batch N+1's kernels launch while batch
# N's hits are built on the host).
PIPELINE_DEPTH_ENV = "ES_TPU_PIPELINE_DEPTH"
PIPELINE_DEPTH_DEFAULT = 2

# Peak accelerator FLOP/s used as the MFU/roofline denominator. The
# default is a v5e's bf16 MXU peak (1.97e14) — a conservative (large)
# denominator for the fp32 kernels, so reported MFU understates rather
# than flatters. Override per part.
PEAK_FLOPS_ENV = "ES_TPU_PEAK_FLOPS"
PEAK_FLOPS_DEFAULT = 1.97e14

# ---- continuous-batching launch-shape ladder (search/batcher.py) ----
#
# ES_TPU_BATCH_BUCKETS:  comma/space-separated query-row bucket sizes the
#                        serving kernels compile at (default derived from
#                        the BPAD cap: "1,4,8,16,…,BPAD"). Dispatch pads a
#                        group to the SMALLEST bucket >= its occupancy, so
#                        a batch of 3 jobs pays a 4-wide launch instead of
#                        the full fixed width. "32" reproduces the
#                        pre-ladder fixed-shape behavior (the latency-
#                        smoke baseline). Values outside [1, BPAD] are
#                        dropped; an empty/invalid list falls back to the
#                        default ladder.
# ES_TPU_BUCKET_WARMUP:  "1" (default) | "0" — eagerly compile every
#                        ladder bucket of a kernel family the first time
#                        that family dispatches, so bucket selection never
#                        compiles on the steady-state hot path. Tier-1
#                        pins it off (tests/conftest.py) to keep suite
#                        compile time down; tests re-arm it per batcher.

BATCH_BUCKETS_ENV = "ES_TPU_BATCH_BUCKETS"
BATCH_WARMUP_ENV = "ES_TPU_BUCKET_WARMUP"

_BUCKETS_MEMO: Dict[Any, tuple] = {}


def _default_batch_buckets(bpad: int) -> tuple:
    out = [1]
    b = 4
    while b < bpad:
        out.append(b)
        b *= 2
    if bpad not in out:
        out.append(bpad)
    return tuple(out)


def batch_buckets(bpad: int = 32) -> tuple:
    """Ascending launch-shape ladder for the query-row dimension."""
    raw = os.environ.get(BATCH_BUCKETS_ENV, "").strip()
    key = (raw, int(bpad))
    memo = _BUCKETS_MEMO.get(key)
    if memo is not None:
        return memo
    vals: tuple = ()
    if raw:
        try:
            parsed = sorted({int(x) for x in raw.replace(",", " ").split()})
            vals = tuple(v for v in parsed if 1 <= v <= bpad)
        except ValueError:
            vals = ()
    if not vals:
        vals = _default_batch_buckets(bpad)
    _BUCKETS_MEMO[key] = vals
    return vals


def bucket_for(n: int, buckets, multiple_of: int = 1) -> int:
    """Smallest ladder bucket >= n (and divisible by `multiple_of`, the
    mesh ``data``-axis constraint). Falls back to rounding n up to the
    multiple when no ladder entry qualifies."""
    m = max(1, int(multiple_of))
    for b in buckets:
        if b >= n and b % m == 0:
            return b
    return m * (-(-max(int(n), 1) // m))


def bucket_warmup() -> bool:
    """Whether first-dispatch eager bucket warmup is enabled."""
    raw = os.environ.get(BATCH_WARMUP_ENV, "").strip().lower()
    return raw not in ("0", "off", "false")


def pipeline_depth() -> int:
    """Dispatcher in-flight ring depth (>= 1)."""
    raw = os.environ.get(PIPELINE_DEPTH_ENV, "")
    try:
        v = int(raw) if raw else PIPELINE_DEPTH_DEFAULT
    except ValueError:
        v = PIPELINE_DEPTH_DEFAULT
    return max(1, v)


# ---- mesh-parallel serving knobs (parallel/mesh_executor.py) ----
#
# ES_TPU_MESH:          "auto" (default: engage when >= 2 devices and the
#                       index has >= 2 shards), "force" (route every
#                       eligible group to the mesh, even on 1 device —
#                       bench sweeps use this), or "off".
# ES_TPU_MESH_DEVICES:  cap on how many devices the serving mesh uses
#                       (default: all visible devices).
# ES_TPU_MESH_DATA:     size of the ``data`` (query-batch) mesh axis
#                       (default 1 — all devices go to the shards axis).
#                       Must divide the BPAD query batch; invalid values
#                       fall back to 1.
# ES_TPU_MESH_T_MAX:    per-(entry, query) tile-slot cap for one mesh
#                       text launch; groups that overflow fall back to
#                       the single-device path (default 4096).

MESH_MODE_ENV = "ES_TPU_MESH"
MESH_DEVICES_ENV = "ES_TPU_MESH_DEVICES"
MESH_DATA_ENV = "ES_TPU_MESH_DATA"
MESH_T_MAX_ENV = "ES_TPU_MESH_T_MAX"
MESH_T_MAX_DEFAULT = 4096


def mesh_mode() -> str:
    """Serving-mesh routing mode: "auto" | "force" | "off"."""
    v = os.environ.get(MESH_MODE_ENV, "auto").strip().lower()
    return v if v in ("auto", "force", "off") else "auto"


def mesh_devices_cap() -> int:
    """Max devices the serving mesh may use (0 = all)."""
    raw = os.environ.get(MESH_DEVICES_ENV, "")
    try:
        v = int(raw) if raw else 0
    except ValueError:
        v = 0
    return max(0, v)


def mesh_data_axis() -> int:
    """Requested size of the mesh ``data`` axis (>= 1)."""
    raw = os.environ.get(MESH_DATA_ENV, "")
    try:
        v = int(raw) if raw else 1
    except ValueError:
        v = 1
    return max(1, v)


def mesh_t_max() -> int:
    """Tile-slot cap per (entry, query) for one mesh text launch."""
    raw = os.environ.get(MESH_T_MAX_ENV, "")
    try:
        v = int(raw) if raw else MESH_T_MAX_DEFAULT
    except ValueError:
        v = MESH_T_MAX_DEFAULT
    return max(64, v)


# ---- device-aggregations knobs (search/aggs_device.py) ----
#
# ES_TPU_DEVICE_AGGS:  "auto" (default) — size:0/agg bodies whose whole
#                      agg tree is device-supported AND float-exact-safe
#                      (integer-valued columns within the float32 exact
#                      window; see search/aggs_device.py) run as
#                      segment-sum kernels on device, everything else on
#                      the host AggCollector; "force" — unsupported
#                      trees RAISE instead of silently host-routing (the
#                      bench/CI routing assertion mode; runtime faults
#                      still fall back to the host); "off" — every agg
#                      body uses the host collector (the pre-PR 8 path).

DEVICE_AGGS_ENV = "ES_TPU_DEVICE_AGGS"


def device_aggs_mode() -> str:
    """Device-aggregations routing mode: "auto" | "force" | "off"."""
    v = os.environ.get(DEVICE_AGGS_ENV, "auto").strip().lower()
    return v if v in ("auto", "force", "off") else "auto"


# ---- second-stage reranking knobs (search/rescorer.py) ----
#
# ES_TPU_RERANK:  "auto" (default) — `rescore` bodies on the jax backend
#                 run the late-interaction maxsim kernel on device over
#                 the fused top-k (ops/rerank.py); any rerank-path
#                 failure degrades to the FIRST-STAGE ranking (never a
#                 failed request), and an HBM budget breach skips the
#                 rerank column build (degrade-to-skip). "force" — a
#                 silently-skipped device rerank (missing column,
#                 budget degrade) RAISES instead (the bench/CI routing
#                 assertion mode; runtime faults still fall back to the
#                 first-stage order). "off" — rescore sections are
#                 accepted but not executed (the ?rescore=false escape
#                 hatch applied node-wide).

RERANK_ENV = "ES_TPU_RERANK"


def rerank_mode() -> str:
    """Second-stage rerank routing mode: "auto" | "force" | "off"."""
    v = os.environ.get(RERANK_ENV, "auto").strip().lower()
    return v if v in ("auto", "force", "off") else "auto"


# ---- streaming-ingest knobs (index/segment_build.py, cluster/indices.py) ----
#
# ES_TPU_DEVICE_BUILD:  "auto" (default) — segment builds on jax-backend
#                       indices materialize their column arrays through
#                       the jitted build kernels (ops/index_build.py);
#                       device-built columns are BIT-IDENTICAL to the
#                       host SegmentBuilder output, and any device-path
#                       failure (fault at `build.device`, HBM budget)
#                       degrades to the host build. "force" — every
#                       build (any backend) uses the device path and
#                       failures RAISE (the parity/CI assertion mode;
#                       HBM degrades still fall back). "off" — the
#                       host SegmentBuilder everywhere (pre-ingest-PR
#                       behavior).
# ES_TPU_BG_REFRESH:    "auto" (default) — every IndexService runs a
#                       background refresher thread driven by the
#                       dynamic `index.refresh_interval` setting
#                       (double-buffered: the next generation's columns
#                       build while the current one serves; the swap is
#                       one atomic generation bump). "off" — no
#                       background thread; refresh only on explicit
#                       calls (tier-1 pins this for determinism).

DEVICE_BUILD_ENV = "ES_TPU_DEVICE_BUILD"
BG_REFRESH_ENV = "ES_TPU_BG_REFRESH"


def device_build_mode() -> str:
    """Device segment-build routing mode: "auto" | "force" | "off"."""
    v = os.environ.get(DEVICE_BUILD_ENV, "auto").strip().lower()
    return v if v in ("auto", "force", "off") else "auto"


def bg_refresh_enabled() -> bool:
    """Whether IndexService starts the background refresher thread."""
    v = os.environ.get(BG_REFRESH_ENV, "auto").strip().lower()
    return v not in ("off", "0", "false")


# ---- admission-control knobs (search/admission.py) ----
#
# ES_TPU_ADMISSION:            "on" (default) | "off" — the per-node
#                              admission layer (weighted fair queueing,
#                              AIMD concurrency limit, deadline shed,
#                              brownout tiers, retry budget) in front
#                              of the batcher. Tests pin it off and
#                              arm it explicitly.
# ES_TPU_ADMISSION_TARGET_MS:  AIMD queue-delay target (default 75):
#                              the batcher enqueue→dispatch wait the
#                              limit steers toward.
# ES_TPU_ADMISSION_MAX_QUEUE:  admission queue bound (default 1024);
#                              overflow sheds with 429 + Retry-After.
#
# The same knobs are dynamically updatable as cluster settings
# (search.admission.*, registered below; ClusterService wires the
# update consumers to admission.configure()).


def peak_flops() -> float:
    """Accelerator peak FLOP/s for MFU accounting."""
    raw = os.environ.get(PEAK_FLOPS_ENV, "")
    try:
        v = float(raw) if raw else PEAK_FLOPS_DEFAULT
    except ValueError:
        v = PEAK_FLOPS_DEFAULT
    return v if v > 0 else PEAK_FLOPS_DEFAULT


class SettingsError(ValueError):
    pass


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).lower()
    if s in ("true", "1"):
        return True
    if s in ("false", "0"):
        return False
    raise SettingsError(f"cannot parse boolean [{v}]")


def _parse_time(v) -> str:
    """TimeValue strings kept as-is but validated (e.g. '1s', '500ms')."""
    s = str(v)
    if s in ("-1", "0"):
        # -1 = disabled; bare 0 = zero time (the slowlog "always fire"
        # threshold, matching the reference's TimeValue.ZERO)
        return s
    for suffix in ("nanos", "micros", "ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            try:
                float(s[: -len(suffix)])
                return s
            except ValueError:
                break
    raise SettingsError(f"failed to parse setting value [{v}] as a time value")


@dataclass
class Setting:
    key: str
    default: Any
    scope: str = CLUSTER_SCOPE
    dynamic: bool = True
    final: bool = False
    parser: Callable[[Any], Any] = str
    validator: Optional[Callable[[Any], None]] = None

    def parse(self, value: Any) -> Any:
        try:
            v = self.parser(value)
        except SettingsError:
            raise
        except (TypeError, ValueError) as e:
            raise SettingsError(
                f"failed to parse value [{value}] for setting [{self.key}]: {e}"
            )
        if self.validator is not None:
            self.validator(v)
        return v


def _positive(name):
    def check(v):
        if v < 1:
            raise SettingsError(f"[{name}] must be >= 1")

    return check


def _non_negative(name):
    def check(v):
        if v < 0:
            raise SettingsError(f"[{name}] must be >= 0")

    return check


def _positive_f(name):
    def check(v):
        if not (v > 0):
            raise SettingsError(f"[{name}] must be > 0")

    return check


def _one_of(name, allowed):
    def check(v):
        if v not in allowed:
            raise SettingsError(
                f"[{name}] must be one of {'|'.join(allowed)}, got [{v}]"
            )

    return check


# ---- index-scoped registry (IndexScopedSettings.BUILT_IN_INDEX_SETTINGS) ----

INDEX_SETTINGS: Dict[str, Setting] = {
    s.key: s
    for s in [
        Setting("number_of_shards", 1, INDEX_SCOPE, dynamic=False, final=True,
                parser=int, validator=_positive("number_of_shards")),
        Setting("number_of_replicas", 1, INDEX_SCOPE, parser=int,
                validator=_non_negative("number_of_replicas")),
        Setting("refresh_interval", "1s", INDEX_SCOPE, parser=_parse_time),
        # jax is the production default (round-2): the REST serving path
        # runs on the device kernels; "numpy" selects the CPU oracle
        Setting("search.backend", "jax", INDEX_SCOPE, dynamic=False),
        Setting("max_result_window", 10000, INDEX_SCOPE, parser=int,
                validator=_positive("max_result_window")),
        # write durability (index/translog.py): "request" fsyncs the WAL
        # before every ack; "async" bounds the acked-but-volatile window
        # to translog.sync_interval (the crash matrix in
        # tests/test_durability.py proves both contracts)
        Setting("translog.durability", "request", INDEX_SCOPE,
                validator=_one_of("translog.durability",
                                  ("request", "async"))),
        Setting("translog.sync_interval", "5s", INDEX_SCOPE,
                parser=_parse_time),
        Setting("merge.policy.max_segments", 8, INDEX_SCOPE, parser=int,
                validator=_positive("merge.policy.max_segments")),
        Setting("knn.quantization", "none", INDEX_SCOPE),
        # IVF ANN tier (ops/ivf.py, search/ann.py): "exact" keeps every
        # knn request on the brute-force oracle; "ivf" clusters each
        # segment's vectors at executor build and probes top-nprobe
        # clusters at query time (per-request `nprobe` override and the
        # ?exact=true escape hatch always available)
        Setting("knn.type", "exact", INDEX_SCOPE,
                validator=_one_of("knn.type", ("exact", "ivf"))),
        # cluster count per segment (0 = auto ~sqrt(N))
        Setting("knn.nlist", 0, INDEX_SCOPE, parser=int,
                validator=_non_negative("knn.nlist")),
        # default probe width (per-request knn.nprobe overrides)
        Setting("knn.nprobe", 8, INDEX_SCOPE, parser=int,
                validator=_positive("knn.nprobe")),
        # learned-sparse impact storage (ops/impact.py, search/sparse.py):
        # int8 — the default — serves from the 4x-smaller per-term
        # symmetric column; "none" keeps the fp32 plane (always present
        # as the exact oracle; a body-level `"exact": true` routes one
        # request to it regardless)
        Setting("sparse.quantization", "int8", INDEX_SCOPE,
                validator=_one_of("sparse.quantization",
                                  ("none", "int8"))),
        # second-stage reranker token storage (search/rescorer.py):
        # int8 mirrors the kNN quantization path — per-token symmetric
        # scales, 4x less HBM per maxsim gather
        Setting("rerank.quantization", "none", INDEX_SCOPE,
                validator=_one_of("rerank.quantization",
                                  ("none", "int8"))),
        # shard request cache default for size:0/agg-only requests
        # (IndicesRequestCache's index.requests.cache.enable); the
        # per-request ?request_cache= param overrides it either way
        Setting("requests.cache.enable", True, INDEX_SCOPE,
                parser=_parse_bool),
        # per-index fair-share weight for the admission layer's stride
        # scheduler: under contention an index drains admission-queue
        # slots proportionally to its weight (default equal shares)
        Setting("search.admission.weight", 1.0, INDEX_SCOPE, parser=float,
                validator=_positive_f("search.admission.weight")),
        Setting("hidden", False, INDEX_SCOPE, parser=_parse_bool),
        Setting("codec", "default", INDEX_SCOPE, dynamic=False),
        Setting("default_pipeline", None, INDEX_SCOPE),
        Setting("final_pipeline", None, INDEX_SCOPE),
        # per-index search slow logs (common/slowlog.py): dynamic
        # per-level thresholds for the query and fetch phases; "-1"
        # disables a level, "0" fires it on every request
        *[
            Setting(
                f"search.slowlog.threshold.{phase}.{lvl}", "-1",
                INDEX_SCOPE, parser=_parse_time,
            )
            for phase in ("query", "fetch")
            for lvl in ("warn", "info", "debug", "trace")
        ],
    ]
}

# ---- cluster-scoped registry ----

CLUSTER_SETTINGS: Dict[str, Setting] = {
    s.key: s
    for s in [
        # allocation/rebalance master switch (EnableAllocationDecider):
        # "all" (default) allows every copy to allocate/relocate,
        # "primaries" restricts to primary copies, "none" freezes both
        # replica allocation and rebalancing (explicit reroute `move`
        # commands are operator intent and bypass only this decider)
        Setting("cluster.routing.allocation.enable", "all",
                validator=_one_of("cluster.routing.allocation.enable",
                                  ("all", "primaries", "none"))),
        # comma-separated node names to drain (FilterAllocationDecider's
        # cluster.routing.allocation.exclude._name): no copy may
        # allocate or rebalance onto an excluded node, and the
        # background rebalancer actively moves copies off of it
        Setting("cluster.routing.allocation.exclude._name", ""),
        # concurrent relocations the rebalancer may keep in flight
        # (ConcurrentRebalanceAllocationDecider)
        Setting("cluster.routing.allocation.cluster_concurrent_rebalance",
                2, parser=int,
                validator=_positive(
                    "cluster.routing.allocation.cluster_concurrent_rebalance")),
        # HBM/disk watermark (DiskThresholdDecider analog reading the
        # per-node circuit-breaker ledger): a node whose tracked-bytes
        # utilisation exceeds this fraction of its breaker budget
        # refuses new shard copies
        Setting("cluster.routing.allocation.watermark.high", 0.9,
                parser=float,
                validator=_positive_f(
                    "cluster.routing.allocation.watermark.high")),
        Setting("action.auto_create_index", True, parser=_parse_bool),
        Setting("search.default_search_timeout", "-1", parser=_parse_time),
        # request default for allow_partial_search_results: false turns
        # ANY shard failure/timeout into a 503 search_phase_execution_
        # exception instead of a partial 200 (TransportSearchAction's
        # SEARCH_DEFAULT_ALLOW_PARTIAL_RESULTS analog)
        Setting("search.default_allow_partial_results", True,
                parser=_parse_bool),
        Setting("search.max_buckets", 65536, parser=int,
                validator=_positive("search.max_buckets")),
        # overload-protection layer (search/admission.py): dynamically
        # updatable; ClusterService wires update consumers through to
        # admission.configure()
        Setting("search.admission.enabled", True, parser=_parse_bool),
        Setting("search.admission.target_delay_ms", 75, parser=int,
                validator=_positive("search.admission.target_delay_ms")),
        Setting("search.admission.max_queue", 1024, parser=int,
                validator=_positive("search.admission.max_queue")),
        Setting("search.admission.retry_budget.ratio", 0.1, parser=float,
                validator=_non_negative(
                    "search.admission.retry_budget.ratio")),
        Setting("indices.recovery.max_bytes_per_sec", "40mb"),
    ]
}


def validate_index_settings(flat: Dict[str, Any], creating: bool) -> Dict[str, Any]:
    """Validates + parses a flat settings dict against the index registry.

    Unknown settings are rejected (like IndexScopedSettings.validate);
    on update (creating=False) final/static settings are rejected too.
    """
    out: Dict[str, Any] = {}
    for key, value in flat.items():
        setting = INDEX_SETTINGS.get(key)
        if setting is None:
            raise SettingsError(
                f"unknown setting [index.{key}] please check that any required "
                "plugins are installed, or check the breaking changes "
                "documentation for removed settings"
            )
        if not creating and (setting.final or not setting.dynamic):
            raise SettingsError(
                f"final {INDEX_SCOPE} setting [index.{key}], not updateable"
            )
        out[key] = setting.parse(value)
    return out


class ClusterSettingsStore:
    """Mutable cluster-wide settings: persistent + transient layers with
    update-consumer dispatch (ClusterSettings.applySettings)."""

    def __init__(self):
        self.persistent: Dict[str, Any] = {}
        self.transient: Dict[str, Any] = {}
        self._consumers: Dict[str, List[Callable[[Any], None]]] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> Any:
        if key in self.transient:
            return self.transient[key]
        if key in self.persistent:
            return self.persistent[key]
        s = CLUSTER_SETTINGS.get(key)
        return s.default if s else None

    def add_consumer(self, key: str, fn: Callable[[Any], None]) -> None:
        self._consumers.setdefault(key, []).append(fn)

    def update(self, body: dict) -> dict:
        with self._lock:
            changed: Dict[str, Any] = {}
            for layer_name in ("persistent", "transient"):
                layer_body = body.get(layer_name) or {}
                layer = getattr(self, layer_name)
                for key, value in _flatten(layer_body).items():
                    setting = CLUSTER_SETTINGS.get(key)
                    if setting is None:
                        raise SettingsError(
                            f"transient setting [{key}], not recognized"
                            if layer_name == "transient"
                            else f"persistent setting [{key}], not recognized"
                        )
                    if value is None:
                        layer.pop(key, None)
                        changed[key] = self.get(key)
                    else:
                        parsed = setting.parse(value)
                        layer[key] = parsed
                        changed[key] = parsed
            for key, value in changed.items():
                for fn in self._consumers.get(key, []):
                    fn(value)
            return {
                "acknowledged": True,
                "persistent": _unflatten(self.persistent),
                "transient": _unflatten(self.transient),
            }

    def to_json(self) -> dict:
        return {
            "persistent": _unflatten(self.persistent),
            "transient": _unflatten(self.transient),
        }

    def load_layers(self, persistent: dict, transient: dict) -> None:
        """Replaces both layers wholesale (cluster-state application on a
        follower: the master published the authoritative settings).  Fires
        consumers only for keys whose effective value actually changed."""
        with self._lock:
            keys = (set(self.persistent) | set(self.transient)
                    | set(persistent) | set(transient))
            before = {k: self.get(k) for k in keys}
            self.persistent = dict(persistent)
            self.transient = dict(transient)
            fired = []
            for k in keys:
                after = self.get(k)
                if after != before[k]:
                    fired.append((k, after))
            for key, value in fired:
                for fn in self._consumers.get(key, []):
                    fn(value)


def _flatten(node: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(node, dict):
        for k, v in node.items():
            key = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                out.update(_flatten(v, key))
            else:
                out[key] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> dict:
    out: dict = {}
    for key, v in flat.items():
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out
