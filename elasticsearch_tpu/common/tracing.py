"""Per-request span-tree tracing (lightweight, always-cheap).

Reference analog: the `X-Opaque-Id` header + task-manager description
propagation in org.elasticsearch.tasks, and the APM-style span trees
the reference ships via apm-agent — here a minimal in-process recorder
so a single slow request can be decomposed (queue wait vs. kernel vs.
merge vs. fetch) without any external collector.

Design:
  * `Trace` holds a bounded list of `Span`s (monotonic nanosecond
    clocks, parent/child ids, free-form tags like index/shard/bucket).
  * `TRACE_CTX` is a contextvar: the REST layer arms it per request
    (`begin()` / `end()`), and every seam that wants a span just reads
    the var — `None` means tracing is off and costs one dict lookup.
    Fan-out pools propagate the var with `contextvars.copy_context()`;
    the Trace object itself is shared and thread-safe, so spans added
    from shard/leg worker threads land in the request's tree.
  * Completed traces go into a bounded ring (`ES_TPU_TRACE_RING`,
    default 256) queryable via `GET /_internal/traces` — a test/smoke
    surface, not a production exporter.
  * `ES_TPU_TRACING=off` disables arming entirely (`begin()` → None).

`OPAQUE_ID_CTX` carries the request's `X-Opaque-Id` header value so
task descriptions, slow-log records, and traces can all attribute work
to the caller's id without threading a parameter through every layer.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# the CURRENT request's X-Opaque-Id header (None outside a request or
# when the client sent none)
OPAQUE_ID_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "opaque_id", default=None
)

# the CURRENT request's Trace (None = tracing off / not a traced path)
TRACE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "trace_ctx", default=None
)

# parent span id for nested `Trace.span()` scopes (copy-on-thread via
# contextvars, so concurrent legs each see their own parent chain)
_PARENT_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "span_parent", default=None
)

# hard cap per trace: a runaway fan-out must not grow one trace without
# bound (drops are counted, not silent)
MAX_SPANS = 512

_trace_ids = itertools.count(1)


def enabled() -> bool:
    return os.environ.get("ES_TPU_TRACING", "on").lower() not in (
        "off", "0", "false",
    )


def _ring_cap() -> int:
    try:
        return max(1, int(os.environ.get("ES_TPU_TRACE_RING", "256")))
    except ValueError:
        return 256


class Span:
    __slots__ = ("id", "parent_id", "name", "start_ns", "end_ns", "tags")

    def __init__(
        self, id: int, parent_id: Optional[int], name: str,
        start_ns: int, end_ns: int, tags: Dict[str, Any],
    ):
        self.id = id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tags = tags

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ns": self.start_ns,
            "duration_ns": max(0, self.end_ns - self.start_ns),
            "tags": self.tags,
        }


class Trace:
    """One request's span tree. Thread-safe: fan-out worker threads
    append concurrently (the object rides a copied context into the
    pools). Clocks are `time.perf_counter_ns()` — monotonic, so spans
    recorded on different threads order correctly within one host."""

    def __init__(self, name: str, opaque_id: Optional[str] = None,
                 **tags: Any):
        self.trace_id = f"trace-{next(_trace_ids)}"
        self.name = name
        self.opaque_id = opaque_id
        self.tags = dict(tags)
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.wall_start = time.time()
        self._spans: List[Span] = []
        self._dropped = 0
        self._span_ids = itertools.count(1)
        self._lock = threading.Lock()

    # ---- recording ----

    def add_span(
        self, name: str, start_ns: int, end_ns: int,
        parent_id: Optional[int] = None, **tags: Any,
    ) -> Optional[int]:
        """Retroactive span from two already-taken perf_counter_ns
        marks (the cheap pattern for code that timed itself anyway).
        Returns the span id, or None if the trace is full."""
        if parent_id is None:
            parent_id = _PARENT_CTX.get()
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                self._dropped += 1
                return None
            sid = next(self._span_ids)
            self._spans.append(
                Span(sid, parent_id, name, int(start_ns), int(end_ns), tags)
            )
        return sid

    def span(self, name: str, **tags: Any):
        """Context-manager scope: times the block and parents any span
        recorded inside it (contextvar chain, thread-local per leg)."""
        return _SpanScope(self, name, tags)

    def finish(self) -> None:
        """Closes the trace and publishes it to the ring."""
        if self.end_ns is not None:
            return
        self.end_ns = time.perf_counter_ns()
        _ring_append(self)

    # ---- export ----

    def to_dict(self) -> dict:
        with self._lock:
            spans = [s.to_dict() for s in self._spans]
            dropped = self._dropped
        end = self.end_ns or time.perf_counter_ns()
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "opaque_id": self.opaque_id,
            "tags": self.tags,
            "started_at": self.wall_start,
            "duration_ns": max(0, end - self.start_ns),
            "span_count": len(spans),
            "dropped_spans": dropped,
            "spans": spans,
        }


class _SpanScope:
    __slots__ = ("trace", "name", "tags", "t0", "_tok")

    def __init__(self, trace: Trace, name: str, tags: Dict[str, Any]):
        self.trace = trace
        self.name = name
        self.tags = tags
        self.t0 = 0
        self._tok = None

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        # reserve the id up front so children can parent onto it; the
        # end time is patched at exit
        with self.trace._lock:
            sid = next(self.trace._span_ids)
        self._tok = _PARENT_CTX.set(sid)
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter_ns()
        sid = _PARENT_CTX.get()
        parent = None
        if self._tok is not None:
            parent = self._tok.old_value
            if parent is contextvars.Token.MISSING:
                parent = None
            _PARENT_CTX.reset(self._tok)
        with self.trace._lock:
            if len(self.trace._spans) >= MAX_SPANS:
                self.trace._dropped += 1
            else:
                self.trace._spans.append(
                    Span(sid, parent, self.name, self.t0, end, self.tags)
                )
        return False


# ---- completed-trace ring (GET /_internal/traces) ----

_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=_ring_cap())


def _ring_append(trace: Trace) -> None:
    with _ring_lock:
        _ring.append(trace)


def recent(n: int = 50) -> List[dict]:
    """Newest-first dicts of the last `n` completed traces."""
    with _ring_lock:
        traces = list(_ring)[-max(0, int(n)):]
    return [t.to_dict() for t in reversed(traces)]


def clear() -> None:
    with _ring_lock:
        _ring.clear()


# ---- REST-layer arming helpers ----

def begin(name: str, **tags: Any):
    """Arms TRACE_CTX for the current context. Returns an opaque handle
    for `end()`, or None when tracing is disabled."""
    if not enabled():
        return None
    tr = Trace(name, opaque_id=OPAQUE_ID_CTX.get(), **tags)
    tok = TRACE_CTX.set(tr)
    return (tr, tok)


def end(handle) -> None:
    """Finishes the trace begun by `begin()` (no-op on None)."""
    if handle is None:
        return
    tr, tok = handle
    try:
        TRACE_CTX.reset(tok)
    except ValueError:  # pragma: no cover - cross-context reset
        TRACE_CTX.set(None)
    tr.finish()


def current() -> Optional[Trace]:
    return TRACE_CTX.get()
