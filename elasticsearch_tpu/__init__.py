"""elasticsearch_tpu — a TPU-native search framework.

A from-scratch, TPU-first re-design of the capabilities of
zhaoweiwang/elasticsearch (an Elasticsearch fork): full-text BM25 search,
dense-vector kNN, hybrid RRF ranking, an Elasticsearch-shaped REST API,
sharded distribution over a `jax.sharding.Mesh`, durable segments + WAL.

Architecture (maps to SURVEY.md layer map):
  rest/       L1  — HTTP API, ES-shaped JSON (ref: server/.../rest/)
  search/     L2/L6 — query DSL, compiler, coordinator + shard execution
                     (ref: org.elasticsearch.search, action.search)
  index/      L5  — mappings, document parsing, tiled columnar segments,
                     translog WAL, engine (ref: org.elasticsearch.index)
  analysis/       — Lucene-parity analyzers (ref: index.analysis)
  models/         — scoring models: BM25, BM25F, kNN similarity, RRF
                     (ref: Lucene BM25Similarity, VectorSimilarityFunction)
  ops/            — device kernels: dense scatter-add scoring, top-k,
                     matmul kNN, Pallas kernels (ref: Lucene scoring loop)
  parallel/       — mesh, shard_map sharded search, ICI top-k merge
                     (ref: sharding + transport scatter/gather)
  cluster/    L3  — cluster state, settings registry, routing
  utils/          — murmur3 (ES routing parity), SmallFloat norms, io

The on-device data model is dense tiled arrays, not objects: postings are
(doc_id, tf, norm_byte) int32 tiles of width 128, scored term-at-a-time
into a dense per-doc accumulator, then `lax.top_k` (which tie-breaks by
low index = low doc id, matching Lucene's score desc / doc asc order).
"""

__version__ = "0.1.0"
