"""Inter-node RPC: the TransportService analog over asyncio TCP.

Reference analog: org.elasticsearch.transport.TransportService +
TcpTransport + modules/transport-netty4 (SURVEY.md §2.7, L4): named
request handlers (`registerRequestHandler`), request-id correlated
async responses, per-request timeouts, and a version handshake on
connect (`TransportHandshaker`). The binary `Writeable` codec is
replaced by length-prefixed JSON frames — control-plane payloads here
are small metadata/doc blobs riding DCN, while bulk scoring data stays
on-device (ICI collectives in parallel/sharded.py); SURVEY §2.7
prescribes exactly this two-plane split.

Wire format: 4-byte big-endian length + UTF-8 JSON frame.
  request:  {"t": "q", "id": n, "a": action, "p": payload}
  response: {"t": "r", "id": n, "p": payload}
  error:    {"t": "e", "id": n, "error": reason, "etype": class}
Handshake (first frame each direction on connect):
  {"t": "h", "node": node_id, "version": TRANSPORT_VERSION, "cluster": name}

The event loop runs on a dedicated daemon thread; handlers execute on a
thread pool so blocking engine work never stalls the loop (the analog of
ES dispatching transport messages onto named threadpools).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import struct
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

# v2: frames carry a leading flag byte (raw | DEFLATE)
TRANSPORT_VERSION = 2
_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


class TransportError(Exception):
    def __init__(self, reason: str, etype: str = "transport_exception"):
        super().__init__(reason)
        self.etype = etype


class ConnectTransportError(TransportError):
    def __init__(self, reason: str):
        super().__init__(reason, "connect_transport_exception")


class ReceiveTimeoutTransportError(TransportError):
    def __init__(self, reason: str):
        super().__init__(reason, "receive_timeout_transport_exception")


class RemoteTransportError(TransportError):
    """An exception raised by the remote handler, re-raised locally.
    Carries the remote exception's REST status/err_type when the remote
    raised a ClusterError-shaped exception, so coordinators can re-raise
    with the right HTTP status (ES serializes ElasticsearchException
    status over the wire the same way)."""

    def __init__(
        self,
        reason: str,
        etype: str,
        status: Optional[int] = None,
        err_type: Optional[str] = None,
    ):
        super().__init__(reason, etype)
        self.status = status
        self.err_type = err_type


# frames at or above this size are DEFLATE-compressed on the wire
# (TransportSettings.TRANSPORT_COMPRESS / Lucene's transport LZ4 —
# recovery file chunks and bulk doc batches shrink several-fold)
COMPRESS_MIN = 8 * 1024
_FLAG_RAW = 0
_FLAG_DEFLATE = 1


async def _read_frame(reader: asyncio.StreamReader) -> dict:
    head = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise TransportError(f"frame of {n} bytes exceeds limit")
    if n < 1:
        raise TransportError("empty frame")
    body = await reader.readexactly(n)
    flag, payload = body[0], body[1:]
    if flag == _FLAG_DEFLATE:
        import zlib

        # bounded inflate: the MAX_FRAME limit must hold for the
        # DECOMPRESSED size too (decompression-bomb guard)
        try:
            d = zlib.decompressobj()
            payload = d.decompress(payload, MAX_FRAME)
        except zlib.error as e:
            raise TransportError(f"corrupt compressed frame: {e}")
        if d.unconsumed_tail:
            raise TransportError(
                f"inflated frame exceeds the {MAX_FRAME} byte limit"
            )
    elif flag != _FLAG_RAW:
        raise TransportError(f"unknown frame flag [{flag}]")
    return json.loads(payload)


def _frame(msg: dict) -> bytes:
    body = json.dumps(msg, separators=(",", ":")).encode()
    flag = _FLAG_RAW
    if len(body) >= COMPRESS_MIN:
        import zlib

        comp = zlib.compress(body, 6)
        if len(comp) < len(body):
            body = comp
            flag = _FLAG_DEFLATE
    return _LEN.pack(len(body) + 1) + bytes([flag]) + body


class _Connection:
    """One outbound connection with request-id correlation."""

    def __init__(self, reader, writer, remote_node: str):
        self.reader = reader
        self.writer = writer
        self.remote_node = remote_node
        self.pending: Dict[int, asyncio.Future] = {}
        self.closed = False

    async def pump(self):
        """Reads responses and resolves pending futures."""
        try:
            while True:
                msg = await _read_frame(self.reader)
                fut = self.pending.pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.closed = True
            for fut in self.pending.values():
                if not fut.done():
                    fut.set_exception(
                        ConnectTransportError("connection closed")
                    )
            self.pending.clear()
            try:
                self.writer.close()
            except Exception:
                pass


class TransportService:
    """Named-action RPC endpoint bound to one node."""

    def __init__(
        self,
        node_id: str,
        cluster_name: str = "elasticsearch-tpu",
        host: str = "127.0.0.1",
        port: int = 0,
        handler_threads: int = 8,
    ):
        self.node_id = node_id
        self.cluster_name = cluster_name
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self._handlers: Dict[str, Callable[[dict], Any]] = {}
        self._conns: Dict[Tuple[str, int], _Connection] = {}
        self._req_ids = itertools.count(1)
        self._pool = ThreadPoolExecutor(
            max_workers=handler_threads, thread_name_prefix=f"transport-{node_id}"
        )
        self._loop = asyncio.new_event_loop()
        # dispatch/pump tasks tracked so close() can cancel them — an
        # un-cancelled pending task at loop close leaks ("Task was
        # destroyed but it is pending!")
        self._tasks: set = set()
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run_loop, name=f"transport-loop-{node_id}", daemon=True
        )
        self.stats = {"rx_count": 0, "tx_count": 0}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "TransportService":
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise TransportError("transport failed to start")
        return self

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start_server())
        self._started.set()
        self._loop.run_forever()
        # drain pending callbacks after stop
        self._loop.run_until_complete(self._loop.shutdown_asyncgens())
        self._loop.close()

    async def _start_server(self):
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self._requested_port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def close(self):
        def _shutdown():
            if self._server is not None:
                self._server.close()
            # cancel in-flight dispatch/pump tasks first; their
            # cancellation wakeups are queued ahead of the stop below,
            # so every task completes (cancelled) before the loop halts
            for t in list(self._tasks):
                t.cancel()
            for c in self._conns.values():
                try:
                    c.writer.close()
                except Exception:
                    pass
            self._loop.call_soon(self._loop.stop)

        if self._loop.is_running():
            self._loop.call_soon_threadsafe(_shutdown)
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)

    def _track(self, coro) -> "asyncio.Task":
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    # ------------------------------------------------------------------
    # server side
    # ------------------------------------------------------------------

    def register_handler(self, action: str, fn: Callable[[dict], Any]):
        """`TransportService.registerRequestHandler` — fn(payload) → payload
        runs on the handler pool; raising maps to an error frame."""
        self._handlers[action] = fn

    async def _serve_conn(self, reader, writer):
        # inbound handler tasks are spawned by asyncio.start_server, not
        # by _track — self-register so close() can cancel them too
        task = asyncio.current_task()
        if task is not None:
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        try:
            hello = await _read_frame(reader)
            if hello.get("t") != "h" or hello.get("version") != TRANSPORT_VERSION:
                writer.write(
                    _frame(
                        {
                            "t": "e",
                            "id": 0,
                            "error": "handshake failed: incompatible version",
                            "etype": "illegal_state_exception",
                        }
                    )
                )
                await writer.drain()
                writer.close()
                return
            writer.write(
                _frame(
                    {
                        "t": "h",
                        "node": self.node_id,
                        "version": TRANSPORT_VERSION,
                        "cluster": self.cluster_name,
                    }
                )
            )
            await writer.drain()
            while True:
                msg = await _read_frame(reader)
                if msg.get("t") != "q":
                    continue
                self.stats["rx_count"] += 1
                self._track(self._dispatch(msg, writer))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg: dict, writer):
        rid = msg.get("id")
        action = msg.get("a")
        fn = self._handlers.get(action)
        if fn is None:
            out = {
                "t": "e",
                "id": rid,
                "error": f"no handler for action [{action}]",
                "etype": "action_not_found_transport_exception",
            }
        else:
            try:
                result = await self._loop.run_in_executor(
                    self._pool, fn, msg.get("p")
                )
                out = {"t": "r", "id": rid, "p": result}
            except Exception as e:
                out = {
                    "t": "e",
                    "id": rid,
                    "error": str(e),
                    "etype": type(e).__name__,
                }
                status = getattr(e, "status", None)
                err_type = getattr(e, "err_type", None)
                if isinstance(status, int):
                    out["status"] = status
                if isinstance(err_type, str):
                    out["err_type"] = err_type
        try:
            writer.write(_frame(out))
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------

    async def _get_conn(self, address: Tuple[str, int]) -> _Connection:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        try:
            reader, writer = await asyncio.open_connection(*address)
        except (ConnectionError, OSError) as e:
            raise ConnectTransportError(f"connect to {address} failed: {e}")
        writer.write(
            _frame(
                {
                    "t": "h",
                    "node": self.node_id,
                    "version": TRANSPORT_VERSION,
                    "cluster": self.cluster_name,
                }
            )
        )
        await writer.drain()
        hello = await _read_frame(reader)
        if hello.get("t") == "e":
            writer.close()
            raise ConnectTransportError(hello.get("error", "handshake rejected"))
        if hello.get("t") != "h" or hello.get("version") != TRANSPORT_VERSION:
            writer.close()
            raise ConnectTransportError("handshake failed: incompatible version")
        if hello.get("cluster") != self.cluster_name:
            writer.close()
            raise ConnectTransportError(
                f"remote cluster name [{hello.get('cluster')}] "
                f"does not match [{self.cluster_name}]"
            )
        conn = _Connection(reader, writer, hello.get("node"))
        self._conns[address] = conn
        self._track(conn.pump())
        return conn

    async def _send_async(
        self, address: Tuple[str, int], action: str, payload, timeout: float
    ):
        conn = await self._get_conn(address)
        rid = next(self._req_ids)
        fut = self._loop.create_future()
        conn.pending[rid] = fut
        conn.writer.write(_frame({"t": "q", "id": rid, "a": action, "p": payload}))
        await conn.writer.drain()
        self.stats["tx_count"] += 1
        try:
            msg = await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            conn.pending.pop(rid, None)
            raise ReceiveTimeoutTransportError(
                f"[{action}] request to {address} timed out after {timeout}s"
            )
        if msg.get("t") == "e":
            raise RemoteTransportError(
                msg.get("error", "remote error"),
                msg.get("etype", "exception"),
                status=msg.get("status"),
                err_type=msg.get("err_type"),
            )
        return msg.get("p")

    def send(
        self,
        address: Tuple[str, int],
        action: str,
        payload=None,
        timeout: float = 30.0,
    ):
        """Synchronous request/response (`TransportService.sendRequest` +
        blocking future). Safe to call from any non-loop thread."""
        from ..common.faults import InjectedFault, faults

        # fault-injection site: drops/delays/errors on the outbound hop
        # (MockTransportService-style disruption, armed via ES_TPU_FAULTS
        # or POST /_internal/faults; a no-op when unarmed)
        try:
            faults.check(
                "transport.send",
                action=action,
                address=f"{address[0]}:{address[1]}",
            )
        except InjectedFault as e:
            if e.err_type == "connect_transport_exception":
                # an injected drop looks exactly like a broken connection
                raise ConnectTransportError(str(e))
            raise
        fut = asyncio.run_coroutine_threadsafe(
            self._send_async(tuple(address), action, payload, timeout), self._loop
        )
        return fut.result(timeout=timeout + 5)

    def ping(self, address: Tuple[str, int], timeout: float = 5.0) -> Optional[str]:
        """Handshake-probe a peer; returns its node id or None.
        (`HandshakingTransportAddressConnector` analog for discovery.)"""
        try:
            return self.send(address, "internal:ping", {}, timeout=timeout)["node"]
        except TransportError:
            return None
