"""Inter-node transport (TransportService analog over asyncio TCP)."""

from .service import (
    ConnectTransportError,
    ReceiveTimeoutTransportError,
    RemoteTransportError,
    TransportError,
    TransportService,
)

__all__ = [
    "TransportService",
    "TransportError",
    "ConnectTransportError",
    "ReceiveTimeoutTransportError",
    "RemoteTransportError",
]
