from .mapping import Mappings, DocumentParser, MappingParseError
from .segment import Segment, SegmentBuilder, TILE

__all__ = ["Mappings", "DocumentParser", "MappingParseError", "Segment", "SegmentBuilder", "TILE"]
