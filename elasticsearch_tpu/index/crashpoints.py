"""Crash-matrix harness for the write path.

The read path got its deterministic fault harness in round 4
(common/faults.py); this module is the durability mirror. It drives a
SCRIPTED write workload (bulk index / update / delete / CAS + refresh +
flush + merge) against a ShardEngine while a ``crash``-kind fault rule
is armed at one write-path site, catches the resulting
:class:`~..common.faults.SimulatedCrash`, tears the engine down WITHOUT
running any close/flush path (``ShardEngine.crash()``), reopens the
shard directory through the real recovery path, and verifies the
durability contract:

* ``request`` durability: EVERY op acked before the crash is present in
  the recovered state (right version, right seq_no, right source).
* ``async`` durability: loss is bounded by the last completed fsync —
  every acked op with seq_no <= the translog's synced high-water mark
  at crash time must survive; newer acked ops MAY be lost but nothing
  may be reordered, duplicated, or invented.
* Recovery always terminates with a consistent engine: no torn
  segment/manifest state, a searchable reader, and (checked by the
  caller) float-exact jax-vs-numpy search parity on the recovered data.

tests/test_durability.py runs the full site x durability matrix through
these helpers; scripts/durability_smoke.sh runs a seeded probabilistic
schedule over the same workload as the pre-push gate.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..analysis import AnalysisRegistry
from ..common.faults import SimulatedCrash, faults
from .engine import ShardEngine, VersionConflictError
from .mapping import Mappings
from .translog import DURABILITY_REQUEST

# the engine-level crash matrix: every write-path site the workload can
# reach, with the rule spec that pins the crash there. `torn` rides the
# translog.append site to leave a partial record on disk; `skip` moves
# the crash onset mid-workload (past the first flush, so the async
# durability bound is non-trivial in those cells — early-onset cells
# keep the before-any-commit shape covered too).
ENGINE_CRASH_SITES: List[Tuple[str, dict]] = [
    ("translog.append[first]", {"site": "translog.append"}),
    ("translog.append[mid]", {"site": "translog.append", "skip": 14}),
    ("translog.append[torn]",
     {"site": "translog.append", "torn": True, "skip": 20}),
    ("translog.fsync[first]", {"site": "translog.fsync"}),
    ("translog.fsync[late]", {"site": "translog.fsync", "skip": 2}),
    ("engine.refresh[first]", {"site": "engine.refresh"}),
    ("engine.refresh[late]", {"site": "engine.refresh", "skip": 2}),
    ("engine.flush[start]",
     {"site": "engine.flush", "match": {"stage": "start"}, "skip": 1}),
    ("engine.flush[pre_manifest]",
     {"site": "engine.flush", "match": {"stage": "pre_manifest"},
      "skip": 1}),
    ("engine.flush[post_manifest]",
     {"site": "engine.flush", "match": {"stage": "post_manifest"},
      "skip": 1}),
    ("engine.merge", {"site": "engine.merge", "skip": 1}),
]

WORKLOAD_MAPPING = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "n": {"type": "integer"},
    }
}

_WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
          "golf", "hotel", "india", "juliet"]


def _source(i: int, rev: int = 0) -> dict:
    return {
        "body": f"{_WORDS[i % len(_WORDS)]} shared "
                f"{_WORDS[(i * 3 + rev) % len(_WORDS)]} tok{i} rev{rev}",
        "tag": _WORDS[(i + rev) % 4],
        "n": i * 10 + rev,
    }


@dataclass
class _AckedOp:
    seq_no: int
    version: int
    deleted: bool
    source: Optional[dict]
    # translog durable high-water AT ACK TIME: under async durability an
    # op is only guaranteed once a LATER fsync covers its seq_no
    synced_seq_at_ack: int


@dataclass
class AckLedger:
    """What the client was told succeeded, in ack order."""

    ops: List[Tuple[str, _AckedOp]] = field(default_factory=list)

    def record(self, eng: ShardEngine, doc_id: str, result) -> None:
        tl = eng.translog
        self.ops.append((
            doc_id,
            _AckedOp(
                seq_no=result.seq_no,
                version=result.version,
                deleted=(result.result == "deleted"),
                source=None,
                synced_seq_at_ack=(
                    -1 if tl is None else tl.last_synced_seq_no
                ),
            ),
        ))

    def record_index(self, eng, doc_id, source, result):
        self.record(eng, doc_id, result)
        self.ops[-1][1].source = source

    @property
    def max_acked_seq(self) -> int:
        return max((op.seq_no for _, op in self.ops), default=-1)

    def acked_states(self, doc_id: str) -> List[_AckedOp]:
        return [op for d, op in self.ops if d == doc_id]

    def expected_after(self, durable_bound: int) -> Dict[str, _AckedOp]:
        """Per doc: the newest acked state with seq_no <= durable_bound
        — the FLOOR recovery must reach (newer acked states are also
        acceptable; older ones are lost acks)."""
        out: Dict[str, _AckedOp] = {}
        for doc_id, op in self.ops:
            if op.seq_no <= durable_bound:
                out[doc_id] = op
        return out


def run_workload(eng: ShardEngine, ledger: AckLedger,
                 n_docs: int = 24) -> None:
    """Deterministic scripted workload touching every write-path verb.
    Every ack is recorded BEFORE the next step so a crash mid-script
    leaves the ledger exactly at the acked prefix."""

    def idx(i: int, rev: int = 0, **kw):
        src = _source(i, rev)
        r = eng.index(f"d{i}", src, **kw)
        ledger.record_index(eng, f"d{i}", src, r)
        return r

    def delete(i: int, **kw):
        r = eng.delete(f"d{i}", **kw)
        if r.result == "deleted":
            ledger.record(eng, f"d{i}", r)
        return r

    half = n_docs // 2
    for i in range(half):
        idx(i)
    eng.refresh()
    # updates over the refreshed segment (live-bit flips + new buffer)
    for i in range(0, 4):
        idx(i, rev=1)
    delete(4)
    delete(5)
    eng.flush()
    # second epoch: ops living only in the WAL tail
    for i in range(half, half + 6):
        idx(i)
    # CAS update through the optimistic-concurrency path
    cur = eng.get("d1")
    try:
        r = eng.index("d1", _source(1, 2), if_seq_no=cur["_seq_no"],
                      if_primary_term=cur["_primary_term"])
        ledger.record_index(eng, "d1", _source(1, 2), r)
    except VersionConflictError:
        pass
    eng.refresh()
    delete(6)
    for i in range(half + 6, n_docs):
        idx(i)
    eng.refresh()
    eng.maybe_merge(max_segments=1)
    eng.flush()
    # third epoch: a fresh unflushed tail so post-flush sites still have
    # work in front of them
    for i in range(n_docs, n_docs + 4):
        idx(i)
    idx(0, rev=3)
    delete(7)
    eng.refresh()
    eng.maybe_merge(max_segments=1)
    eng.flush()


def verify_recovery(eng: ShardEngine, ledger: AckLedger, durability: str,
                    synced_seq_at_crash: int) -> dict:
    """Asserts the durability contract on a freshly-reopened engine."""
    durable_bound = (
        ledger.max_acked_seq
        if durability == DURABILITY_REQUEST
        else synced_seq_at_crash
    )
    floor = ledger.expected_after(durable_bound)
    lost_acks = 0
    for doc_id in {d for d, _ in ledger.ops}:
        states = ledger.acked_states(doc_id)
        acked_by_seq = {op.seq_no: op for op in states}
        newest = states[-1]
        doc = eng.get(doc_id)
        want = floor.get(doc_id)
        if doc is None:
            # absent is only legal if the floor state is a delete (or
            # the doc has no durable-bound state at all)
            assert want is None or want.deleted, (
                f"[{doc_id}] lost: acked (v{want.version}, seq "
                f"{want.seq_no}) is within the durable bound "
                f"{durable_bound} under [{durability}] durability"
            )
            if not newest.deleted:
                lost_acks += 1  # volatile acked write lost: allowed,
                # counted (the async bound already passed above)
            continue
        got_seq = doc["_seq_no"]
        # never an invented state: what recovery shows must be SOME
        # acked non-deleted state of this doc
        assert got_seq in acked_by_seq and not acked_by_seq[got_seq].deleted, (
            f"[{doc_id}] recovered to seq {got_seq}, which was never "
            f"acked as a live state"
        )
        got = acked_by_seq[got_seq]
        assert doc["_version"] == got.version, (
            f"[{doc_id}] seq {got_seq} acked as v{got.version} but "
            f"recovered as v{doc['_version']}"
        )
        assert doc["_source"] == got.source, (
            f"[{doc_id}] recovered source diverges from the acked "
            f"source at seq {got_seq}"
        )
        if want is not None:
            # never older than the durable floor
            assert got_seq >= want.seq_no, (
                f"[{doc_id}] recovered seq {got_seq} is OLDER than the "
                f"durable floor seq {want.seq_no} under [{durability}]"
            )
        if got_seq < newest.seq_no:
            lost_acks += 1
    return {
        "durable_bound": durable_bound,
        "max_acked_seq": ledger.max_acked_seq,
        "lost_acks_beyond_bound": lost_acks,
        "recovered_docs": eng.num_docs,
    }


def engine_state_checksum(eng: ShardEngine) -> str:
    """Checksum of the full logical replica state: live doc set +
    versions + seq_nos + sources. Two converged copies must be
    checksum-identical regardless of segment layout."""
    items = []
    with eng._lock:
        ids = sorted(
            d for d, ve in eng._versions.items() if not ve.deleted
        )
    for doc_id in ids:
        doc = eng.get(doc_id)
        if doc is None:
            continue
        items.append([
            doc_id, doc["_version"], doc["_seq_no"],
            json.dumps(doc["_source"], sort_keys=True),
        ])
    return hashlib.sha256(
        json.dumps(items, sort_keys=True).encode()
    ).hexdigest()


def run_engine_crash_case(
    path: str,
    rule: dict,
    durability: str,
    sync_interval: float = 5.0,
    seed: int = 0,
    times: int = 1,
) -> Tuple[ShardEngine, AckLedger, dict]:
    """One cell of the crash matrix: workload → injected crash →
    teardown-without-close → reopen → contract verification. Returns
    (recovered engine, ledger, report); the recovered engine is OPEN —
    the caller closes it (and can run search parity on it first)."""
    mappings = Mappings(WORKLOAD_MAPPING)
    eng = ShardEngine(
        mappings, AnalysisRegistry(), path=path,
        durability=durability, sync_interval=sync_interval,
    )
    ledger = AckLedger()
    faults.configure(
        {"seed": seed, "rules": [{**rule, "kind": "crash", "times": times}]}
    )
    crashed = False
    try:
        run_workload(eng, ledger)
    except SimulatedCrash:
        crashed = True
    finally:
        faults.clear()
    synced = (
        eng.translog.last_synced_seq_no if eng.translog is not None else -1
    )
    eng.crash()
    recovered = ShardEngine(
        mappings, AnalysisRegistry(), path=path,
        durability=durability, sync_interval=sync_interval,
    )
    report = verify_recovery(recovered, ledger, durability, synced)
    report["crashed"] = crashed
    # no torn commit state: the manifest (if any) must reference only
    # fully-loadable segments — ShardEngine.__init__ would have raised —
    # and the shard dir must hold no unreferenced garbage
    if os.path.exists(os.path.join(path, "manifest.json")):
        with open(os.path.join(path, "manifest.json"),
                  encoding="utf-8") as f:
            manifest = json.load(f)
        referenced = {
            e if isinstance(e, str) else e["name"]
            for e in manifest["segments"]
        }
        on_disk = {
            d for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d)) and d != "translog"
        }
        assert on_disk == referenced, (
            f"recovery left torn segment state: disk {on_disk} vs "
            f"manifest {referenced}"
        )
    return recovered, ledger, report
