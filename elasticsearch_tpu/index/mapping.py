"""Field mappings and document parsing.

Parity targets: org.elasticsearch.index.mapper — MapperService (mapping
merge), DocumentParser.parseDocument (JSON doc → indexable fields),
TextFieldMapper / KeywordFieldMapper / NumberFieldMapper /
BooleanFieldMapper / DateFieldMapper / DenseVectorFieldMapper
(server/src/main/java/org/elasticsearch/index/mapper/, .../mapper/vectors/).

Unlike the reference's per-field Lucene IndexableField objects, parsing
here produces columnar-friendly intermediates: term lists with positions
(text), exact terms (keyword), numeric doc values, and dense vectors —
inputs to the tiled segment builder (segment.py).
"""

from __future__ import annotations

import datetime as _dt
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import AnalysisRegistry

TEXT = "text"
KEYWORD = "keyword"
LONG = "long"
INTEGER = "integer"
SHORT = "short"
BYTE = "byte"
DOUBLE = "double"
FLOAT = "float"
HALF_FLOAT = "half_float"
BOOLEAN = "boolean"
DATE = "date"
DENSE_VECTOR = "dense_vector"
RANK_VECTORS = "rank_vectors"
SPARSE_VECTOR = "sparse_vector"
GEO_POINT = "geo_point"
NESTED = "nested"
PERCOLATOR = "percolator"

NUMERIC_TYPES = (LONG, INTEGER, SHORT, BYTE, DOUBLE, FLOAT, HALF_FLOAT)
_INT_TYPES = (LONG, INTEGER, SHORT, BYTE)


@dataclass
class MappedField:
    name: str  # full dotted path
    type: str
    analyzer: str = "standard"
    search_analyzer: Optional[str] = None
    index: bool = True
    doc_values: bool = True
    boost: float = 1.0
    # dense_vector options
    dims: int = 0
    similarity: str = "cosine"  # cosine | dot_product | l2_norm
    # date format (subset: epoch_millis and ISO handled)
    format: Optional[str] = None
    # keyword ignore_above
    ignore_above: Optional[int] = None
    # copy_to targets (values also indexed into these fields)
    copy_to: tuple = ()
    # sparse_vector static pruning: per term, drop the lowest-impact
    # tail keeping ceil((1 - ratio) * df) postings (0.0 = keep all)
    pruning_ratio: float = 0.0

    def is_numeric(self) -> bool:
        return self.type in NUMERIC_TYPES or self.type in (DATE, BOOLEAN)


class MappingParseError(ValueError):
    pass


class Mappings:
    """Parsed index mappings: flat dotted-path → MappedField registry, plus
    dynamic mapping of unseen fields (ES default dynamic:true semantics:
    strings → text + .keyword subfield, ints → long, floats → float,
    bools → boolean)."""

    def __init__(self, mapping_json: Optional[dict] = None, dynamic: bool = True):
        self.fields: Dict[str, MappedField] = {}
        # parent path → sub-field names declared via "fields" (multi-fields)
        self.multi_fields: Dict[str, List[str]] = {}
        self.dynamic = dynamic
        mapping_json = mapping_json or {}
        if "dynamic" in mapping_json:
            self.dynamic = mapping_json["dynamic"] not in (False, "false", "strict")
            self.strict = mapping_json["dynamic"] == "strict"
        else:
            self.strict = False
        # dynamic_templates: [{name: {match/path_match/
        # match_mapping_type, mapping}}] applied by dynamic_map
        self.dynamic_templates: List[dict] = list(
            mapping_json.get("dynamic_templates", [])
        )
        self._parse_properties(mapping_json.get("properties", {}), prefix="")

    def _parse_properties(self, props: dict, prefix: str):
        for name, cfg in props.items():
            path = f"{prefix}{name}"
            if "properties" in cfg and "type" not in cfg:
                # object field
                self._parse_properties(cfg["properties"], prefix=f"{path}.")
                continue
            ftype = cfg.get("type", "object")
            if ftype == "object":
                self._parse_properties(cfg.get("properties", {}), prefix=f"{path}.")
                continue
            if ftype == NESTED:
                # register the nested root AND its children — children
                # carry analyzers/types for the per-object evaluator but
                # are never flattened into parent columns
                self._add_field(path, ftype, cfg)
                self._parse_properties(
                    cfg.get("properties", {}), prefix=f"{path}."
                )
                continue
            self._add_field(path, ftype, cfg)
            for sub, subcfg in cfg.get("fields", {}).items():
                self._add_field(f"{path}.{sub}", subcfg.get("type", KEYWORD), subcfg)
                self.multi_fields.setdefault(path, []).append(sub)

    def _add_field(self, path: str, ftype: str, cfg: dict):
        known = (
            TEXT, KEYWORD, BOOLEAN, DATE, DENSE_VECTOR, RANK_VECTORS,
            SPARSE_VECTOR, GEO_POINT, NESTED, PERCOLATOR,
        ) + NUMERIC_TYPES
        if ftype not in known:
            raise MappingParseError(f"No handler for type [{ftype}] declared on field [{path}]")
        f = MappedField(
            name=path,
            type=ftype,
            analyzer=cfg.get("analyzer", "standard"),
            search_analyzer=cfg.get("search_analyzer"),
            index=cfg.get("index", True),
            doc_values=cfg.get("doc_values", True),
            boost=float(cfg.get("boost", 1.0)),
            dims=int(cfg.get("dims", 0)),
            similarity=cfg.get("similarity", "cosine"),
            format=cfg.get("format"),
            ignore_above=cfg.get("ignore_above"),
            copy_to=tuple(
                [cfg["copy_to"]]
                if isinstance(cfg.get("copy_to"), str)
                else cfg.get("copy_to", ())
            ),
            pruning_ratio=float(cfg.get("pruning_ratio", 0.0)),
        )
        if ftype == SPARSE_VECTOR and not (0.0 <= f.pruning_ratio < 1.0):
            raise MappingParseError(
                f"pruning_ratio on field [{path}] must be in [0, 1), "
                f"got [{f.pruning_ratio}]"
            )
        if ftype == DENSE_VECTOR and f.dims <= 0:
            # ES infers dims from the first vector if unset; we allow that too
            f.dims = int(cfg.get("dims", 0))
        self.fields[path] = f

    def get(self, name: str) -> Optional[MappedField]:
        return self.fields.get(name)

    def dynamic_map(self, name: str, value: Any) -> Optional[MappedField]:
        """ES dynamic-mapping rules for an unseen field."""
        if not self.dynamic:
            if self.strict:
                raise MappingParseError(
                    f"mapping set to strict, dynamic introduction of [{name}] is not allowed"
                )
            return None
        tpl = self._match_dynamic_template(name, value)
        if tpl is not None:
            cfg = dict(tpl)
            dynamic_type = _json_type_name(value)
            ftype = cfg.pop("type", None)
            if ftype in (None, "{dynamic_type}"):
                ftype = _DYNAMIC_TYPE_MAP.get(dynamic_type, TEXT)
            self._add_field(name, ftype, cfg)
            # template "fields" blocks declare multi-fields exactly as
            # explicit mappings do (the canonical text+.keyword shape)
            for sub, subcfg in cfg.get("fields", {}).items():
                self._add_field(
                    f"{name}.{sub}", subcfg.get("type", KEYWORD), subcfg
                )
                self.multi_fields.setdefault(name, []).append(sub)
            return self.fields[name]
        if isinstance(value, bool):
            ftype = BOOLEAN
        elif isinstance(value, int):
            ftype = LONG
        elif isinstance(value, float):
            ftype = FLOAT
        elif isinstance(value, str):
            # ES maps strings to text with a .keyword multi-field
            self._add_field(name, TEXT, {})
            self._add_field(f"{name}.keyword", KEYWORD, {"ignore_above": 256})
            self.multi_fields.setdefault(name, []).append("keyword")
            return self.fields[name]
        else:
            return None
        self._add_field(name, ftype, {})
        return self.fields[name]

    def _match_dynamic_template(self, name: str, value) -> Optional[dict]:
        """First dynamic template whose match/path_match/
        match_mapping_type conditions all hold (DynamicTemplate)."""
        import fnmatch

        def fn_any(patterns, target: str) -> bool:
            # ES accepts a single pattern or an array for match/unmatch/
            # path_match
            pats = patterns if isinstance(patterns, list) else [patterns]
            return any(fnmatch.fnmatch(target, str(p)) for p in pats)

        vtype = _json_type_name(value)
        leaf = name.rsplit(".", 1)[-1]
        for entry in self.dynamic_templates:
            if not isinstance(entry, dict) or len(entry) != 1:
                continue
            tpl = next(iter(entry.values()))
            if not isinstance(tpl, dict) or "mapping" not in tpl:
                continue
            if "match" in tpl and not fn_any(tpl["match"], leaf):
                continue
            if "unmatch" in tpl and fn_any(tpl["unmatch"], leaf):
                continue
            if "path_match" in tpl and not fn_any(tpl["path_match"], name):
                continue
            if (
                "match_mapping_type" in tpl
                and tpl["match_mapping_type"] not in ("*", vtype)
            ):
                continue
            return tpl["mapping"]
        return None

    def merge(self, mapping_json: dict):
        """MapperService.merge subset: add new fields; reject type changes
        and changes to index-time parameters (analyzer, dims, similarity)
        on existing fields, as the reference does."""
        other = Mappings(mapping_json)
        for name, f in other.fields.items():
            mine = self.fields.get(name)
            if mine is not None:
                if mine.type != f.type:
                    raise MappingParseError(
                        f"mapper [{name}] cannot be changed from type "
                        f"[{mine.type}] to [{f.type}]"
                    )
                for param in ("analyzer", "dims", "similarity", "pruning_ratio"):
                    theirs = getattr(f, param)
                    if param == "dims" and not theirs:
                        # dims omitted in the incoming mapping: keep the
                        # (possibly doc-inferred) existing value — an
                        # idempotent PUT-mapping must be a no-op
                        continue
                    if getattr(mine, param) != theirs:
                        raise MappingParseError(
                            f"Mapper for [{name}] conflicts: cannot update "
                            f"parameter [{param}] from "
                            f"[{getattr(mine, param)}] to [{theirs}]"
                        )
                continue  # keep the existing (richer) field object
            self.fields[name] = f
        for parent, subs in other.multi_fields.items():
            mine_subs = self.multi_fields.setdefault(parent, [])
            for s in subs:
                if s not in mine_subs:
                    mine_subs.append(s)
        if "dynamic_templates" in mapping_json:
            # ES replaces the template list wholesale on merge
            self.dynamic_templates = list(other.dynamic_templates)

    def to_json(self) -> dict:
        out = self._to_json_props()
        if self.dynamic_templates:
            out["dynamic_templates"] = self.dynamic_templates
        return out

    def _to_json_props(self) -> dict:
        props: dict = {}
        mf_children = {
            f"{p}.{s}" for p, subs in self.multi_fields.items() for s in subs
        }
        for name, f in sorted(self.fields.items()):
            if name in mf_children:
                continue  # rendered under the parent's "fields"
            parts = name.split(".")
            node = props
            for p in parts[:-1]:
                parent = node.setdefault(p, {"properties": {}})
                node = parent.setdefault("properties", {})
            entry = self._field_json(f)
            for sub in self.multi_fields.get(name, []):
                subf = self.fields.get(f"{name}.{sub}")
                if subf is not None:
                    entry.setdefault("fields", {})[sub] = self._field_json(subf)
            node[parts[-1]] = entry
        return {"properties": props}

    @staticmethod
    def _field_json(f: "MappedField") -> dict:
        entry: dict = {"type": f.type}
        if f.type == TEXT and f.analyzer != "standard":
            entry["analyzer"] = f.analyzer
        if f.type in (DENSE_VECTOR, RANK_VECTORS):
            entry["dims"] = f.dims
            entry["similarity"] = f.similarity
        if f.type == SPARSE_VECTOR and f.pruning_ratio:
            entry["pruning_ratio"] = f.pruning_ratio
        if f.ignore_above is not None:
            entry["ignore_above"] = f.ignore_above
        if f.copy_to:
            entry["copy_to"] = list(f.copy_to)
        return entry


_DYNAMIC_TYPE_MAP = {
    "string": TEXT,
    "long": LONG,
    "double": FLOAT,
    "boolean": BOOLEAN,
}


def _json_type_name(value) -> str:
    """ES match_mapping_type vocabulary for a JSON value."""
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, int):
        return "long"
    if isinstance(value, float):
        return "double"
    if isinstance(value, str):
        return "string"
    if isinstance(value, dict):
        return "object"
    return "*"


@dataclass
class ParsedDocument:
    """Columnar-friendly parse result for one document."""

    doc_id: str  # _id
    source: dict
    # field → list of (term, position) for indexed text fields
    text_terms: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)
    # field → exact terms (keyword); list to support arrays
    keyword_terms: Dict[str, List[str]] = field(default_factory=dict)
    # field → numeric doc value(s) as float64-compatible numbers
    numeric_values: Dict[str, List[float]] = field(default_factory=dict)
    # field → vector
    vectors: Dict[str, List[float]] = field(default_factory=dict)
    # field → per-doc token-embedding matrix (rank_vectors: one row per
    # token, the late-interaction reranker's document side)
    multi_vectors: Dict[str, List[List[float]]] = field(default_factory=dict)
    # field → term→weight map (sparse_vector: SPLADE-shaped learned
    # sparse representations, input to the impact-ordered postings)
    sparse_vectors: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # field → field length (token count incl. duplicates) for norms
    field_lengths: Dict[str, int] = field(default_factory=dict)


def parse_date_millis(value: Any, fmt: Optional[str] = None) -> float:
    """Date → epoch millis. Supports epoch_millis numbers and ISO-8601."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    s = str(value)
    if s.isdigit():
        return float(int(s))
    iso = s.replace("Z", "+00:00")
    try:
        dt = _dt.datetime.fromisoformat(iso)
    except ValueError as e:
        raise MappingParseError(f"failed to parse date field [{value}]") from e
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt.timestamp() * 1000.0


class DocumentParser:
    """DocumentParser.parseDocument analog: walks the source JSON, resolves
    each leaf against the mappings (dynamically mapping unseen fields), and
    emits analyzer output / doc values / vectors."""

    def __init__(self, mappings: Mappings, analysis: AnalysisRegistry):
        self.mappings = mappings
        self.analysis = analysis

    def parse(self, doc_id: str, source: dict) -> ParsedDocument:
        out = ParsedDocument(doc_id=doc_id, source=source)
        self._walk(source, "", out)
        return out

    def _walk(self, obj: Any, prefix: str, out: ParsedDocument):
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if isinstance(value, dict):
                f = self.mappings.get(path)
                if f is not None:
                    if f.type == GEO_POINT:
                        self._index_values(f, path, [value], out)
                        continue
                    if f.type == SPARSE_VECTOR:
                        # term→weight maps arrive as JSON objects; the
                        # weights must be finite numbers (the reference's
                        # SparseVectorFieldMapper rejects anything else)
                        self._index_values(f, path, [value], out)
                        continue
                    if f.type == PERCOLATOR:
                        # stored queries live in _source; validate NOW so
                        # a malformed query is rejected at index time
                        # (PercolatorFieldMapper parses at index time)
                        from ..search import dsl as _dsl

                        try:
                            _dsl.parse_query(value)
                        except _dsl.QueryParseError as e:
                            raise MappingParseError(
                                f"percolator field [{path}]: {e}"
                            )
                        continue
                    if f.type == NESTED:
                        # nested objects stay whole in _source: they are
                        # NOT flattened into parent columns, which is
                        # exactly why cross-object queries can't match
                        # (the reference stores them as separate docs)
                        continue
                    # leaf/object conflict — the reference rejects this at
                    # parse time rather than silently corrupting fields
                    raise MappingParseError(
                        f"object mapping for [{path}] tried to parse field "
                        f"as object, but found a concrete value"
                        if f.type != DENSE_VECTOR
                        else f"dense_vector field [{path}] must be an array of numbers"
                    )
                self._walk(value, f"{path}.", out)
                continue
            values = value if isinstance(value, list) else [value]
            if not values:
                continue
            f = self.mappings.get(path)
            if f is not None and f.type == NESTED:
                continue  # list-of-objects form; see the dict branch
            if f is not None and f.type == GEO_POINT:
                # [lon, lat] array form is one point, not multi-values
                geo_vals = (
                    [value]
                    if isinstance(value, list)
                    and len(value) == 2
                    and all(isinstance(x, (int, float)) for x in value)
                    else values
                )
                self._index_values(f, path, geo_vals, out)
                continue
            if f is None:
                probe = values[0]
                if isinstance(probe, (int, float, str, bool)):
                    f = self.mappings.dynamic_map(path, probe)
                elif probe is None:
                    continue
                else:
                    continue
            if f is None:
                continue
            self._index_with_multifields(f, path, values, out)
            # copy_to: values also index into the target fields (one
            # level — the reference rejects copy_to chains), including
            # the targets' own multi-fields (e.g. a dynamic .keyword)
            for target in f.copy_to:
                tf = self.mappings.get(target)
                if tf is None:
                    tf = self.mappings.dynamic_map(target, values[0])
                if tf is not None:
                    self._index_with_multifields(tf, target, values, out)

    def _index_with_multifields(
        self, f: MappedField, path: str, values: List[Any], out: ParsedDocument
    ):
        self._index_values(f, path, values, out)
        # multi-fields explicitly declared via "fields" (or dynamic
        # .keyword) — never object children that merely share a prefix
        for sub in self.mappings.multi_fields.get(path, ()):
            sub_field = self.mappings.get(f"{path}.{sub}")
            if sub_field is not None:
                self._index_values(sub_field, f"{path}.{sub}", values, out)

    def _index_values(self, f: MappedField, path: str, values: List[Any], out: ParsedDocument):
        if f.type == TEXT:
            if not f.index:
                return
            analyzer = self.analysis.get(f.analyzer)
            terms = out.text_terms.setdefault(path, [])
            pos = (max(p for _, p in terms) + 101) if terms else 0
            length = out.field_lengths.get(path, 0)
            for v in values:
                if v is None:
                    continue
                toks = analyzer.analyze(str(v))
                for t in toks:
                    terms.append((t.text, pos + t.position))
                if toks:
                    pos += toks[-1].position + 101  # ES position_increment_gap=100
                length += len(toks)
            out.field_lengths[path] = length
        elif f.type == KEYWORD:
            kws = out.keyword_terms.setdefault(path, [])
            for v in values:
                if v is None:
                    continue
                s = str(v) if not isinstance(v, bool) else ("true" if v else "false")
                if f.ignore_above is not None and len(s) > f.ignore_above:
                    continue
                kws.append(s)
        elif f.type in NUMERIC_TYPES:
            nums = out.numeric_values.setdefault(path, [])
            for v in values:
                if v is None:
                    continue
                try:
                    x = float(v)
                except (TypeError, ValueError) as e:
                    raise MappingParseError(
                        f"failed to parse field [{path}] of type [{f.type}]"
                    ) from e
                if f.type in _INT_TYPES and not isinstance(v, bool):
                    x = float(int(x))
                if math.isnan(x) or math.isinf(x):
                    raise MappingParseError(f"illegal value for field [{path}]: {v}")
                nums.append(x)
        elif f.type == BOOLEAN:
            nums = out.numeric_values.setdefault(path, [])
            for v in values:
                if v is None:
                    continue
                if isinstance(v, bool):
                    nums.append(1.0 if v else 0.0)
                elif v in ("true", "false", ""):
                    nums.append(1.0 if v == "true" else 0.0)
                else:
                    raise MappingParseError(
                        f"Failed to parse value [{v}] as only [true] or [false] are allowed."
                    )
        elif f.type == DATE:
            nums = out.numeric_values.setdefault(path, [])
            for v in values:
                if v is None:
                    continue
                nums.append(parse_date_millis(v, f.format))
        elif f.type == GEO_POINT:
            lats = out.numeric_values.setdefault(f"{path}.lat", [])
            lons = out.numeric_values.setdefault(f"{path}.lon", [])
            for v in values:
                if v is None:
                    continue
                if isinstance(v, dict):
                    lat, lon = v.get("lat"), v.get("lon")
                elif isinstance(v, str):
                    parts = [p.strip() for p in v.split(",")]
                    if len(parts) != 2:
                        raise MappingParseError(
                            f"failed to parse geo_point [{path}]: [{v}]"
                        )
                    lat, lon = parts[0], parts[1]
                elif isinstance(v, (list, tuple)) and len(v) == 2:
                    lon, lat = v[0], v[1]  # GeoJSON order
                else:
                    raise MappingParseError(
                        f"failed to parse geo_point [{path}]: [{v}]"
                    )
                try:
                    lat_f, lon_f = float(lat), float(lon)
                except (TypeError, ValueError) as e:
                    raise MappingParseError(
                        f"failed to parse geo_point [{path}]"
                    ) from e
                if not (-90 <= lat_f <= 90) or not (-180 <= lon_f <= 180):
                    raise MappingParseError(
                        f"geo_point [{path}] out of bounds: "
                        f"{lat_f},{lon_f}"
                    )
                lats.append(lat_f)
                lons.append(lon_f)
        elif f.type == NESTED:
            pass  # nested objects live in _source only (see _walk)
        elif f.type == PERCOLATOR:
            # a non-dict value reached here (dicts are intercepted in
            # _walk): the reference rejects such docs at index time
            raise MappingParseError(
                f"percolator field [{path}] must hold a query object"
            )
        elif f.type == SPARSE_VECTOR:
            weights: Dict[str, float] = dict(out.sparse_vectors.get(path, {}))
            for v in values:
                if v is None:
                    continue
                if not isinstance(v, dict):
                    raise MappingParseError(
                        f"sparse_vector field [{path}] must hold a "
                        "term→weight object"
                    )
                for term, w in v.items():
                    if isinstance(w, bool) or not isinstance(w, (int, float)):
                        raise MappingParseError(
                            f"sparse_vector field [{path}] weight for term "
                            f"[{term}] must be a number, got [{w!r}]"
                        )
                    wf = float(w)
                    if math.isnan(wf) or math.isinf(wf):
                        raise MappingParseError(
                            f"sparse_vector field [{path}] weight for term "
                            f"[{term}] must be finite, got [{w}]"
                        )
                    if wf <= 0.0:
                        # non-positive weights can never contribute to a
                        # max-score top-k; drop them like the reference
                        # drops zero-weight features
                        continue
                    weights[str(term)] = wf
            if weights:
                out.sparse_vectors[path] = weights
        elif f.type == DENSE_VECTOR:
            vec = [float(x) for x in values]
            if f.dims and len(vec) != f.dims:
                raise MappingParseError(
                    f"The [{path}] field has dims [{f.dims}] but the indexed "
                    f"vector has [{len(vec)}] dimensions"
                )
            if not f.dims:
                f.dims = len(vec)
            out.vectors[path] = vec
        elif f.type == RANK_VECTORS:
            # one matrix per doc: [[...], ...] (a flat vector is accepted
            # as a one-token matrix). Rows all share the mapped dims —
            # the padded per-segment column needs a rectangular gather.
            rows = values
            if rows and all(
                isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in rows
            ):
                rows = [rows]
            mat: List[List[float]] = []
            for row in rows:
                if row is None:
                    continue
                if not isinstance(row, (list, tuple)):
                    raise MappingParseError(
                        f"rank_vectors field [{path}] must hold an array "
                        "of vectors"
                    )
                vec = [float(x) for x in row]
                if f.dims and len(vec) != f.dims:
                    raise MappingParseError(
                        f"The [{path}] field has dims [{f.dims}] but an "
                        f"indexed vector has [{len(vec)}] dimensions"
                    )
                if not f.dims:
                    f.dims = len(vec)
                mat.append(vec)
            if mat:
                out.multi_vectors[path] = mat
