"""Immutable tiled columnar segments — the TPU-native index format.

Reference analog: a Lucene segment (postings + norms + doc values + stored
fields + vectors), as orchestrated by InternalEngine/IndexWriter
(server/.../index/engine/InternalEngine.java) and read through codecs
(server/.../index/codec/). The *format* is redesigned for TPU execution
rather than ported:

  - Postings are laid out as dense tiles of TILE=128 lanes (the TPU lane
    width): `doc_ids[int32, n_tiles, 128]` / `tfs[int32, n_tiles, 128]`,
    padded with doc_id = -1. A term owns a contiguous tile range
    (`term_tile_start/term_tile_count`), so a query gathers whole tile rows
    — no pointer chasing, no variable-length block decode on device. This
    replaces Lucene's FOR/PFOR-compressed 128-doc postings blocks
    (ForUtil / Lucene postings format): decode happens ONCE at index build,
    not per query (the BASELINE.json north-star layout).
  - Per-tile sidecars `tile_max_tf` / `tile_min_norm` support block-max
    pruning (the WAND analog: an upper score bound per tile is
    max_tf/(max_tf + denom(min_norm)) since tf/(tf+d) is monotone).
  - Norms are Lucene SmallFloat byte4-encoded field lengths (exact BM25
    parity with the reference's quantized doc lengths).
  - Keyword fields get the same postings layout (tf=1) plus sorted-set
    ordinal doc values for aggregations.
  - Numeric/date/boolean fields are dense float64 doc-value columns with
    a missing mask; range/term filters become vectorized comparisons
    (a dense compare beats a BKD tree on this hardware).
  - dense_vector fields are (N, dims) float32 matrices (cosine fields also
    store a unit-normalized copy used for scoring) — brute-force kNN is
    one MXU matmul.

Persistence: one directory per segment holding .npy files plus a
`segment.json` manifest; term dictionaries are a utf-8 blob + offsets
(terms may contain any byte except nothing). Commits are crash-safe via
atomic manifest rename at the shard level (see engine.py).
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.smallfloat import encode_norms
from .mapping import (
    DENSE_VECTOR,
    KEYWORD,
    RANK_VECTORS,
    TEXT,
    Mappings,
    ParsedDocument,
)

TILE = 128  # TPU lane width; one tile = one row of the postings arrays
INVALID_DOC = -1


@dataclass
class FieldStats:
    """Per-field collection statistics (Lucene CollectionStatistics)."""

    doc_count: int = 0  # docs that have this field
    sum_total_term_freq: int = 0  # total tokens across docs
    sum_doc_freq: int = 0  # total (term, doc) postings


@dataclass
class PostingsField:
    """Tiled postings for one indexed field."""

    terms: List[str]  # sorted term dictionary
    term_df: np.ndarray  # int32[n_terms] document frequency
    term_total_tf: np.ndarray  # int64[n_terms] total term frequency
    term_tile_start: np.ndarray  # int32[n_terms]
    term_tile_count: np.ndarray  # int32[n_terms]
    doc_ids: np.ndarray  # int32[n_tiles, TILE], padded with INVALID_DOC
    tfs: np.ndarray  # int32[n_tiles, TILE], padded with 0
    tile_max_tf: np.ndarray  # int32[n_tiles]
    tile_min_norm: np.ndarray  # uint8[n_tiles] min norm byte in tile
    norms: np.ndarray  # uint8[N] SmallFloat-encoded field length per doc
    stats: FieldStats = field(default_factory=FieldStats)
    # columnar positions (text fields; None for keyword/legacy segments).
    # Compact CSR aligned to posting order: posting k of term t lives at
    # global posting index term_pos_start[t] + k, and its sorted positions
    # are pos_data[pos_offsets[p] : pos_offsets[p+1]]. This is the tiled
    # analog of Lucene's PositionsEnum — decoded once at index build, so
    # match_phrase never re-analyzes stored _source (SURVEY.md §2.5
    # postings row; VERDICT round-1 weak #5).
    term_pos_start: Optional[np.ndarray] = None  # int64[n_terms]
    pos_offsets: Optional[np.ndarray] = None  # int64[sum(df)+1]
    pos_data: Optional[np.ndarray] = None  # int32[sum(tf)]
    # precomputed BM25 impacts (text fields; the BM25S eager-scoring
    # layout): per posting, the tf/norm factor 1 - 1/(1 + tf*inv_norm)
    # folded at build time with the SEGMENT-local avgdl, quantized to
    # int8 with per-term symmetric scales. Query-time scoring of a term
    # then reduces to idf * dequantized gather — no norm math on the hot
    # path. Built for text fields on both host and device build paths
    # (bit-identical, parity-gated).
    impacts: Optional[np.ndarray] = None  # int8[n_tiles, TILE]
    impact_scales: Optional[np.ndarray] = None  # float32[n_terms]
    _term_index: Optional[Dict[str, int]] = None

    def term_id(self, term: str) -> int:
        if self._term_index is None:
            self._term_index = {t: i for i, t in enumerate(self.terms)}
        return self._term_index.get(term, -1)

    @property
    def n_tiles(self) -> int:
        return self.doc_ids.shape[0]

    @property
    def has_positions(self) -> bool:
        return self.pos_data is not None

    def term_docs(self, tid: int) -> np.ndarray:
        """Compact (unpadded) sorted doc-id list for one term."""
        start = int(self.term_tile_start[tid])
        count = int(self.term_tile_count[tid])
        return self.doc_ids[start : start + count].ravel()[: int(self.term_df[tid])]

    def doc_positions(self, tid: int, doc: int) -> Optional[np.ndarray]:
        """Sorted positions of term `tid` in local doc `doc`, or None if
        the term does not occur there (or positions are absent)."""
        if self.pos_data is None:
            return None
        docs = self.term_docs(tid)
        k = int(np.searchsorted(docs, doc))
        if k >= len(docs) or docs[k] != doc:
            return None
        p = int(self.term_pos_start[tid]) + k
        return self.pos_data[self.pos_offsets[p] : self.pos_offsets[p + 1]]


@dataclass
class NumericField:
    values: np.ndarray  # float64[N] (first value per doc; arrays keep min)
    exists: np.ndarray  # bool[N]
    # multi-values flattened for exists/terms semantics (round 2: full MV)


@dataclass
class OrdinalField:
    """Sorted-set ordinals for keyword doc values (global ords analog)."""

    ord_terms: List[str]  # sorted unique values
    ords: np.ndarray  # int32[N] ordinal of first value, -1 = missing
    # full multi-value ordinals (CSR): for aggs over keyword arrays
    mv_ords: np.ndarray  # int32[total_values]
    mv_offsets: np.ndarray  # int32[N+1]


@dataclass
class VectorField:
    vectors: np.ndarray  # float32[N, dims]; zero rows where missing
    exists: np.ndarray  # bool[N]
    similarity: str
    unit_vectors: Optional[np.ndarray] = None  # normalized copy for cosine


@dataclass
class MultiVectorField:
    """Per-doc token-embedding matrices (`rank_vectors`) in a flat CSR
    layout: doc d owns token rows tok_offsets[d] : tok_offsets[d+1] of
    tok_vectors. The late-interaction reranker gathers whole per-doc
    blocks, so rows stay contiguous per doc; cosine fields store rows
    unit-normalized at build (maxsim over unit rows = cosine maxsim)."""

    tok_vectors: np.ndarray  # float32[total_tokens, dims]
    tok_offsets: np.ndarray  # int32[N+1]
    exists: np.ndarray  # bool[N]
    similarity: str

    @property
    def max_tokens(self) -> int:
        if len(self.tok_offsets) <= 1:
            return 0
        return int(np.diff(self.tok_offsets).max())


@dataclass
class SparseField:
    """Impact-ordered tiled postings for one `sparse_vector` field (the
    GPUSparse/BM25S layout): a term owns a contiguous tile range whose
    postings are sorted by weight DESC (doc asc tie-break), so the
    highest-impact postings of every term live in its first tiles and a
    per-tile `tile_max` sidecar is non-increasing within a term — the
    block-max pruning invariant. The fp32 `weights` plane is the exact
    oracle source of truth; `qweights` is its int8 per-term-symmetric
    twin (4x smaller in HBM), with `tile_qmax` giving the dequantized
    per-tile bound so pruning stays exact in either serving mode."""

    terms: List[str]  # sorted term dictionary
    term_df: np.ndarray  # int32[n_terms] kept postings per term
    term_tile_start: np.ndarray  # int32[n_terms]
    term_tile_count: np.ndarray  # int32[n_terms]
    doc_ids: np.ndarray  # int32[n_tiles, TILE], impact-ordered, pad -1
    weights: np.ndarray  # float32[n_tiles, TILE], pad 0 (exact plane)
    qweights: np.ndarray  # int8[n_tiles, TILE] per-term symmetric twin
    scales: np.ndarray  # float32[n_terms] dequant scale = maxabs/127
    tile_max: np.ndarray  # float32[n_tiles] max fp32 weight in tile
    tile_qmax: np.ndarray  # float32[n_tiles] max dequantized weight
    exists: np.ndarray  # bool[N]
    pruned: int = 0  # postings dropped by static pruning at build
    _term_index: Optional[Dict[str, int]] = None

    def term_id(self, term: str) -> int:
        if self._term_index is None:
            self._term_index = {t: i for i, t in enumerate(self.terms)}
        return self._term_index.get(term, -1)

    @property
    def n_tiles(self) -> int:
        return self.doc_ids.shape[0]

    def term_postings(self, tid: int) -> Tuple[np.ndarray, np.ndarray]:
        """Compact (unpadded) impact-ordered (docs, fp32 weights)."""
        start = int(self.term_tile_start[tid])
        count = int(self.term_tile_count[tid])
        df = int(self.term_df[tid])
        return (
            self.doc_ids[start : start + count].ravel()[:df],
            self.weights[start : start + count].ravel()[:df],
        )


def sparse_plan(inv: Dict[str, Dict[int, float]], pruning_ratio: float) -> dict:
    """Host-side layout plan for one sparse_vector column, shared by the
    host build AND the device build (ops/index_build.sparse_planes_device):
    sorted term dictionary, impact ordering (weight desc, doc asc
    tie-break), static pruning of the lowest-impact tail, and flat scatter
    destinations. All layout decisions happen exactly once here, so the
    two materializers stay bit-identical by construction — the device
    kernels only scatter, reduce with exact max, and quantize."""
    terms = sorted(inv)
    n_terms = len(terms)
    term_df = np.zeros(n_terms, np.int32)
    term_tile_start = np.zeros(n_terms, np.int32)
    term_tile_count = np.zeros(n_terms, np.int32)
    docs_parts: List[np.ndarray] = []
    w_parts: List[np.ndarray] = []
    dest_parts: List[np.ndarray] = []
    next_tile = 0
    pruned = 0
    for tid, term in enumerate(terms):
        plist = inv[term]
        d_arr = np.fromiter(sorted(plist), count=len(plist), dtype=np.int32)
        w_arr = np.asarray([plist[int(d)] for d in d_arr], dtype=np.float32)
        order = np.lexsort((d_arr, -w_arr))
        d_arr, w_arr = d_arr[order], w_arr[order]
        if pruning_ratio > 0.0 and len(d_arr) > 1:
            keep = max(1, math.ceil((1.0 - pruning_ratio) * len(d_arr)))
            pruned += len(d_arr) - keep
            d_arr, w_arr = d_arr[:keep], w_arr[:keep]
        df = len(d_arr)
        term_df[tid] = df
        nt = (df + TILE - 1) // TILE
        term_tile_start[tid] = next_tile
        term_tile_count[tid] = nt
        dest_parts.append(next_tile * TILE + np.arange(df, dtype=np.int64))
        docs_parts.append(d_arr)
        w_parts.append(w_arr)
        next_tile += nt
    return {
        "terms": terms,
        "term_df": term_df,
        "term_tile_start": term_tile_start,
        "term_tile_count": term_tile_count,
        "n_tiles": next_tile,
        "pruned": pruned,
        "docs": (
            np.concatenate(docs_parts) if docs_parts else np.zeros(0, np.int32)
        ),
        "weights": (
            np.concatenate(w_parts) if w_parts else np.zeros(0, np.float32)
        ),
        "dest": (
            np.concatenate(dest_parts) if dest_parts else np.zeros(0, np.int64)
        ),
        "tile_term": np.repeat(
            np.arange(n_terms, dtype=np.int32), term_tile_count
        ),
    }


def sparse_from_plan(plan: dict, n: int, exists: np.ndarray) -> SparseField:
    """Host materializer: scatter the planned postings into padded tile
    planes and derive the quantized twin + block-max sidecars. Mirrors
    ops/index_build.sparse_planes_device formula-for-formula (scatter,
    exact max reductions, f32 divides, rint) for bit-parity."""
    n_tiles = int(plan["n_tiles"])
    n_terms = len(plan["terms"])
    doc_plane = np.full(n_tiles * TILE, INVALID_DOC, np.int32)
    w_plane = np.zeros(n_tiles * TILE, np.float32)
    doc_plane[plan["dest"]] = plan["docs"]
    w_plane[plan["dest"]] = plan["weights"]
    doc_ids = doc_plane.reshape(n_tiles, TILE)
    weights = w_plane.reshape(n_tiles, TILE)
    tile_term = plan["tile_term"]
    if n_tiles:
        tile_max = weights.max(axis=1).astype(np.float32)
    else:
        tile_max = np.zeros(0, np.float32)
    scales = np.zeros(n_terms, np.float32)
    if n_terms:
        # impact ordering puts every term's global max in its first tile
        first = plan["term_tile_start"].astype(np.int64)
        scales = (tile_max[first] / np.float32(127.0)).astype(np.float32)
    if n_tiles:
        slot_scale = scales[tile_term]
        safe = np.where(
            slot_scale == 0.0, np.float32(1.0), slot_scale
        ).astype(np.float32)
        qweights = np.clip(
            np.rint(weights / safe[:, None]), -127, 127
        ).astype(np.int8)
        tile_qmax = (
            qweights.max(axis=1).astype(np.float32) * slot_scale
        ).astype(np.float32)
    else:
        qweights = np.zeros((0, TILE), np.int8)
        tile_qmax = np.zeros(0, np.float32)
    return SparseField(
        terms=plan["terms"],
        term_df=plan["term_df"],
        term_tile_start=plan["term_tile_start"],
        term_tile_count=plan["term_tile_count"],
        doc_ids=doc_ids,
        weights=weights,
        qweights=qweights,
        scales=scales,
        tile_max=tile_max,
        tile_qmax=tile_qmax,
        exists=exists,
        pruned=int(plan["pruned"]),
    )


def attach_impacts(pf: PostingsField, inv_norm_cache: np.ndarray) -> None:
    """Fold the BM25 tf/norm factor into per-posting int8 impacts (BM25S
    eager scoring): impact = 1 - 1/(1 + tf * inv_norm[norm_byte]) with
    the SEGMENT-local avgdl baked into `inv_norm_cache` (256-entry f32
    table, computed once on host and shared with the device build path
    so both produce identical bits). Query-time scoring of term t is
    then idf(t) * impact — pure gather+sum."""
    n_terms = len(pf.terms)
    if pf.n_tiles == 0:
        pf.impacts = np.zeros((0, TILE), np.int8)
        pf.impact_scales = np.zeros(n_terms, np.float32)
        return
    valid = pf.doc_ids >= 0
    n = len(pf.norms)
    nb = pf.norms[np.clip(pf.doc_ids, 0, n - 1 if n else 0)]
    one = np.float32(1.0)
    inv = inv_norm_cache[nb.astype(np.int64)]
    imp = (one - one / (one + pf.tfs.astype(np.float32) * inv)).astype(
        np.float32
    )
    imp = np.where(valid, imp, np.float32(0.0))
    tile_imax = imp.max(axis=1).astype(np.float32)
    starts = pf.term_tile_start.astype(np.int64)
    term_max = np.maximum.reduceat(tile_imax, starts).astype(np.float32)
    scales = (term_max / np.float32(127.0)).astype(np.float32)
    tile_term = np.repeat(
        np.arange(n_terms, dtype=np.int64), pf.term_tile_count
    )
    slot_scale = scales[tile_term]
    safe = np.where(slot_scale == 0.0, np.float32(1.0), slot_scale).astype(
        np.float32
    )
    pf.impacts = np.clip(np.rint(imp / safe[:, None]), -127, 127).astype(
        np.int8
    )
    pf.impact_scales = scales


class Segment:
    """An immutable searchable segment of N documents (local ids 0..N-1)."""

    def __init__(
        self,
        num_docs: int,
        doc_ids: List[str],
        sources: List[Optional[dict]],
        postings: Dict[str, PostingsField],
        numerics: Dict[str, NumericField],
        ordinals: Dict[str, OrdinalField],
        vectors: Dict[str, VectorField],
        generation: int = 0,
        multi_vectors: Optional[Dict[str, MultiVectorField]] = None,
        sparse: Optional[Dict[str, SparseField]] = None,
    ):
        self.num_docs = num_docs
        self.doc_ids = doc_ids  # _id per local doc
        self.sources = sources  # _source per local doc
        self.postings = postings
        self.numerics = numerics
        self.ordinals = ordinals
        self.vectors = vectors
        self.multi_vectors = multi_vectors or {}
        self.sparse = sparse or {}
        self.generation = generation

    # ---------- persistence ----------

    def save(self, path: str, codec: str = "default") -> None:
        os.makedirs(path, exist_ok=True)
        compress = codec == "best_compression"
        manifest: dict = {
            "format_version": 1,
            "num_docs": self.num_docs,
            "generation": self.generation,
            "codec": codec,
            "postings": {},
            "numerics": sorted(self.numerics),
            "ordinals": sorted(self.ordinals),
            "vectors": {},
            "multi_vectors": {},
            "sparse": {},
        }
        arrays: Dict[str, np.ndarray] = {}

        def put(name: str, arr: np.ndarray):
            arrays[name] = np.ascontiguousarray(arr)

        for fname, pf in self.postings.items():
            key = _fkey(fname)
            manifest["postings"][fname] = {
                "key": key,
                "n_terms": len(pf.terms),
                "stats": vars(pf.stats),
            }
            blob, offsets = _encode_terms(pf.terms)
            arrays[f"{key}.terms_blob"] = blob
            put(f"{key}.term_offsets", offsets)
            put(f"{key}.term_df", pf.term_df)
            put(f"{key}.term_total_tf", pf.term_total_tf)
            put(f"{key}.term_tile_start", pf.term_tile_start)
            put(f"{key}.term_tile_count", pf.term_tile_count)
            if compress:
                # best_compression: posting tiles go to disk delta+varint
                # encoded (the native codec — ForUtil's on-disk role);
                # decoded once at load into the dense HBM-upload form
                from ..native import tiles_encode, vb_encode

                manifest["postings"][fname]["tiles_vb"] = list(
                    pf.doc_ids.shape
                )
                arrays[f"{key}.doc_ids_vb"] = np.frombuffer(
                    tiles_encode(pf.doc_ids), np.uint8
                )
                arrays[f"{key}.tfs_vb"] = np.frombuffer(
                    vb_encode(pf.tfs.ravel()), np.uint8
                )
            else:
                put(f"{key}.doc_ids", pf.doc_ids)
                put(f"{key}.tfs", pf.tfs)
            put(f"{key}.tile_max_tf", pf.tile_max_tf)
            put(f"{key}.tile_min_norm", pf.tile_min_norm)
            put(f"{key}.norms", pf.norms)
            if pf.has_positions:
                manifest["postings"][fname]["positions"] = True
                put(f"{key}.term_pos_start", pf.term_pos_start)
                put(f"{key}.pos_offsets", pf.pos_offsets)
                put(f"{key}.pos_data", pf.pos_data)
            if pf.impacts is not None:
                manifest["postings"][fname]["impacts"] = True
                put(f"{key}.impacts", pf.impacts)
                put(f"{key}.impact_scales", pf.impact_scales)
        for fname, nf in self.numerics.items():
            key = _fkey(fname)
            put(f"num.{key}.values", nf.values)
            put(f"num.{key}.exists", nf.exists)
        for fname, of in self.ordinals.items():
            key = _fkey(fname)
            blob, offsets = _encode_terms(of.ord_terms)
            arrays[f"ord.{key}.terms_blob"] = blob
            put(f"ord.{key}.term_offsets", offsets)
            put(f"ord.{key}.ords", of.ords)
            put(f"ord.{key}.mv_ords", of.mv_ords)
            put(f"ord.{key}.mv_offsets", of.mv_offsets)
        for fname, vf in self.vectors.items():
            key = _fkey(fname)
            manifest["vectors"][fname] = {"key": key, "similarity": vf.similarity}
            put(f"vec.{key}.vectors", vf.vectors)
            put(f"vec.{key}.exists", vf.exists)
        for fname, mvf in self.multi_vectors.items():
            key = _fkey(fname)
            manifest["multi_vectors"][fname] = {
                "key": key,
                "similarity": mvf.similarity,
            }
            put(f"mvec.{key}.tok_vectors", mvf.tok_vectors)
            put(f"mvec.{key}.tok_offsets", mvf.tok_offsets)
            put(f"mvec.{key}.exists", mvf.exists)
        for fname, sf in self.sparse.items():
            key = _fkey(fname)
            manifest["sparse"][fname] = {
                "key": key,
                "n_terms": len(sf.terms),
                "pruned": sf.pruned,
            }
            blob, offsets = _encode_terms(sf.terms)
            arrays[f"sp.{key}.terms_blob"] = blob
            put(f"sp.{key}.term_offsets", offsets)
            put(f"sp.{key}.term_df", sf.term_df)
            put(f"sp.{key}.term_tile_start", sf.term_tile_start)
            put(f"sp.{key}.term_tile_count", sf.term_tile_count)
            put(f"sp.{key}.doc_ids", sf.doc_ids)
            put(f"sp.{key}.weights", sf.weights)
            put(f"sp.{key}.qweights", sf.qweights)
            put(f"sp.{key}.scales", sf.scales)
            put(f"sp.{key}.tile_max", sf.tile_max)
            put(f"sp.{key}.tile_qmax", sf.tile_qmax)
            put(f"sp.{key}.exists", sf.exists)

        np.savez(os.path.join(path, "arrays.npz"), **arrays)
        fsync_path(os.path.join(path, "arrays.npz"))
        if compress:
            # stored fields ride DEFLATE (the reference's
            # best_compression stored-fields codec)
            import gzip

            with gzip.open(
                os.path.join(path, "docs.json.gz"), "wt", encoding="utf-8"
            ) as f:
                json.dump(
                    {"doc_ids": self.doc_ids, "sources": self.sources}, f
                )
            fsync_path(os.path.join(path, "docs.json.gz"))
        else:
            with open(os.path.join(path, "docs.json"), "w") as f:
                json.dump(
                    {"doc_ids": self.doc_ids, "sources": self.sources}, f
                )
                f.flush()
                os.fsync(f.fileno())
        tmp = os.path.join(path, "segment.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, "segment.json"))
        fsync_dir(path)

    @classmethod
    def load(cls, path: str) -> "Segment":
        with open(os.path.join(path, "segment.json")) as f:
            manifest = json.load(f)
        gz = os.path.join(path, "docs.json.gz")
        if os.path.exists(gz):
            import gzip

            with gzip.open(gz, "rt", encoding="utf-8") as f:
                docs = json.load(f)
        else:
            with open(os.path.join(path, "docs.json")) as f:
                docs = json.load(f)
        data = np.load(os.path.join(path, "arrays.npz"), allow_pickle=False)
        postings: Dict[str, PostingsField] = {}
        for fname, meta in manifest["postings"].items():
            key = meta["key"]
            terms = _decode_terms(data[f"{key}.terms_blob"], data[f"{key}.term_offsets"])
            if meta.get("tiles_vb"):
                # best_compression: one-time native decode into the
                # dense HBM-upload form (the ForUtil decode moment)
                from ..native import tiles_decode, vb_decode

                n_tiles, width = meta["tiles_vb"]
                doc_ids = tiles_decode(
                    data[f"{key}.doc_ids_vb"].tobytes(), n_tiles, width
                )
                tfs = vb_decode(
                    data[f"{key}.tfs_vb"].tobytes(), n_tiles * width
                ).reshape(n_tiles, width)
            else:
                doc_ids = data[f"{key}.doc_ids"]
                tfs = data[f"{key}.tfs"]
            postings[fname] = PostingsField(
                terms=terms,
                term_df=data[f"{key}.term_df"],
                term_total_tf=data[f"{key}.term_total_tf"],
                term_tile_start=data[f"{key}.term_tile_start"],
                term_tile_count=data[f"{key}.term_tile_count"],
                doc_ids=doc_ids,
                tfs=tfs,
                tile_max_tf=data[f"{key}.tile_max_tf"],
                tile_min_norm=data[f"{key}.tile_min_norm"],
                norms=data[f"{key}.norms"],
                stats=FieldStats(**meta["stats"]),
                term_pos_start=(
                    data[f"{key}.term_pos_start"] if meta.get("positions") else None
                ),
                pos_offsets=(
                    data[f"{key}.pos_offsets"] if meta.get("positions") else None
                ),
                pos_data=(
                    data[f"{key}.pos_data"] if meta.get("positions") else None
                ),
                impacts=(
                    data[f"{key}.impacts"] if meta.get("impacts") else None
                ),
                impact_scales=(
                    data[f"{key}.impact_scales"]
                    if meta.get("impacts")
                    else None
                ),
            )
        numerics = {
            fname: NumericField(
                values=data[f"num.{_fkey(fname)}.values"],
                exists=data[f"num.{_fkey(fname)}.exists"],
            )
            for fname in manifest["numerics"]
        }
        ordinals = {}
        for fname in manifest["ordinals"]:
            key = _fkey(fname)
            ordinals[fname] = OrdinalField(
                ord_terms=_decode_terms(
                    data[f"ord.{key}.terms_blob"], data[f"ord.{key}.term_offsets"]
                ),
                ords=data[f"ord.{key}.ords"],
                mv_ords=data[f"ord.{key}.mv_ords"],
                mv_offsets=data[f"ord.{key}.mv_offsets"],
            )
        vectors = {}
        for fname, meta in manifest["vectors"].items():
            key = meta["key"]
            vf = VectorField(
                vectors=data[f"vec.{key}.vectors"],
                exists=data[f"vec.{key}.exists"],
                similarity=meta["similarity"],
            )
            if vf.similarity == "cosine":
                vf.unit_vectors = _unit_normalize(vf.vectors)
            vectors[fname] = vf
        multi_vectors = {}
        for fname, meta in manifest.get("multi_vectors", {}).items():
            key = meta["key"]
            multi_vectors[fname] = MultiVectorField(
                tok_vectors=data[f"mvec.{key}.tok_vectors"],
                tok_offsets=data[f"mvec.{key}.tok_offsets"],
                exists=data[f"mvec.{key}.exists"],
                similarity=meta["similarity"],
            )
        sparse = {}
        for fname, meta in manifest.get("sparse", {}).items():
            key = meta["key"]
            sparse[fname] = SparseField(
                terms=_decode_terms(
                    data[f"sp.{key}.terms_blob"],
                    data[f"sp.{key}.term_offsets"],
                ),
                term_df=data[f"sp.{key}.term_df"],
                term_tile_start=data[f"sp.{key}.term_tile_start"],
                term_tile_count=data[f"sp.{key}.term_tile_count"],
                doc_ids=data[f"sp.{key}.doc_ids"],
                weights=data[f"sp.{key}.weights"],
                qweights=data[f"sp.{key}.qweights"],
                scales=data[f"sp.{key}.scales"],
                tile_max=data[f"sp.{key}.tile_max"],
                tile_qmax=data[f"sp.{key}.tile_qmax"],
                exists=data[f"sp.{key}.exists"],
                pruned=int(meta.get("pruned", 0)),
            )
        return cls(
            num_docs=manifest["num_docs"],
            doc_ids=docs["doc_ids"],
            sources=docs["sources"],
            postings=postings,
            numerics=numerics,
            ordinals=ordinals,
            vectors=vectors,
            generation=manifest.get("generation", 0),
            multi_vectors=multi_vectors,
            sparse=sparse,
        )


def fsync_path(path: str) -> None:
    """fsync an already-written file by path (durability before commit)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so its entries (renames, new files) are durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fkey(fname: str) -> str:
    return fname.replace("/", "_")


def _encode_terms(terms: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    encoded = [t.encode("utf-8") for t in terms]
    blob = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    offsets = np.zeros(len(terms) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return blob, offsets


def _decode_terms(blob: np.ndarray, offsets: np.ndarray) -> List[str]:
    raw = blob.tobytes()
    return [
        raw[offsets[i] : offsets[i + 1]].decode("utf-8")
        for i in range(len(offsets) - 1)
    ]


def _unit_normalize(vectors: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(vectors, axis=1, keepdims=True)
    return (vectors / np.where(norms == 0, 1.0, norms)).astype(np.float32)


class SegmentBuilder:
    """Builds an immutable Segment from parsed documents (the analog of
    Lucene's DefaultIndexingChain flush)."""

    def __init__(self, mappings: Mappings, generation: int = 0):
        self.mappings = mappings
        self.generation = generation
        self._docs: List[ParsedDocument] = []

    def add(self, doc: ParsedDocument) -> int:
        self._docs.append(doc)
        return len(self._docs) - 1

    def __len__(self) -> int:
        return len(self._docs)

    def build(self) -> Segment:
        docs = self._docs
        n = len(docs)
        postings: Dict[str, PostingsField] = {}
        numerics: Dict[str, NumericField] = {}
        ordinals: Dict[str, OrdinalField] = {}
        vectors: Dict[str, VectorField] = {}

        # ---- indexed text fields → tiled postings with tf + positions ----
        text_fields = sorted({f for d in docs for f in d.text_terms})
        for fname in text_fields:
            inv_pos: Dict[str, Dict[int, List[int]]] = {}
            lengths = np.zeros(n, dtype=np.int64)
            doc_count = 0
            for local_id, d in enumerate(docs):
                terms = d.text_terms.get(fname)
                if not terms:
                    continue
                doc_count += 1
                lengths[local_id] = d.field_lengths.get(fname, len(terms))
                for term, pos in terms:
                    inv_pos.setdefault(term, {}).setdefault(local_id, []).append(
                        pos
                    )
            inv = {
                t: {d: len(ps) for d, ps in pl.items()}
                for t, pl in inv_pos.items()
            }
            pf = self._build_postings(inv, lengths, n, doc_count)
            self._attach_positions(pf, inv_pos)
            mf = self.mappings.get(fname)
            if mf is None or mf.type == TEXT:
                from ..models import bm25

                attach_impacts(
                    pf,
                    bm25.norm_inverse_cache(
                        bm25.avg_field_length(
                            pf.stats.sum_total_term_freq, pf.stats.doc_count
                        )
                    ),
                )
            postings[fname] = pf

        # ---- keyword fields → postings (tf=1) + ordinals ----
        kw_fields = sorted({f for d in docs for f in d.keyword_terms})
        for fname in kw_fields:
            inv = {}
            lengths = np.zeros(n, dtype=np.int64)
            doc_count = 0
            all_vals: List[List[str]] = []
            for local_id, d in enumerate(docs):
                vals = d.keyword_terms.get(fname) or []
                all_vals.append(vals)
                if vals:
                    doc_count += 1
                    lengths[local_id] = len(vals)
                for v in set(vals):
                    inv.setdefault(v, {})[local_id] = 1
            postings[fname] = self._build_postings(inv, lengths, n, doc_count)
            ordinals[fname] = self._build_ordinals(all_vals, n)

        # ---- numeric/date/boolean doc values ----
        num_fields = sorted({f for d in docs for f in d.numeric_values})
        for fname in num_fields:
            values = np.zeros(n, dtype=np.float64)
            exists = np.zeros(n, dtype=bool)
            for local_id, d in enumerate(docs):
                vals = d.numeric_values.get(fname)
                if vals:
                    values[local_id] = vals[0]
                    exists[local_id] = True
            numerics[fname] = NumericField(values=values, exists=exists)

        # ---- dense vectors ----
        vec_fields = sorted({f for d in docs for f in d.vectors})
        for fname in vec_fields:
            mf = self.mappings.get(fname)
            dims = mf.dims if mf else len(next(v for d in docs for f2, v in d.vectors.items() if f2 == fname))
            mat = np.zeros((n, dims), dtype=np.float32)
            exists = np.zeros(n, dtype=bool)
            for local_id, d in enumerate(docs):
                v = d.vectors.get(fname)
                if v is not None:
                    mat[local_id] = np.asarray(v, dtype=np.float32)
                    exists[local_id] = True
            sim = mf.similarity if mf else "cosine"
            vf = VectorField(vectors=mat, exists=exists, similarity=sim)
            if sim == "cosine":
                vf.unit_vectors = _unit_normalize(mat)
            vectors[fname] = vf

        # ---- rank_vectors: per-doc token matrices, flat CSR layout ----
        multi_vectors: Dict[str, MultiVectorField] = {}
        mv_fields = sorted({f for d in docs for f in d.multi_vectors})
        for fname in mv_fields:
            mf = self.mappings.get(fname)
            dims = (
                mf.dims
                if mf and mf.dims
                else len(
                    next(
                        row
                        for d in docs
                        for m in (d.multi_vectors.get(fname),)
                        if m
                        for row in m[:1]
                    )
                )
            )
            sim = mf.similarity if mf else "cosine"
            offsets = np.zeros(n + 1, dtype=np.int32)
            chunks: List[np.ndarray] = []
            exists = np.zeros(n, dtype=bool)
            total = 0
            for local_id, d in enumerate(docs):
                mat = d.multi_vectors.get(fname)
                if mat:
                    arr = np.asarray(mat, dtype=np.float32)
                    if sim == "cosine":
                        arr = _unit_normalize(arr)
                    chunks.append(arr)
                    total += len(arr)
                    exists[local_id] = True
                offsets[local_id + 1] = total
            tok = (
                np.concatenate(chunks, axis=0)
                if chunks
                else np.zeros((0, dims), np.float32)
            )
            multi_vectors[fname] = MultiVectorField(
                tok_vectors=tok,
                tok_offsets=offsets,
                exists=exists,
                similarity=sim,
            )

        # ---- sparse_vector: impact-ordered quantized postings ----
        sparse: Dict[str, SparseField] = {}
        sp_fields = sorted({f for d in docs for f in d.sparse_vectors})
        for fname in sp_fields:
            mf = self.mappings.get(fname)
            ratio = mf.pruning_ratio if mf else 0.0
            inv_w: Dict[str, Dict[int, float]] = {}
            exists = np.zeros(n, dtype=bool)
            for local_id, d in enumerate(docs):
                wmap = d.sparse_vectors.get(fname)
                if not wmap:
                    continue
                exists[local_id] = True
                for term, w in wmap.items():
                    inv_w.setdefault(term, {})[local_id] = float(w)
            plan = sparse_plan(inv_w, ratio)
            sparse[fname] = sparse_from_plan(plan, n, exists)

        return Segment(
            num_docs=n,
            doc_ids=[d.doc_id for d in docs],
            sources=[d.source for d in docs],
            postings=postings,
            numerics=numerics,
            ordinals=ordinals,
            vectors=vectors,
            generation=self.generation,
            multi_vectors=multi_vectors,
            sparse=sparse,
        )

    @staticmethod
    def _build_postings(
        inv: Dict[str, Dict[int, int]], lengths: np.ndarray, n: int, doc_count: int
    ) -> PostingsField:
        terms = sorted(inv)
        n_terms = len(terms)
        term_df = np.zeros(n_terms, dtype=np.int32)
        term_total_tf = np.zeros(n_terms, dtype=np.int64)
        term_tile_start = np.zeros(n_terms, dtype=np.int32)
        term_tile_count = np.zeros(n_terms, dtype=np.int32)

        # norms: SmallFloat-encoded field length per doc (0 where absent)
        norms = encode_norms(lengths)

        tile_rows_doc: List[np.ndarray] = []
        tile_rows_tf: List[np.ndarray] = []
        next_tile = 0
        for tid, term in enumerate(terms):
            plist = inv[term]
            df = len(plist)
            term_df[tid] = df
            term_total_tf[tid] = sum(plist.values())
            d_arr = np.fromiter(sorted(plist), count=df, dtype=np.int32)
            t_arr = np.fromiter((plist[d] for d in d_arr), count=df, dtype=np.int32)
            n_tiles = (df + TILE - 1) // TILE
            pad = n_tiles * TILE - df
            if pad:
                d_arr = np.concatenate([d_arr, np.full(pad, INVALID_DOC, np.int32)])
                t_arr = np.concatenate([t_arr, np.zeros(pad, np.int32)])
            tile_rows_doc.append(d_arr.reshape(n_tiles, TILE))
            tile_rows_tf.append(t_arr.reshape(n_tiles, TILE))
            term_tile_start[tid] = next_tile
            term_tile_count[tid] = n_tiles
            next_tile += n_tiles

        if tile_rows_doc:
            doc_ids = np.concatenate(tile_rows_doc, axis=0)
            tfs = np.concatenate(tile_rows_tf, axis=0)
        else:
            doc_ids = np.full((0, TILE), INVALID_DOC, np.int32)
            tfs = np.zeros((0, TILE), np.int32)

        tile_max_tf = tfs.max(axis=1).astype(np.int32) if len(tfs) else np.zeros(0, np.int32)
        # min norm byte over *valid* postings per tile (255 where padded-only)
        if len(doc_ids):
            valid = doc_ids >= 0
            tile_norms = np.where(valid, norms[np.clip(doc_ids, 0, n - 1 if n else 0)], 255)
            tile_min_norm = tile_norms.min(axis=1).astype(np.uint8)
        else:
            tile_min_norm = np.zeros(0, np.uint8)

        stats = FieldStats(
            doc_count=doc_count,
            sum_total_term_freq=int(term_total_tf.sum()),
            sum_doc_freq=int(term_df.sum()),
        )
        return PostingsField(
            terms=terms,
            term_df=term_df,
            term_total_tf=term_total_tf,
            term_tile_start=term_tile_start,
            term_tile_count=term_tile_count,
            doc_ids=doc_ids,
            tfs=tfs,
            tile_max_tf=tile_max_tf,
            tile_min_norm=tile_min_norm,
            norms=norms,
            stats=stats,
        )

    @staticmethod
    def _attach_positions(
        pf: PostingsField, inv_pos: Dict[str, Dict[int, List[int]]]
    ) -> None:
        """Builds the compact-CSR position arrays aligned with posting
        order: term t's posting k (k-th doc in sorted doc order) owns the
        slice pos_offsets[term_pos_start[t]+k : +1] of pos_data."""
        n_terms = len(pf.terms)
        term_pos_start = np.zeros(n_terms, dtype=np.int64)
        if n_terms > 1:
            np.cumsum(pf.term_df[:-1].astype(np.int64), out=term_pos_start[1:])
        total_postings = int(pf.term_df.sum())
        pos_offsets = np.zeros(total_postings + 1, dtype=np.int64)
        chunks: List[List[int]] = []
        p = 0
        for tid, term in enumerate(pf.terms):
            plist = inv_pos[term]
            for d in sorted(plist):
                ps = sorted(plist[d])
                chunks.append(ps)
                pos_offsets[p + 1] = pos_offsets[p] + len(ps)
                p += 1
        pf.term_pos_start = term_pos_start
        pf.pos_offsets = pos_offsets
        pf.pos_data = (
            np.concatenate([np.asarray(c, np.int32) for c in chunks])
            if chunks
            else np.zeros(0, np.int32)
        )

    @staticmethod
    def _build_ordinals(all_vals: List[List[str]], n: int) -> OrdinalField:
        uniq = sorted({v for vals in all_vals for v in vals})
        ord_of = {v: i for i, v in enumerate(uniq)}
        ords = np.full(n, -1, dtype=np.int32)
        mv_offsets = np.zeros(n + 1, dtype=np.int32)
        mv: List[int] = []
        for i, vals in enumerate(all_vals):
            sorted_ords = sorted(ord_of[v] for v in set(vals))
            if sorted_ords:
                ords[i] = sorted_ords[0]
            mv.extend(sorted_ords)
            mv_offsets[i + 1] = len(mv)
        return OrdinalField(
            ord_terms=uniq,
            ords=ords,
            mv_ords=np.asarray(mv, dtype=np.int32),
            mv_offsets=mv_offsets,
        )
