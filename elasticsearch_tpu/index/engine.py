"""Per-shard write engine: versioned CAS indexing, refresh, flush, merge.

Reference analog: org.elasticsearch.index.engine.InternalEngine — the
orchestration of Lucene's IndexWriter + Translog behind IndexShard
(SURVEY.md §3.2): `InternalEngine.index/delete/get` with per-_id
versioned uniqueness (LiveVersionMap), `refresh` making ops searchable
(NRT reader), `flush` = durable commit + translog trim, sequence numbers
(LocalCheckpointTracker), and recovery replaying the translog tail
(`recoverFromTranslog`).

TPU-native redesign: a "Lucene commit" becomes an atomically-replaced
JSON manifest naming immutable columnar segment directories (the arrays
the device mmaps/uploads), plus per-segment live-doc bitmaps and doc
versions persisted as .npy sidecars. Updates/deletes never mutate a
segment — they flip live_docs bits (soft-deletes) and new doc versions
land in the next refresh's segment, exactly Lucene's delete-and-reinsert
model, which is also what keeps device-resident postings immutable.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis import AnalysisRegistry
from ..common.faults import faults
from ..search.executor import ShardReader
from .mapping import DocumentParser, Mappings
from .segment import Segment
from .translog import (
    DEFAULT_SYNC_INTERVAL,
    DURABILITY_REQUEST,
    Translog,
    bump_durability_stat,
)


class EngineError(Exception):
    pass


class VersionConflictError(EngineError):
    """version_conflict_engine_exception (HTTP 409)."""


@dataclass
class OpResult:
    doc_id: str
    result: str  # created | updated | deleted | not_found | noop
    version: int
    seq_no: int
    primary_term: int


@dataclass
class _VersionEntry:
    version: int
    seq_no: int
    deleted: bool


@dataclass
class _BufferedDoc:
    source: dict
    version: int
    seq_no: int
    parsed: Optional[object] = None  # ParsedDocument, reused by refresh
    ts: float = 0.0  # monotonic ack time — refresh-lag accounting


class ShardEngine:
    """One shard: in-memory indexing buffer + immutable segments + WAL."""

    def __init__(
        self,
        mappings: Mappings,
        analysis: AnalysisRegistry,
        path: Optional[str] = None,
        shard_id: int = 0,
        durability: str = DURABILITY_REQUEST,
        sync_interval: float = DEFAULT_SYNC_INTERVAL,
        primary_term: int = 1,
        codec: str = "default",
        device_build: bool = False,
    ):
        self.mappings = mappings
        self.analysis = analysis
        self.parser = DocumentParser(mappings, analysis)
        self.path = path
        self.shard_id = shard_id
        self.primary_term = primary_term
        self.codec = codec
        # jax-backend indices prefer the device segment-build pipeline
        # (index/segment_build.py; ES_TPU_DEVICE_BUILD still overrides)
        self.device_build = device_build
        self._lock = threading.RLock()
        # serializes refreshes (sync AND concurrent) without blocking
        # writes/reads: the double-buffered build runs outside _lock
        self._refresh_mutex = threading.Lock()
        # bumped by every committed segment-set change (refresh, merge)
        # so a concurrent half-build can detect it was superseded and
        # discard itself instead of installing a duplicate segment
        self._refresh_epoch = 0

        self.segments: List[Segment] = []
        self.live_docs: List[Optional[np.ndarray]] = []
        self.seg_versions: List[np.ndarray] = []  # int64 per-doc version
        self.seg_seqnos: List[np.ndarray] = []  # int64 per-doc seq_no
        self.seg_names: List[str] = []
        self.committed_generation = 0
        self.committed_seq_no = -1

        # live version map: _id → newest (version, seq_no, deleted)
        self._versions: Dict[str, _VersionEntry] = {}
        # _id → (segment index, local doc) for the newest *searchable* copy
        self._locations: Dict[str, Tuple[int, int]] = {}
        # unrefreshed ops, in arrival order per _id (newest wins)
        self._buffer: Dict[str, _BufferedDoc] = {}
        self._buffered_deletes: Dict[str, _VersionEntry] = {}

        self._next_seq = 0
        # an in-memory merge not yet reflected in the on-disk manifest
        self._merge_uncommitted = False
        # bumped whenever the searchable state changes (refresh/merge) —
        # lets callers cache readers/executors per generation
        self.change_generation = 0
        # IndexingStats / RefreshStats / FlushStats / MergeStats counters
        self.op_stats = {
            "index_total": 0,
            "index_time_in_nanos": 0,
            "delete_total": 0,
            "refresh_total": 0,
            "flush_total": 0,
            "merge_total": 0,
        }
        self.translog: Optional[Translog] = None
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._recover(durability, sync_interval)

    # ------------------------------------------------------------------
    # write path (InternalEngine.index / delete)
    # ------------------------------------------------------------------

    def index(
        self,
        doc_id: str,
        source: dict,
        op_type: str = "index",
        if_seq_no: Optional[int] = None,
        if_primary_term: Optional[int] = None,
    ) -> OpResult:
        with self._lock:
            cur = self._versions.get(doc_id)
            exists = cur is not None and not cur.deleted
            if op_type == "create" and exists:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, document already exists "
                    f"(current version [{cur.version}])"
                )
            if if_seq_no is not None or if_primary_term is not None:
                if (
                    cur is None
                    or cur.deleted
                    or (if_seq_no is not None and cur.seq_no != if_seq_no)
                    or (
                        if_primary_term is not None
                        and self.primary_term != if_primary_term
                    )
                ):
                    have = (cur.seq_no, self.primary_term) if cur else (-1, 0)
                    raise VersionConflictError(
                        f"[{doc_id}]: version conflict, required seqNo "
                        f"[{if_seq_no}], primary term [{if_primary_term}], "
                        f"current document has seqNo [{have[0]}] and primary "
                        f"term [{have[1]}]"
                    )
            # parse up front: mapping errors must reject the op, not poison
            # the next refresh — and refresh reuses the parse (analysis is
            # the write path's hot loop; don't pay it twice)
            t0 = _time.perf_counter_ns()
            parsed = self.parser.parse(doc_id, source)
            version = (cur.version + 1) if cur is not None else 1
            seq_no = self._next_seq
            self._next_seq += 1
            self._versions[doc_id] = _VersionEntry(version, seq_no, False)
            self._buffer[doc_id] = _BufferedDoc(
                source, version, seq_no, parsed, ts=_time.monotonic()
            )
            self._buffered_deletes.pop(doc_id, None)
            if self.translog is not None:
                self.translog.add(
                    {
                        "op": "index",
                        "id": doc_id,
                        "source": source,
                        "seq_no": seq_no,
                        "version": version,
                    }
                )
            self.op_stats["index_total"] += 1
            self.op_stats["index_time_in_nanos"] += _time.perf_counter_ns() - t0
            return OpResult(
                doc_id,
                "updated" if exists else "created",
                version,
                seq_no,
                self.primary_term,
            )

    def delete(
        self,
        doc_id: str,
        if_seq_no: Optional[int] = None,
        if_primary_term: Optional[int] = None,
    ) -> OpResult:
        with self._lock:
            cur = self._versions.get(doc_id)
            exists = cur is not None and not cur.deleted
            if if_seq_no is not None and (cur is None or cur.seq_no != if_seq_no):
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict on delete"
                )
            if if_primary_term is not None and self.primary_term != if_primary_term:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict on delete"
                )
            seq_no = self._next_seq
            self._next_seq += 1
            if not exists:
                return OpResult(doc_id, "not_found", 1, seq_no, self.primary_term)
            version = cur.version + 1
            entry = _VersionEntry(version, seq_no, True)
            self._versions[doc_id] = entry
            self._buffer.pop(doc_id, None)
            self._buffered_deletes[doc_id] = entry
            if self.translog is not None:
                self.translog.add(
                    {"op": "delete", "id": doc_id, "seq_no": seq_no, "version": version}
                )
            self.op_stats["delete_total"] += 1
            return OpResult(doc_id, "deleted", version, seq_no, self.primary_term)

    # ------------------------------------------------------------------
    # replica apply (InternalEngine.index on a replica: no CAS — the
    # primary already assigned version+seqno; replicas dedup by seqno,
    # the LiveVersionMap "op came out of order" check)
    # ------------------------------------------------------------------

    def index_replica(
        self, doc_id: str, source: dict, version: int, seq_no: int
    ) -> OpResult:
        with self._lock:
            cur = self._versions.get(doc_id)
            self._next_seq = max(self._next_seq, seq_no + 1)
            if cur is not None and cur.seq_no >= seq_no:
                return OpResult(doc_id, "noop", cur.version, cur.seq_no,
                                self.primary_term)
            parsed = self.parser.parse(doc_id, source)
            self._versions[doc_id] = _VersionEntry(version, seq_no, False)
            self._buffer[doc_id] = _BufferedDoc(
                source, version, seq_no, parsed, ts=_time.monotonic()
            )
            self._buffered_deletes.pop(doc_id, None)
            if self.translog is not None:
                self.translog.add(
                    {
                        "op": "index",
                        "id": doc_id,
                        "source": source,
                        "seq_no": seq_no,
                        "version": version,
                    }
                )
            self.op_stats["index_total"] += 1
            return OpResult(doc_id, "created", version, seq_no, self.primary_term)

    def delete_replica(self, doc_id: str, version: int, seq_no: int) -> OpResult:
        with self._lock:
            cur = self._versions.get(doc_id)
            self._next_seq = max(self._next_seq, seq_no + 1)
            if cur is not None and cur.seq_no >= seq_no:
                return OpResult(doc_id, "noop", cur.version, cur.seq_no,
                                self.primary_term)
            entry = _VersionEntry(version, seq_no, True)
            self._versions[doc_id] = entry
            self._buffer.pop(doc_id, None)
            self._buffered_deletes[doc_id] = entry
            if self.translog is not None:
                self.translog.add(
                    {"op": "delete", "id": doc_id, "seq_no": seq_no,
                     "version": version}
                )
            self.op_stats["delete_total"] += 1
            return OpResult(doc_id, "deleted", version, seq_no, self.primary_term)

    # ------------------------------------------------------------------
    # read path (Engine.get — realtime)
    # ------------------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> Optional[dict]:
        with self._lock:
            cur = self._versions.get(doc_id)
            if realtime:
                if cur is None or cur.deleted:
                    return None
                buf = self._buffer.get(doc_id)
                if buf is not None:
                    return {
                        "_id": doc_id,
                        "_version": buf.version,
                        "_seq_no": buf.seq_no,
                        "_primary_term": self.primary_term,
                        "_source": buf.source,
                    }
            loc = self._locations.get(doc_id)
            if loc is None:
                return None
            si, local = loc
            live = self.live_docs[si]
            if live is not None and not live[local]:
                return None
            return {
                "_id": doc_id,
                "_version": int(self.seg_versions[si][local]),
                "_seq_no": int(self.seg_seqnos[si][local]),
                "_primary_term": self.primary_term,
                "_source": self.segments[si].sources[local],
            }

    # ------------------------------------------------------------------
    # refresh (make buffered ops searchable)
    # ------------------------------------------------------------------

    def _apply_stale_flips(self) -> bool:
        """Applies buffered deletes/updates to older segments via
        live_docs bits (caller holds self._lock). Returns True when any
        bit flipped."""
        changed = False
        stale = list(self._buffer) + list(self._buffered_deletes)
        for doc_id in stale:
            loc = self._locations.get(doc_id)
            if loc is None:
                continue
            si, local = loc
            if self.live_docs[si] is None:
                self.live_docs[si] = np.ones(
                    self.segments[si].num_docs, dtype=bool
                )
            if self.live_docs[si][local]:
                self.live_docs[si][local] = False
                changed = True
            if doc_id in self._buffered_deletes:
                self._locations.pop(doc_id, None)
        self._buffered_deletes.clear()
        return changed

    def _build_from_items(self, items):
        """(segment, versions, seqnos) for a captured buffer snapshot —
        the heavy step; safe to run outside self._lock (the captured
        _BufferedDoc entries are immutable). Routed through the
        device/host segment-build pipeline (index/segment_build.py)."""
        from . import segment_build

        docs = [
            buf.parsed
            if buf.parsed is not None
            else self.parser.parse(doc_id, buf.source)
            for doc_id, buf in items
        ]
        seg = segment_build.build_segment(
            self.mappings,
            docs,
            shard_id=self.shard_id,
            prefer_device=self.device_build,
        )
        versions = np.asarray([buf.version for _, buf in items], np.int64)
        seqnos = np.asarray([buf.seq_no for _, buf in items], np.int64)
        return seg, versions, seqnos

    def _note_refresh_lag(self, items) -> None:
        from . import segment_build

        ts = [buf.ts for _, buf in items if buf.ts > 0.0]
        if ts:
            segment_build.note_refresh_lag(
                (_time.monotonic() - min(ts)) * 1000.0
            )

    def refresh(self) -> bool:
        """Builds a new segment from the buffer; returns True if one was
        created or deletes were applied. Blocking variant: the build
        runs under the engine lock (flush/recovery/REST `_refresh` call
        this; the background refresher uses `refresh_concurrent`)."""
        from . import segment_build

        with self._lock:
            # crash here = power loss with the buffer un-refreshed: the
            # translog already holds every acked op, so recovery replays
            faults.check("engine.refresh", shard=self.shard_id)
            changed = self._apply_stale_flips()
            items = list(self._buffer.items())
            if items:
                seg, versions, seqnos = self._build_from_items(items)
                si = len(self.segments)
                for local, (doc_id, _buf) in enumerate(items):
                    self._locations[doc_id] = (si, local)
                self.segments.append(seg)
                self.live_docs.append(None)
                self.seg_versions.append(versions)
                self.seg_seqnos.append(seqnos)
                self.seg_names.append(f"seg_{self.committed_generation}_{si}")
                self._buffer.clear()
                self._note_refresh_lag(items)
                changed = True
            if changed:
                self.change_generation += 1
                self._refresh_epoch += 1
                self.op_stats["refresh_total"] += 1
                segment_build.note("refreshes")
            return changed

    def refresh_concurrent(self) -> bool:
        """Double-buffered NRT refresh: the next generation's segment
        builds OUTSIDE the engine lock — writes keep landing in the
        buffer and searches keep serving the current generation — and
        the swap is one atomic generation bump under the lock. A
        mid-build failure (injected `engine.refresh`/`build.device`
        fault, device error) discards the half-build and keeps the old
        generation serving; ops stay in the buffer (and the translog)
        for the next cycle. An explicit refresh/merge landing during
        the build supersedes it (epoch check) — the half-build is
        discarded, never installed twice. Writes captured in the
        snapshot but superseded during the build (newer version or
        delete) install dead-on-arrival via the new segment's live
        bitmap, so the swap can never resurrect an overwritten doc."""
        from . import segment_build

        with self._refresh_mutex:
            with self._lock:
                faults.check("engine.refresh", shard=self.shard_id)
                flips = self._apply_stale_flips()
                items = list(self._buffer.items())
                epoch = self._refresh_epoch
                if not items:
                    if flips:
                        self.change_generation += 1
                        self._refresh_epoch += 1
                        self.op_stats["refresh_total"] += 1
                        segment_build.note("refreshes")
                    return flips
            t0 = _time.perf_counter()
            try:
                seg, versions, seqnos = self._build_from_items(items)
            except BaseException:
                # half-build discarded; the flips (acked deletes) still
                # become visible so a failed build can't extend their
                # invisibility window
                segment_build.note("generations_discarded")
                with self._lock:
                    if flips and self._refresh_epoch == epoch:
                        self.change_generation += 1
                        self._refresh_epoch += 1
                raise
            segment_build.note(
                "overlap_ms", (_time.perf_counter() - t0) * 1000.0
            )
            with self._lock:
                if self._refresh_epoch != epoch:
                    # a blocking refresh/merge swapped mid-build: its
                    # segment already holds these ops — discard ours
                    segment_build.note("generations_discarded")
                    return True
                si = len(self.segments)
                live = None
                for local, (doc_id, buf) in enumerate(items):
                    cur_buf = self._buffer.get(doc_id)
                    if cur_buf is not None and cur_buf.seq_no == buf.seq_no:
                        del self._buffer[doc_id]
                    cur = self._versions.get(doc_id)
                    if (
                        cur is not None
                        and cur.seq_no == buf.seq_no
                        and not cur.deleted
                    ):
                        self._locations[doc_id] = (si, local)
                    else:
                        # superseded during the build: dead on arrival
                        if live is None:
                            live = np.ones(len(items), dtype=bool)
                        live[local] = False
                self.segments.append(seg)
                self.live_docs.append(live)
                self.seg_versions.append(versions)
                self.seg_seqnos.append(seqnos)
                self.seg_names.append(f"seg_{self.committed_generation}_{si}")
                self._note_refresh_lag(items)
                self.change_generation += 1
                self._refresh_epoch += 1
                self.op_stats["refresh_total"] += 1
                segment_build.note("refreshes")
                segment_build.note("concurrent_refreshes")
            return True

    @property
    def dirty(self) -> bool:
        """True when a refresh would change the searchable state."""
        return bool(self._buffer) or bool(self._buffered_deletes)

    # ------------------------------------------------------------------
    # flush (durable commit) & merge
    # ------------------------------------------------------------------

    def flush(self) -> None:
        """Refresh + persist segments + atomic manifest commit + translog
        trim (IndexShard.flush → Lucene commit + trimUnreferencedReaders).

        Crash-safe commit protocol (the reference fsyncs every segment
        file before the commit point and never mutates committed files):
          1. every new segment dir is fully written AND fsynced first
             (versions/seqnos sidecars are immutable per segment and are
             written exactly once, with the segment);
          2. mutable live-doc bitmaps go to fresh per-generation names
             (``live-<gen>.npy``) — committed files are never rewritten;
          3. the manifest referencing them is atomically replaced and the
             shard directory fsynced;
          4. only then is the translog trimmed and old files GC'd.
        A power loss at any step leaves either the old commit (all its
        files untouched) or the new one (all its files durable)."""
        with self._lock:
            faults.check("engine.flush", shard=self.shard_id, stage="start")
            self.refresh()
            self.op_stats["flush_total"] += 1
            if self.path is None:
                return
            if (
                not self._merge_uncommitted
                and self.committed_seq_no == self._next_seq - 1
                and os.path.exists(os.path.join(self.path, "manifest.json"))
            ):
                # nothing since the last commit — idempotent flush, the
                # manifest (and thus snapshot blobs) stays byte-identical
                return
            from .segment import fsync_dir, fsync_path

            self.committed_generation += 1
            gen = self.committed_generation
            if self.translog is not None:
                self.translog.roll_generation()
            seg_entries = []
            for si, seg in enumerate(self.segments):
                name = self.seg_names[si]
                seg_dir = os.path.join(self.path, name)
                sentinel = os.path.join(seg_dir, "segment.json")
                if os.path.exists(sentinel):
                    # a crashed earlier flush can leave a SAME-NAMED dir
                    # holding a different segmentation (recovery rebuilds
                    # the replayed buffer as one segment, reusing low
                    # indices) — committing the manifest over the stale
                    # dir would silently lose acked docs. Verify the
                    # sentinel actually describes THIS segment; torn or
                    # mismatched dirs are quarantined and rewritten.
                    try:
                        with open(sentinel, encoding="utf-8") as f:
                            ondisk = json.load(f)
                        stale = int(ondisk.get("num_docs", -1)) != seg.num_docs
                    except (OSError, ValueError):
                        stale = True
                    if stale:
                        shutil.rmtree(seg_dir, ignore_errors=True)
                        bump_durability_stat("quarantined_segments")
                if not os.path.exists(sentinel):
                    # sidecars FIRST: segment.json is the "segment fully
                    # persisted" sentinel (checked above), so everything
                    # it references must be durable before seg.save
                    # atomically commits it — otherwise a crash between
                    # the two leaves a sentinel whose sidecars are torn
                    # and the skip branch would never repair them
                    os.makedirs(seg_dir, exist_ok=True)
                    np.save(
                        os.path.join(seg_dir, "versions.npy"),
                        self.seg_versions[si],
                    )
                    np.save(
                        os.path.join(seg_dir, "seqnos.npy"), self.seg_seqnos[si]
                    )
                    fsync_path(os.path.join(seg_dir, "versions.npy"))
                    fsync_path(os.path.join(seg_dir, "seqnos.npy"))
                    # fsyncs its files + dir, commits segment.json last
                    seg.save(seg_dir, codec=self.codec)
                live = self.live_docs[si]
                live_gen = None
                if live is not None:
                    live_gen = gen
                    live_path = os.path.join(seg_dir, f"live-{gen}.npy")
                    np.save(live_path, live)
                    fsync_path(live_path)
                    fsync_dir(seg_dir)
                seg_entries.append({"name": name, "live_gen": live_gen})
            committed_seq = self._next_seq - 1
            manifest = {
                "format_version": 2,
                "generation": gen,
                "segments": seg_entries,
                "max_seq_no": committed_seq,
                "primary_term": self.primary_term,
            }
            # every segment file is durable but the commit point is not:
            # a crash here must recover the PREVIOUS commit + WAL replay
            faults.check("engine.flush", shard=self.shard_id,
                         stage="pre_manifest")
            tmp = os.path.join(self.path, "manifest.json.tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.path, "manifest.json"))
            fsync_dir(self.path)
            # the commit is durable; the translog is not yet trimmed — a
            # crash here recovers from the NEW commit (replay skips ops
            # its max_seq_no covers) and the next flush re-trims
            faults.check("engine.flush", shard=self.shard_id,
                         stage="post_manifest")
            self.committed_seq_no = committed_seq
            self._merge_uncommitted = False
            if self.translog is not None:
                self.translog.trim_unreferenced(committed_seq)
            self._gc_segments(seg_entries)

    def _gc_segments(self, referenced: List[dict]) -> None:
        assert self.path is not None
        keep = {e["name"] for e in referenced} | {"translog"}
        live_gens = {e["name"]: e["live_gen"] for e in referenced}
        for fname in os.listdir(self.path):
            full = os.path.join(self.path, fname)
            if not os.path.isdir(full):
                continue
            if fname not in keep:
                shutil.rmtree(full, ignore_errors=True)
                continue
            # drop superseded per-generation live bitmaps
            want = live_gens.get(fname)
            for sub in os.listdir(full):
                if sub.startswith("live-") and sub.endswith(".npy"):
                    g = sub[len("live-") : -len(".npy")]
                    if not g.isdigit() or want is None or int(g) != want:
                        try:
                            os.remove(os.path.join(full, sub))
                        except OSError:
                            pass
                elif sub == "live.npy" and want is not None:
                    # pre-format-v2 mutable bitmap superseded by live-<gen>
                    try:
                        os.remove(os.path.join(full, sub))
                    except OSError:
                        pass

    def maybe_merge(self, max_segments: int = 8) -> bool:
        """Segment-count merge policy (TieredMergePolicy, crudely): when
        the shard accumulates more than ``max_segments`` segments, rebuild
        all live docs into one. Columnar segments can't be concatenated
        (term dictionaries and norms are per-segment), so a merge re-parses
        retained sources — the analog of Lucene rewriting merged segments."""
        with self._lock:
            if len(self.segments) <= max_segments:
                return False
            # crash here = power loss mid-merge: nothing on disk moved
            # yet (the merge result only becomes durable at flush)
            faults.check("engine.merge", shard=self.shard_id)
            from . import segment_build

            docs = []
            versions: List[int] = []
            seqnos: List[int] = []
            new_locations: Dict[str, Tuple[int, int]] = {}
            local = 0
            for si, seg in enumerate(self.segments):
                live = self.live_docs[si]
                for d in range(seg.num_docs):
                    if live is not None and not live[d]:
                        continue
                    doc_id = seg.doc_ids[d]
                    docs.append(self.parser.parse(doc_id, seg.sources[d]))
                    versions.append(int(self.seg_versions[si][d]))
                    seqnos.append(int(self.seg_seqnos[si][d]))
                    new_locations[doc_id] = (0, local)
                    local += 1
            # merges are the biggest builds of all — they ride the same
            # device/host build pipeline as refresh
            merged = segment_build.build_segment(
                self.mappings, docs, shard_id=self.shard_id,
                prefer_device=self.device_build,
            )
            self.segments = [merged]
            self.live_docs = [None]
            self.seg_versions = [np.asarray(versions, np.int64)]
            self.seg_seqnos = [np.asarray(seqnos, np.int64)]
            self.seg_names = [f"seg_{self.committed_generation}_m0"]
            self._locations = new_locations
            self.change_generation += 1
            # a merge rewrites the segment list: any concurrent refresh
            # build captured before it must discard itself
            self._refresh_epoch += 1
            self.op_stats["merge_total"] += 1
            self._merge_uncommitted = True
            return True

    def merge_concurrent(self, max_segments: int = 8) -> bool:
        """Double-buffered merge: same policy as `maybe_merge`, but the
        merged segment — the biggest build a shard ever does — runs
        OUTSIDE the engine lock, so writes keep landing in the buffer
        and searches keep serving the current generation while it
        builds. The swap is one atomic generation bump under the lock,
        guarded by the same epoch check as `refresh_concurrent`: any
        refresh or merge that swapped mid-build supersedes this one
        (the half-build is discarded; the next tick re-evaluates the
        policy against the NEW segment list). Docs captured in the
        snapshot but superseded during the build (newer version or
        delete) install dead-on-arrival via the merged segment's live
        bitmap. Holds `_refresh_mutex` for the duration, so a merge
        delays the next background refresh but never blocks the write
        path — that is the pacing bound tier-1 gates."""
        from . import segment_build

        with self._refresh_mutex:
            with self._lock:
                if len(self.segments) <= max_segments:
                    return False
                # crash here = power loss mid-merge: nothing on disk
                # moved yet (the result only becomes durable at flush)
                faults.check("engine.merge", shard=self.shard_id)
                epoch = self._refresh_epoch
                rows: List[Tuple[str, str, int, int]] = []
                for si, seg in enumerate(self.segments):
                    live = self.live_docs[si]
                    for d in range(seg.num_docs):
                        if live is not None and not live[d]:
                            continue
                        rows.append(
                            (
                                seg.doc_ids[d],
                                seg.sources[d],
                                int(self.seg_versions[si][d]),
                                int(self.seg_seqnos[si][d]),
                            )
                        )
            t0 = _time.perf_counter()
            try:
                docs = [
                    self.parser.parse(doc_id, src)
                    for doc_id, src, _v, _s in rows
                ]
                merged = segment_build.build_segment(
                    self.mappings, docs, shard_id=self.shard_id,
                    prefer_device=self.device_build,
                )
            except BaseException:
                # half-build discarded; the old segment list keeps
                # serving and the policy retries next tick
                segment_build.note("generations_discarded")
                raise
            segment_build.note(
                "overlap_ms", (_time.perf_counter() - t0) * 1000.0
            )
            with self._lock:
                if self._refresh_epoch != epoch:
                    # a refresh/merge swapped mid-build: the segment
                    # list we merged no longer exists — discard
                    segment_build.note("generations_discarded")
                    return False
                live = None
                new_locations: Dict[str, Tuple[int, int]] = {}
                for local, (doc_id, _src, _v, seq) in enumerate(rows):
                    cur = self._versions.get(doc_id)
                    if (
                        cur is not None
                        and cur.seq_no == seq
                        and not cur.deleted
                    ):
                        new_locations[doc_id] = (0, local)
                    else:
                        # superseded during the build: dead on arrival
                        if live is None:
                            live = np.ones(len(rows), dtype=bool)
                        live[local] = False
                self.segments = [merged]
                self.live_docs = [live]
                self.seg_versions = [
                    np.asarray([v for _i, _s, v, _q in rows], np.int64)
                ]
                self.seg_seqnos = [
                    np.asarray([q for _i, _s, _v, q in rows], np.int64)
                ]
                self.seg_names = [f"seg_{self.committed_generation}_m0"]
                self._locations = new_locations
                self.change_generation += 1
                self._refresh_epoch += 1
                self.op_stats["merge_total"] += 1
                self._merge_uncommitted = True
                segment_build.note("concurrent_merges")
            return True

    # ------------------------------------------------------------------
    # recovery (open an existing shard directory)
    # ------------------------------------------------------------------

    def _recover(self, durability: str,
                 sync_interval: float = DEFAULT_SYNC_INTERVAL) -> None:
        assert self.path is not None

        manifest_path = os.path.join(self.path, "manifest.json")
        # a crash between the manifest tmp-write and its os.replace
        # leaves manifest.json.tmp behind; remove it before anything
        # else can mistake it for state
        tmp_manifest = manifest_path + ".tmp"
        if os.path.exists(tmp_manifest):
            try:
                os.remove(tmp_manifest)
                bump_durability_stat("orphan_manifests_removed")
            except OSError:
                pass
        committed_seq = -1
        manifest = None
        if os.path.exists(manifest_path):
            with open(manifest_path, encoding="utf-8") as f:
                manifest = json.load(f)
        # quarantine segment directories the commit does NOT reference:
        # they are partially-written leftovers of a crashed flush. Left
        # in place, a post-replay flush could collide with a stale
        # same-named dir and commit a manifest over the WRONG bytes —
        # the replayed ops re-materialize their docs, so deleting the
        # orphans loses nothing.
        referenced = set()
        if manifest is not None:
            for entry in manifest["segments"]:
                referenced.add(entry if isinstance(entry, str)
                               else entry["name"])
        for fname in os.listdir(self.path):
            full = os.path.join(self.path, fname)
            if not os.path.isdir(full) or fname == "translog":
                continue
            if fname not in referenced:
                shutil.rmtree(full, ignore_errors=True)
                bump_durability_stat("quarantined_segments")
        if manifest is not None:
            self.committed_generation = manifest["generation"]
            committed_seq = manifest["max_seq_no"]
            self.primary_term = manifest.get("primary_term", self.primary_term)
            for si, entry in enumerate(manifest["segments"]):
                if isinstance(entry, str):  # format_version 1
                    name, live_gen = entry, None
                else:
                    name, live_gen = entry["name"], entry.get("live_gen")
                seg_dir = os.path.join(self.path, name)
                seg = Segment.load(seg_dir)
                self.segments.append(seg)
                self.seg_names.append(name)
                self.seg_versions.append(
                    np.load(os.path.join(seg_dir, "versions.npy"))
                )
                self.seg_seqnos.append(np.load(os.path.join(seg_dir, "seqnos.npy")))
                if live_gen is not None:
                    live_path = os.path.join(seg_dir, f"live-{live_gen}.npy")
                else:
                    live_path = os.path.join(seg_dir, "live.npy")
                self.live_docs.append(
                    np.load(live_path) if os.path.exists(live_path) else None
                )
            # rebuild the version map from segments (newest segment wins)
            for si, seg in enumerate(self.segments):
                live = self.live_docs[si]
                for d, doc_id in enumerate(seg.doc_ids):
                    if live is not None and not live[d]:
                        continue
                    self._locations[doc_id] = (si, d)
                    self._versions[doc_id] = _VersionEntry(
                        int(self.seg_versions[si][d]),
                        int(self.seg_seqnos[si][d]),
                        False,
                    )
        self.committed_seq_no = committed_seq
        self._next_seq = committed_seq + 1
        self.translog = Translog(
            os.path.join(self.path, "translog"),
            durability=durability,
            sync_interval=sync_interval,
            shard_id=self.shard_id,
        )
        # replay the translog tail (ops newer than the commit)
        replayed = 0
        for op in self.translog.read_ops_after(committed_seq):
            seq_no = op["seq_no"]
            self._next_seq = max(self._next_seq, seq_no + 1)
            doc_id = op["id"]
            if op["op"] == "index":
                self._versions[doc_id] = _VersionEntry(op["version"], seq_no, False)
                self._buffer[doc_id] = _BufferedDoc(op["source"], op["version"], seq_no)
                self._buffered_deletes.pop(doc_id, None)
            else:
                entry = _VersionEntry(op["version"], seq_no, True)
                self._versions[doc_id] = entry
                self._buffer.pop(doc_id, None)
                self._buffered_deletes[doc_id] = entry
            replayed += 1
        if replayed:
            bump_durability_stat("replayed_ops", replayed)
            bump_durability_stat("tail_replays")
            self.refresh()

    # ------------------------------------------------------------------
    # readers & stats
    # ------------------------------------------------------------------

    def reader(self) -> ShardReader:
        """Point-in-time snapshot of the searchable state (live_docs are
        copied so concurrent deletes don't mutate an open reader)."""
        with self._lock:
            return ShardReader(
                list(self.segments),
                self.mappings,
                self.analysis,
                [None if l is None else l.copy() for l in self.live_docs],
            )

    @property
    def num_docs(self) -> int:
        with self._lock:
            n = 0
            for si, seg in enumerate(self.segments):
                live = self.live_docs[si]
                n += seg.num_docs if live is None else int(live.sum())
            return n

    @property
    def max_seq_no(self) -> int:
        return self._next_seq - 1

    def translog_stats(self) -> dict:
        """The per-shard slice of the `_nodes/stats` translog block."""
        with self._lock:
            out = {
                "uncommitted_ops": max(
                    0, (self._next_seq - 1) - self.committed_seq_no
                ),
                "uncommitted_bytes": 0,
                "last_fsync_age_ms": None,
                "pending_ops": 0,
                "durability": None,
            }
            if self.translog is not None:
                tl = self.translog.stats()
                out["uncommitted_bytes"] = tl["uncommitted_bytes"]
                out["last_fsync_age_ms"] = tl["last_fsync_age_ms"]
                out["pending_ops"] = tl["pending_ops"]
                out["durability"] = tl["durability"]
            return out

    def close(self) -> None:
        with self._lock:
            if self.translog is not None:
                self.translog.close()

    def crash(self) -> None:
        """Simulated power loss (the durability harness's teardown): NO
        flush, NO refresh, NO translog sync — the translog drops its
        acked-but-unfsynced tail exactly like the page cache on a dead
        box, and the in-memory state is abandoned. Reopening the same
        path afterwards exercises the real recovery path."""
        with self._lock:
            if self.translog is not None:
                self.translog.crash()
