"""Per-shard durable write-ahead log (the translog).

Reference analog: org.elasticsearch.index.translog — `Translog.add`
appends every accepted operation before it is acknowledged,
`index.translog.durability` selects fsync-per-request vs async,
generations roll at flush and are trimmed once a Lucene commit covers
their sequence numbers, and an atomic `Checkpoint` file records the
durable state (server/.../index/translog/Translog.java, Checkpoint.java).

TPU-native redesign notes: ops are JSON-lines (host-side durability is
CPU work; there is no device involvement), one file per generation
(``translog-<gen>.log``), with an atomically-replaced ``translog.ckp``
holding {generation, min_retained_seq_no}. Recovery replays every op
with seq_no > the commit's max_seq_no (InternalEngine#recoverFromTranslog
analog in engine.py).
"""

from __future__ import annotations

import json
import os
import time as _time
from typing import Iterator, Optional

DURABILITY_REQUEST = "request"  # fsync before ack (default)
DURABILITY_ASYNC = "async"  # fsync at most sync_interval behind
DEFAULT_SYNC_INTERVAL = 5.0  # index.translog.sync_interval default (5s)


class Translog:
    def __init__(
        self,
        path: str,
        durability: str = DURABILITY_REQUEST,
        sync_interval: float = DEFAULT_SYNC_INTERVAL,
    ):
        self.dir = path
        self.durability = durability
        self.sync_interval = sync_interval
        os.makedirs(path, exist_ok=True)
        ckp = self._read_checkpoint()
        self.generation = ckp.get("generation", 1)
        self.min_retained_seq_no = ckp.get("min_retained_seq_no", 0)
        self._file = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._ops_in_gen = 0
        self._last_sync = _time.monotonic()

    # ---- paths ----

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _ckp_path(self) -> str:
        return os.path.join(self.dir, "translog.ckp")

    def _read_checkpoint(self) -> dict:
        try:
            with open(self._ckp_path(), encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_checkpoint(self) -> None:
        tmp = self._ckp_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "generation": self.generation,
                    "min_retained_seq_no": self.min_retained_seq_no,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckp_path())

    # ---- write path ----

    def add(self, op: dict) -> None:
        """Appends one operation (must carry ``seq_no``).

        ``async`` durability bounds the acked-but-volatile window to
        ``sync_interval`` (index.translog.sync_interval, default 5s) by
        checking the clock on every append — no timer thread, but an
        actively-written shard fsyncs at least every interval; an idle
        shard's tail syncs at the next op, roll, or close."""
        self._file.write(json.dumps(op, separators=(",", ":")) + "\n")
        if self.durability == DURABILITY_REQUEST:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._last_sync = _time.monotonic()
        elif _time.monotonic() - self._last_sync >= self.sync_interval:
            self.sync()
        self._ops_in_gen += 1

    def sync(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())
        self._last_sync = _time.monotonic()

    # ---- generations ----

    def roll_generation(self) -> None:
        """Starts a new generation (called by flush before commit)."""
        self.sync()
        self._file.close()
        self.generation += 1
        self._file = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self._ops_in_gen = 0
        self._write_checkpoint()

    def trim_unreferenced(self, committed_seq_no: int) -> None:
        """Deletes generations whose ops are all covered by the commit."""
        self.min_retained_seq_no = committed_seq_no + 1
        self._write_checkpoint()
        for fname in os.listdir(self.dir):
            if not fname.startswith("translog-"):
                continue
            gen = int(fname[len("translog-") : -len(".log")])
            if gen >= self.generation:
                continue
            path = os.path.join(self.dir, fname)
            keep = False
            for op in self._read_ops(path):
                if op.get("seq_no", -1) > committed_seq_no:
                    keep = True
                    break
            if not keep:
                os.remove(path)

    # ---- recovery ----

    @staticmethod
    def _read_ops(path: str) -> Iterator[dict]:
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        return  # torn tail write: stop at corruption
        except FileNotFoundError:
            return

    def read_ops_after(self, seq_no: int) -> Iterator[dict]:
        """All ops with seq_no > the given value, in log order."""
        gens = sorted(
            int(f[len("translog-") : -len(".log")])
            for f in os.listdir(self.dir)
            if f.startswith("translog-")
        )
        self.sync()
        for gen in gens:
            for op in self._read_ops(self._gen_path(gen)):
                if op.get("seq_no", -1) > seq_no:
                    yield op

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._file.close()
