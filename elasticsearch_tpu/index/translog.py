"""Per-shard durable write-ahead log (the translog).

Reference analog: org.elasticsearch.index.translog — `Translog.add`
appends every accepted operation before it is acknowledged,
`index.translog.durability` selects fsync-per-request vs async,
generations roll at flush and are trimmed once a Lucene commit covers
their sequence numbers, and an atomic `Checkpoint` file records the
durable state (server/.../index/translog/Translog.java, Checkpoint.java).

TPU-native redesign notes: ops are JSON-lines (host-side durability is
CPU work; there is no device involvement), one file per generation
(``translog-<gen>.log``), with an atomically-replaced ``translog.ckp``
holding {generation, min_retained_seq_no}. Recovery replays every op
with seq_no > the commit's max_seq_no (InternalEngine#recoverFromTranslog
analog in engine.py).

Crash model (round 11): the log file is opened UNBUFFERED and every
record goes through an explicit in-memory pending tail — a byte only
counts as durable once `sync()` has written AND fsynced it. `request`
durability syncs inside every `add`; `async` lets the pending tail ride
until `sync_interval` elapses. A simulated power loss (`crash()`, driven
by the ``crash`` fault kind in common/faults.py) drops the pending tail
on the floor, exactly what the page cache loses when the box dies — so
the acked-but-volatile window of `async` mode is a REAL, testable loss
window instead of an accident of Python buffering.

Reopen hardening (round 11): `__init__` now (1) removes an orphaned
``translog.ckp.tmp`` left by a crash between checkpoint write and
`os.replace`, (2) deletes stale ``translog-<gen>.log`` files NEWER than
the checkpointed generation (a crash inside `roll_generation` between
new-file creation and checkpoint write leaves one; it holds no acked
ops), and (3) TRUNCATES a torn trailing record in the active generation
— previously a reopen appended after the garbage, so `_read_ops`
stopped at the corruption and silently dropped every later op in that
generation. All three are counted in the durability stats block.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from typing import Iterator, List, Optional

from ..common.faults import SimulatedCrash, faults

DURABILITY_REQUEST = "request"  # fsync before ack (default)
DURABILITY_ASYNC = "async"  # fsync at most sync_interval behind
DEFAULT_SYNC_INTERVAL = 5.0  # index.translog.sync_interval default (5s)


# ---------------------------------------------------------------------------
# process-wide durability counters (the `translog`/`recovery` blocks of
# `_nodes/stats`; tests and scripts/durability_smoke.sh read them too).
# Kept here — translog.py has no heavy imports, so engine.py, node.py
# and rest/actions.py can all use it without cycles.
# ---------------------------------------------------------------------------

_DSTATS_LOCK = threading.Lock()

_DSTATS_ZERO = {
    # translog hygiene
    "torn_tails_truncated": 0,
    "torn_bytes_dropped": 0,
    "orphan_checkpoints_removed": 0,
    "orphan_manifests_removed": 0,
    "stale_generations_removed": 0,
    "translog_fsyncs": 0,
    "translog_appended_ops": 0,
    # engine recovery
    "replayed_ops": 0,
    "tail_replays": 0,
    "quarantined_segments": 0,
    # peer recovery (cluster/node.py)
    "recoveries_started": 0,
    "recoveries_completed": 0,
    "recoveries_failed": 0,
    "recovery_retries": 0,
    "recovered_files": 0,
    "recovered_ops": 0,
    "finalize_redelivered": 0,
}

DURABILITY_STATS = dict(_DSTATS_ZERO)


def bump_durability_stat(key: str, n: int = 1) -> None:
    with _DSTATS_LOCK:
        DURABILITY_STATS[key] = DURABILITY_STATS.get(key, 0) + n


def durability_stats_snapshot() -> dict:
    with _DSTATS_LOCK:
        return dict(DURABILITY_STATS)


def reset_durability_stats() -> None:
    with _DSTATS_LOCK:
        DURABILITY_STATS.clear()
        DURABILITY_STATS.update(_DSTATS_ZERO)


class Translog:
    def __init__(
        self,
        path: str,
        durability: str = DURABILITY_REQUEST,
        sync_interval: float = DEFAULT_SYNC_INTERVAL,
        shard_id: int = 0,
    ):
        self.dir = path
        self.durability = durability
        self.sync_interval = sync_interval
        self.shard_id = shard_id
        os.makedirs(path, exist_ok=True)
        self._cleanup_orphan_checkpoint()
        ckp = self._read_checkpoint()
        self.generation = ckp.get("generation", 1)
        self.min_retained_seq_no = ckp.get("min_retained_seq_no", 0)
        self._cleanup_stale_generations()
        self._truncate_torn_tail(self._gen_path(self.generation))
        # unbuffered: what `_file.write` returns from is ON DISK (modulo
        # fsync); the acked-but-volatile window lives in _pending, never
        # in an invisible Python buffer
        self._file = open(self._gen_path(self.generation), "ab", buffering=0)
        self._pending: List[bytes] = []  # appended, not yet written+fsynced
        self._ops_in_gen = 0
        self._last_sync = _time.monotonic()
        # highest seq_no known written+fsynced THIS session (the async
        # durability bound the crash harness asserts against)
        self.last_synced_seq_no = -1
        self._max_seq_appended = -1
        # approximate WAL bytes not yet covered by a commit (reset when
        # the commit trims generations)
        self.bytes_since_trim = 0

    # ---- paths ----

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _ckp_path(self) -> str:
        return os.path.join(self.dir, "translog.ckp")

    def _read_checkpoint(self) -> dict:
        try:
            with open(self._ckp_path(), encoding="utf-8") as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return {}

    def _write_checkpoint(self) -> None:
        tmp = self._ckp_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "generation": self.generation,
                    "min_retained_seq_no": self.min_retained_seq_no,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckp_path())

    # ---- reopen hygiene ----

    def _cleanup_orphan_checkpoint(self) -> None:
        """A crash between the checkpoint tmp-write and its os.replace
        leaves translog.ckp.tmp behind; it must not confuse the next
        recovery (the committed .ckp is the only truth)."""
        tmp = self._ckp_path() + ".tmp"
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
                bump_durability_stat("orphan_checkpoints_removed")
            except OSError:
                pass

    def _cleanup_stale_generations(self) -> None:
        """Deletes translog-<gen>.log files NEWER than the checkpointed
        generation. Only an interrupted roll_generation (crash between
        creating the new file and writing the checkpoint) produces one;
        no op is ever appended to a generation before its checkpoint is
        durable, so the file holds nothing acked."""
        for fname in os.listdir(self.dir):
            if not (fname.startswith("translog-") and fname.endswith(".log")):
                continue
            try:
                gen = int(fname[len("translog-") : -len(".log")])
            except ValueError:
                continue
            if gen > self.generation:
                try:
                    os.remove(os.path.join(self.dir, fname))
                    bump_durability_stat("stale_generations_removed")
                except OSError:
                    pass

    @staticmethod
    def _truncate_torn_tail(path: str) -> None:
        """Truncates a torn trailing record so the next append starts at
        a clean line boundary. Without this, a reopen in append mode
        concatenated new records onto the garbage and `_read_ops`
        stopped at the corruption — silently dropping every LATER op in
        the generation (the seed bug this round fixes)."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except (FileNotFoundError, OSError):
            return
        if not data:
            return
        pos = 0
        while True:
            nl = data.find(b"\n", pos)
            if nl == -1:
                break  # trailing bytes with no newline: torn
            seg = data[pos:nl].strip()
            if seg:
                try:
                    json.loads(seg)
                except ValueError:
                    break  # corrupt record: everything from here is torn
            pos = nl + 1
        if pos < len(data):
            with open(path, "r+b") as f:
                f.truncate(pos)
                os.fsync(f.fileno())
            bump_durability_stat("torn_tails_truncated")
            bump_durability_stat("torn_bytes_dropped", len(data) - pos)

    # ---- write path ----

    def add(self, op: dict) -> None:
        """Appends one operation (must carry ``seq_no``).

        ``async`` durability bounds the acked-but-volatile window to
        ``sync_interval`` (index.translog.sync_interval, default 5s) by
        checking the clock on every append — no timer thread, but an
        actively-written shard fsyncs at least every interval; an idle
        shard's tail syncs at the next op, roll, or close."""
        line = (json.dumps(op, separators=(",", ":")) + "\n").encode("utf-8")
        try:
            faults.check(
                "translog.append",
                shard=self.shard_id,
                gen=self.generation,
                seq_no=op.get("seq_no"),
                op=op.get("op"),
            )
        except SimulatedCrash as e:
            if e.torn:
                # power failed MID-write: a prefix of the record reaches
                # the platter — the torn tail recovery must truncate
                try:
                    self._file.write(line[: max(1, len(line) // 2)])
                except OSError:
                    pass
            raise
        self._pending.append(line)
        self._ops_in_gen += 1
        self.bytes_since_trim += len(line)
        seq = op.get("seq_no")
        if isinstance(seq, int):
            self._max_seq_appended = max(self._max_seq_appended, seq)
        bump_durability_stat("translog_appended_ops")
        if self.durability == DURABILITY_REQUEST:
            self.sync()
        elif _time.monotonic() - self._last_sync >= self.sync_interval:
            self.sync()

    def sync(self) -> None:
        # the crash site sits BEFORE the write: a power loss during an
        # fsync makes no promise about the pending tail
        faults.check("translog.fsync", shard=self.shard_id,
                     gen=self.generation)
        if self._pending:
            self._file.write(b"".join(self._pending))
            self._pending.clear()
        os.fsync(self._file.fileno())
        self.last_synced_seq_no = max(
            self.last_synced_seq_no, self._max_seq_appended
        )
        self._last_sync = _time.monotonic()
        bump_durability_stat("translog_fsyncs")

    @property
    def last_fsync_age(self) -> float:
        """Seconds since the last successful fsync."""
        return _time.monotonic() - self._last_sync

    # ---- generations ----

    def roll_generation(self) -> None:
        """Starts a new generation (called by flush before commit)."""
        self.sync()
        self._file.close()
        self.generation += 1
        self._file = open(self._gen_path(self.generation), "ab", buffering=0)
        self._ops_in_gen = 0
        self._write_checkpoint()

    def trim_unreferenced(self, committed_seq_no: int) -> None:
        """Deletes generations whose ops are all covered by the commit.

        Ordering contract (the crash matrix proves it): the caller's
        commit — segment files + manifest — is already DURABLE when this
        runs; a crash between the checkpoint write and the deletes below
        only leaves covered files behind, which the next recovery skips
        (ops <= committed) and the next trim removes."""
        self.min_retained_seq_no = committed_seq_no + 1
        self._write_checkpoint()
        for fname in os.listdir(self.dir):
            if not fname.startswith("translog-"):
                continue
            gen = int(fname[len("translog-") : -len(".log")])
            if gen >= self.generation:
                continue
            path = os.path.join(self.dir, fname)
            keep = False
            for op in self._read_ops(path):
                if op.get("seq_no", -1) > committed_seq_no:
                    keep = True
                    break
            if not keep:
                os.remove(path)
        self.bytes_since_trim = 0

    # ---- recovery ----

    @staticmethod
    def _read_ops(path: str) -> Iterator[dict]:
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        return  # torn tail write: stop at corruption
        except FileNotFoundError:
            return

    def read_ops_after(self, seq_no: int) -> Iterator[dict]:
        """All ops with seq_no > the given value, in log order."""
        gens = sorted(
            int(f[len("translog-") : -len(".log")])
            for f in os.listdir(self.dir)
            if f.startswith("translog-")
        )
        self.sync()
        for gen in gens:
            for op in self._read_ops(self._gen_path(gen)):
                if op.get("seq_no", -1) > seq_no:
                    yield op

    def stats(self) -> dict:
        return {
            "ops_in_generation": self._ops_in_gen,
            "pending_ops": len(self._pending),
            "uncommitted_bytes": self.bytes_since_trim,
            "last_fsync_age_ms": round(self.last_fsync_age * 1000.0, 1),
            "generation": self.generation,
            "durability": self.durability,
        }

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._file.close()

    def crash(self) -> None:
        """Simulated power loss: the pending (acked-but-unfsynced) tail
        is DROPPED, nothing is flushed, no checkpoint is written. The
        file handle itself is unbuffered, so closing it cannot leak the
        dropped bytes onto disk."""
        self._pending.clear()
        try:
            self._file.close()
        except OSError:
            pass
