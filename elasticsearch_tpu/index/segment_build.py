"""Segment-build orchestration for streaming ingest (device or host).

`build_segment` turns a batch of parsed documents into an immutable
`Segment`. The host reference path is `SegmentBuilder.build()` —
unchanged, forever the oracle. The device path (`ES_TPU_DEVICE_BUILD`,
see common/settings.py) keeps the token/hash/string work on the host
(tokenization happened at parse time; term dictionaries sort here) and
materializes the column arrays through the jitted kernels in
ops/index_build.py: postings tiling + norms + block-max sidecars,
keyword ordinal CSRs, dense vector layout, rank_vectors CSR offsets.
Device-built columns are BIT-IDENTICAL to the host build for every
column family (tests/test_ingest_nrt.py asserts array equality), so
routing is free to change at any time without changing any answer.

Degrade contract (the serving-path pattern applied to the write path):

  - `build.device` fault site fires before the device build; an
    injected error falls back to the host build (counted `fallbacks`),
    a `crash` kind propagates as SimulatedCrash (power loss mid-build);
  - transient device arrays are charged to the `build` HbmLedger
    category; a build that would not fit degrades to the host build
    (counted `degraded`) instead of tripping the breaker;
  - ANY device-path failure falls back to the host build — a refresh
    never fails because an optimization did.

This module also owns the node-wide ingest/refresh stats registry (the
`ingest` block of `_nodes/stats`): refresh counts and lag percentiles,
device-vs-host build counters, concurrent-build overlap, and
generations discarded on mid-build failure.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np

from ..common.faults import SimulatedCrash, faults
from .mapping import TEXT, Mappings, ParsedDocument
from .segment import (
    MultiVectorField,
    NumericField,
    OrdinalField,
    PostingsField,
    Segment,
    SegmentBuilder,
    SparseField,
    VectorField,
    FieldStats,
    TILE,
    _unit_normalize,
    sparse_plan,
)

# ---------------------------------------------------------------------------
# ingest / refresh observability
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
INGEST_STATS = {
    "refreshes": 0,  # committed refreshes (all shards, all indices)
    "concurrent_refreshes": 0,  # double-buffered (built outside the lock)
    "concurrent_merges": 0,  # double-buffered merges (built outside the lock)
    "device_builds": 0,  # segments whose columns were built on device
    "host_builds": 0,  # segments built by the host SegmentBuilder
    "fallbacks": 0,  # device-path failures → host build
    "degraded": 0,  # HBM-budget degrades → host build
    "generations_discarded": 0,  # half-builds dropped (fault / superseded)
    "overlap_ms": 0.0,  # build wall time overlapped with serving
    "prewarm_ms": 0.0,  # post-swap executor/mesh prewarm wall time
    "wait_for_waits": 0,  # ?refresh=wait_for blocks on the next swap
}
_REFRESH_LAGS = deque(maxlen=4096)  # worst-doc visibility lag per refresh, ms


class _Degraded(Exception):
    """Internal: device build would not fit the HBM budget."""


def note(key: str, n=1) -> None:
    with _LOCK:
        INGEST_STATS[key] += n


def note_refresh_lag(ms: float) -> None:
    with _LOCK:
        _REFRESH_LAGS.append(float(ms))


def refresh_lag_percentiles() -> dict:
    with _LOCK:
        lags = list(_REFRESH_LAGS)
    if not lags:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None, "samples": 0}
    arr = np.asarray(lags)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 2),
        "p95_ms": round(float(np.percentile(arr, 95)), 2),
        "p99_ms": round(float(np.percentile(arr, 99)), 2),
        "samples": len(lags),
    }


def stats_snapshot() -> dict:
    """The `ingest` block of `_nodes/stats` (joined with the build
    kernel timings and the `build` ledger bytes)."""
    from ..common.memory import hbm_ledger
    from ..ops.index_build import kernel_stats_snapshot

    with _LOCK:
        out = dict(INGEST_STATS)
    out["overlap_ms"] = round(out["overlap_ms"], 2)
    out["prewarm_ms"] = round(out["prewarm_ms"], 2)
    out["refresh_lag"] = refresh_lag_percentiles()
    out["build_kernels"] = kernel_stats_snapshot()
    out["build_ledger_bytes"] = int(
        hbm_ledger.stats()["by_category"].get("build", 0)
    )
    return out


def reset_stats() -> None:
    """Test/bench hook: zero the counters and the lag reservoir."""
    from ..ops.index_build import reset_kernel_stats

    with _LOCK:
        for k, v in list(INGEST_STATS.items()):
            INGEST_STATS[k] = 0.0 if isinstance(v, float) else 0
        _REFRESH_LAGS.clear()
    reset_kernel_stats()


# ---------------------------------------------------------------------------
# build entry point
# ---------------------------------------------------------------------------


def build_segment(
    mappings: Mappings,
    docs: List[ParsedDocument],
    generation: int = 0,
    shard_id: int = 0,
    prefer_device: bool = False,
) -> Segment:
    """An immutable Segment from parsed docs, device-built when the
    `ES_TPU_DEVICE_BUILD` mode (and the owning index's backend, via
    `prefer_device`) says so; bit-identical either way."""
    from ..common.settings import device_build_mode

    builder = SegmentBuilder(mappings, generation)
    for d in docs:
        builder.add(d)
    mode = device_build_mode()
    use_device = mode == "force" or (mode == "auto" and prefer_device)
    if use_device and len(docs):
        try:
            faults.check("build.device", shard=shard_id)
            seg = _device_build(builder)
            note("device_builds")
            return seg
        except SimulatedCrash:
            raise  # power loss mid-build: unwind to the harness
        except _Degraded:
            note("degraded")
        except Exception:
            if mode == "force":
                raise
            note("fallbacks")
    note("host_builds")
    return builder.build()


def _charge_build(nbytes: int):
    """Transient `build`-category ledger charge for one device-build
    family; raises _Degraded (→ host build) when it would not fit."""
    from ..common.memory import hbm_ledger

    if not hbm_ledger.would_fit(nbytes):
        hbm_ledger.note_degraded()
        raise _Degraded(f"device build of {nbytes} bytes over budget")
    hbm_ledger.add("build", nbytes, breaker=False)
    return nbytes


def _release_build(nbytes: int) -> None:
    from ..common.memory import hbm_ledger

    hbm_ledger.release("build", nbytes)


def _device_build(builder: SegmentBuilder) -> Segment:
    """The device mirror of SegmentBuilder.build(): same field
    discovery, same outputs, column materialization on device."""
    from ..ops import index_build as ib

    docs = builder._docs
    n = len(docs)
    postings = {}
    numerics = {}
    ordinals = {}
    vectors = {}
    multi_vectors = {}

    # ---- text fields: tiled postings + positions ----
    text_fields = sorted({f for d in docs for f in d.text_terms})
    for fname in text_fields:
        inv_pos = {}
        lengths = np.zeros(n, dtype=np.int64)
        doc_count = 0
        for local_id, d in enumerate(docs):
            terms = d.text_terms.get(fname)
            if not terms:
                continue
            doc_count += 1
            lengths[local_id] = d.field_lengths.get(fname, len(terms))
            for term, pos in terms:
                inv_pos.setdefault(term, {}).setdefault(local_id, []).append(
                    pos
                )
        inv = {
            t: {d_: len(ps) for d_, ps in pl.items()}
            for t, pl in inv_pos.items()
        }
        pf = _device_postings(ib, inv, lengths, n, doc_count)
        SegmentBuilder._attach_positions(pf, inv_pos)
        mf = builder.mappings.get(fname)
        if mf is None or mf.type == TEXT:
            _device_impacts(ib, pf, n)
        postings[fname] = pf

    # ---- keyword fields: postings (tf=1) + device ordinal CSR ----
    kw_fields = sorted({f for d in docs for f in d.keyword_terms})
    for fname in kw_fields:
        inv = {}
        lengths = np.zeros(n, dtype=np.int64)
        doc_count = 0
        all_vals: List[List[str]] = []
        for local_id, d in enumerate(docs):
            vals = d.keyword_terms.get(fname) or []
            all_vals.append(vals)
            if vals:
                doc_count += 1
                lengths[local_id] = len(vals)
            for v in set(vals):
                inv.setdefault(v, {})[local_id] = 1
        postings[fname] = _device_postings(ib, inv, lengths, n, doc_count)
        ordinals[fname] = _device_ordinals(ib, all_vals, n)

    # ---- numerics: cheap dense host columns (identical code path) ----
    num_fields = sorted({f for d in docs for f in d.numeric_values})
    for fname in num_fields:
        values = np.zeros(n, dtype=np.float64)
        exists = np.zeros(n, dtype=bool)
        for local_id, d in enumerate(docs):
            vals = d.numeric_values.get(fname)
            if vals:
                values[local_id] = vals[0]
                exists[local_id] = True
        numerics[fname] = NumericField(values=values, exists=exists)

    # ---- dense vectors: device scatter into the [N, dims] layout ----
    vec_fields = sorted({f for d in docs for f in d.vectors})
    for fname in vec_fields:
        mf = builder.mappings.get(fname)
        dims = (
            mf.dims
            if mf
            else len(
                next(
                    v
                    for d in docs
                    for f2, v in d.vectors.items()
                    if f2 == fname
                )
            )
        )
        rows = []
        idx = []
        for local_id, d in enumerate(docs):
            v = d.vectors.get(fname)
            if v is not None:
                rows.append(np.asarray(v, dtype=np.float32))
                idx.append(local_id)
        sim = mf.similarity if mf else "cosine"
        if rows:
            rmat = np.stack(rows)
            ridx = np.asarray(idx, np.int32)
            nb = _charge_build(
                int(rmat.nbytes) * 3 + ib.bucket_pow2(n) * (dims * 4 + 1)
            )
            try:
                mat, exists = ib.scatter_rows_device(rmat, ridx, n)
            finally:
                _release_build(nb)
        else:
            mat = np.zeros((n, dims), np.float32)
            exists = np.zeros(n, bool)
        vf = VectorField(vectors=mat, exists=exists, similarity=sim)
        if sim == "cosine":
            # float reduction: shared host routine in BOTH paths (like
            # tokenization — normalization is part of doc prep)
            vf.unit_vectors = _unit_normalize(mat)
        vectors[fname] = vf

    # ---- rank_vectors: flat CSR token column, device offsets ----
    mv_fields = sorted({f for d in docs for f in d.multi_vectors})
    for fname in mv_fields:
        mf = builder.mappings.get(fname)
        dims = (
            mf.dims
            if mf and mf.dims
            else len(
                next(
                    row
                    for d in docs
                    for m in (d.multi_vectors.get(fname),)
                    if m
                    for row in m[:1]
                )
            )
        )
        sim = mf.similarity if mf else "cosine"
        counts = np.zeros(n, np.int32)
        chunks: List[np.ndarray] = []
        for local_id, d in enumerate(docs):
            mat = d.multi_vectors.get(fname)
            if mat:
                arr = np.asarray(mat, dtype=np.float32)
                if sim == "cosine":
                    arr = _unit_normalize(arr)
                chunks.append(arr)
                counts[local_id] = len(arr)
        tok = (
            np.concatenate(chunks, axis=0)
            if chunks
            else np.zeros((0, dims), np.float32)
        )
        nb = _charge_build(ib.bucket_pow2(n) * 8)
        try:
            offsets, exists = ib.csr_offsets_device(counts, n)
        finally:
            _release_build(nb)
        multi_vectors[fname] = MultiVectorField(
            tok_vectors=tok,
            tok_offsets=offsets,
            exists=exists,
            similarity=sim,
        )

    # ---- sparse_vector: impact-ordered planes materialized on device.
    # The host owns the layout plan (index/segment.sparse_plan — sort,
    # impact ordering, pruning), so the device twin is bit-identical by
    # construction; the kernel scatters + quantizes. ----
    sparse = {}
    sp_fields = sorted({f for d in docs for f in d.sparse_vectors})
    for fname in sp_fields:
        mf = builder.mappings.get(fname)
        ratio = mf.pruning_ratio if mf else 0.0
        inv_w = {}
        sp_exists = np.zeros(n, dtype=bool)
        for local_id, d in enumerate(docs):
            wmap = d.sparse_vectors.get(fname)
            if not wmap:
                continue
            sp_exists[local_id] = True
            for term, w in wmap.items():
                inv_w.setdefault(term, {})[local_id] = float(w)
        plan = sparse_plan(inv_w, ratio)
        nb = _charge_build(
            ib.estimate_sparse_nbytes(
                len(plan["docs"]), plan["n_tiles"], len(plan["terms"])
            )
        )
        try:
            doc_ids, weights, qweights, scales, tile_max, tile_qmax = (
                ib.sparse_planes_device(plan)
            )
        finally:
            _release_build(nb)
        sparse[fname] = SparseField(
            terms=plan["terms"],
            term_df=plan["term_df"],
            term_tile_start=plan["term_tile_start"],
            term_tile_count=plan["term_tile_count"],
            doc_ids=doc_ids,
            weights=weights,
            qweights=qweights,
            scales=scales,
            tile_max=tile_max,
            tile_qmax=tile_qmax,
            exists=sp_exists,
            pruned=int(plan["pruned"]),
        )

    return Segment(
        num_docs=n,
        doc_ids=[d.doc_id for d in docs],
        sources=[d.source for d in docs],
        postings=postings,
        numerics=numerics,
        ordinals=ordinals,
        vectors=vectors,
        generation=builder.generation,
        multi_vectors=multi_vectors,
        sparse=sparse,
    )


def _device_postings(
    ib, inv, lengths: np.ndarray, n: int, doc_count: int
) -> PostingsField:
    """PostingsField with the tiled planes materialized on device. The
    host does the dictionary sort and the vectorized layout plan (one
    lexsort — no per-term Python loop over tile rows)."""
    from ..utils.smallfloat import encode_norms

    terms = sorted(inv)
    n_terms = len(terms)
    if n_terms == 0:
        return PostingsField(
            terms=[],
            term_df=np.zeros(0, np.int32),
            term_total_tf=np.zeros(0, np.int64),
            term_tile_start=np.zeros(0, np.int32),
            term_tile_count=np.zeros(0, np.int32),
            doc_ids=np.full((0, TILE), -1, np.int32),
            tfs=np.zeros((0, TILE), np.int32),
            tile_max_tf=np.zeros(0, np.int32),
            tile_min_norm=np.zeros(0, np.uint8),
            norms=encode_norms(lengths),
            stats=FieldStats(doc_count=doc_count),
        )
    # flat (term_id, doc, tf) stream — the residual host hash work
    tid_l: List[int] = []
    doc_l: List[int] = []
    tf_l: List[int] = []
    for tid, t in enumerate(terms):
        plist = inv[t]
        tid_l.extend([tid] * len(plist))
        doc_l.extend(plist.keys())
        tf_l.extend(plist.values())
    tids = np.asarray(tid_l, np.int64)
    docs_arr = np.asarray(doc_l, np.int32)
    tfs_arr = np.asarray(tf_l, np.int32)
    order = np.lexsort((docs_arr, tids))  # term-major, doc asc
    tids = tids[order]
    docs_arr = docs_arr[order]
    tfs_arr = tfs_arr[order]
    term_df = np.bincount(tids, minlength=n_terms).astype(np.int32)
    term_total_tf = np.bincount(
        tids, weights=tfs_arr.astype(np.float64), minlength=n_terms
    ).astype(np.int64)
    term_tile_count = ((term_df + TILE - 1) // TILE).astype(np.int32)
    term_tile_start = np.zeros(n_terms, np.int32)
    if n_terms > 1:
        np.cumsum(term_tile_count[:-1], out=term_tile_start[1:])
    n_tiles = int(term_tile_count.sum())
    est = ib.estimate_postings_nbytes(len(docs_arr), n_tiles, n)
    nb = _charge_build(est)
    try:
        doc_ids, tfs, tile_max_tf, norms, tile_min_norm = (
            ib.postings_tiles_device(
                tids, docs_arr, tfs_arr, term_tile_start, term_df,
                lengths, n_tiles, n,
            )
        )
    finally:
        _release_build(nb)
    stats = FieldStats(
        doc_count=doc_count,
        sum_total_term_freq=int(term_total_tf.sum()),
        sum_doc_freq=int(term_df.sum()),
    )
    return PostingsField(
        terms=terms,
        term_df=term_df,
        term_total_tf=term_total_tf,
        term_tile_start=term_tile_start,
        term_tile_count=term_tile_count,
        doc_ids=doc_ids,
        tfs=tfs,
        tile_max_tf=tile_max_tf,
        tile_min_norm=tile_min_norm,
        norms=norms,
        stats=stats,
    )


def _device_impacts(ib, pf: PostingsField, n: int) -> None:
    """Attach the precomputed BM25 impacts to a device-built text
    postings column. The 256-entry segment-local inv-norm cache is
    computed on HOST (models/bm25.norm_inverse_cache — the same float
    path the host attach uses), so both builds fold identical bits; the
    device folds it into per-posting int8 impacts."""
    from ..models import bm25

    n_terms = len(pf.terms)
    if pf.n_tiles == 0:
        pf.impacts = np.zeros((0, TILE), np.int8)
        pf.impact_scales = np.zeros(n_terms, np.float32)
        return
    cache = bm25.norm_inverse_cache(
        bm25.avg_field_length(
            pf.stats.sum_total_term_freq, pf.stats.doc_count
        )
    )
    tile_term = np.repeat(
        np.arange(n_terms, dtype=np.int32), pf.term_tile_count
    )
    nb = _charge_build(
        ib.bucket_pow2(pf.n_tiles, floor=1) * TILE * 9
        + ib.bucket_pow2(n, floor=1)
    )
    try:
        impacts, scales = ib.text_impacts_device(
            pf.doc_ids, pf.tfs, pf.norms, cache, tile_term, n_terms, n
        )
    finally:
        _release_build(nb)
    pf.impacts = impacts
    pf.impact_scales = scales


def _device_ordinals(ib, all_vals: List[List[str]], n: int) -> OrdinalField:
    """OrdinalField with the multi-value CSR assembled on device (dedup
    + sort + compaction); the host does only the string work."""
    uniq = sorted({v for vals in all_vals for v in vals})
    ord_of = {v: i for i, v in enumerate(uniq)}
    doc_l: List[int] = []
    ord_l: List[int] = []
    for i, vals in enumerate(all_vals):
        for v in vals:  # dups allowed — the device dedups
            doc_l.append(i)
            ord_l.append(ord_of[v])
    docs_arr = np.asarray(doc_l, np.int32)
    ords_arr = np.asarray(ord_l, np.int32)
    nb = _charge_build(int(docs_arr.nbytes) * 8 + ib.bucket_pow2(n) * 8)
    try:
        ords_col, mv_ords, mv_offsets = ib.ordinals_device(
            docs_arr, ords_arr, n
        )
    finally:
        _release_build(nb)
    return OrdinalField(
        ord_terms=uniq,
        ords=ords_col,
        mv_ords=mv_ords,
        mv_offsets=mv_offsets,
    )
