"""Lucene SmallFloat int↔byte4 norm encoding.

Parity target: org.apache.lucene.util.SmallFloat.intToByte4 / byte4ToInt
(Lucene jar; used by BM25Similarity to store document length in one byte).
Exact parity matters: BM25 scores are computed from the *decoded* quantized
length, so using the raw length would silently break recall@1000 parity
with the reference (SURVEY.md §7 hard parts: analyzer/norm parity).

Encoding: values 0..39 map to themselves; larger values are stored as a
4-bit-mantissa float (numBits=4, zeroExp=0 in longToInt4), shifted so the
byte range covers lengths up to ~2^28.
"""

from __future__ import annotations

import numpy as np

def _long_to_int4(i: int) -> int:
    """SmallFloat.longToInt4: monotone map long→4-bit-mantissa 'float'."""
    if i < 0:
        raise ValueError("only supports positive values")
    num_bits = i.bit_length()
    if num_bits < 4:
        # subnormal value
        return i
    # normal value
    shift = num_bits - 4
    # only keep the 5 most significant bits
    encoded = i >> shift
    # clear the most significant bit (always 1)
    encoded &= 0x07
    # encode the shift, adding 1 because 0 is reserved for subnormal values
    encoded |= (shift + 1) << 3
    return encoded


def _int4_to_long(i: int) -> int:
    """SmallFloat.int4ToLong: inverse of longToInt4 (lossy round-trip)."""
    bits = i & 0x07
    shift = (i >> 3) - 1
    if shift == -1:
        # subnormal value
        decoded = bits
    else:
        # normal value
        decoded = (bits | 0x08) << shift
    return decoded


MAX_INT4 = _long_to_int4(2**31 - 1)  # = 231
NUM_FREE_VALUES = 255 - MAX_INT4  # = 24; values below this encode as themselves


def int_to_byte4(i: int) -> int:
    """SmallFloat.intToByte4: int in [0, 2^31) → byte (returned as 0..255)."""
    if i < 0:
        raise ValueError("only supports positive values")
    if i < NUM_FREE_VALUES:
        return i
    return NUM_FREE_VALUES + _long_to_int4(i - NUM_FREE_VALUES)


def byte4_to_int(b: int) -> int:
    """SmallFloat.byte4ToInt: byte (0..255) → decoded int."""
    if b < NUM_FREE_VALUES:
        return b
    return NUM_FREE_VALUES + _int4_to_long(b - NUM_FREE_VALUES)


# Precomputed 256-entry decode table (BM25Similarity.LENGTH_TABLE analog).
LENGTH_TABLE = np.array([byte4_to_int(b) for b in range(256)], dtype=np.int64)


def encode_norms(lengths: np.ndarray) -> np.ndarray:
    """Vectorized intToByte4 over an array of field lengths → uint8 norms.

    intToByte4 truncates: encode(x) is the largest byte whose decoded value
    is <= x. LENGTH_TABLE is strictly increasing, so searchsorted gives the
    same answer as the scalar routine (property-tested against it).
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    if lengths.size and lengths.min() < 0:
        raise ValueError("only supports positive values")
    return (np.searchsorted(LENGTH_TABLE, lengths, side="right") - 1).astype(
        np.uint8
    )
