"""MurmurHash3 x86_32 with Elasticsearch routing semantics.

Parity target: org.elasticsearch.cluster.routing.Murmur3HashFunction
(server/src/main/java/org/elasticsearch/cluster/routing/Murmur3HashFunction.java),
which encodes the routing string's UTF-16 code units as little-endian byte
pairs and applies Lucene's StringHelper.murmurhash3_x86_32 with seed 0.
Doc→shard routing is then `floorMod(hash, num_shards)` (OperationRouting /
IndexRouting in server/.../cluster/routing/).
"""

from __future__ import annotations

_MASK32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK32


def murmurhash3_x86_32(data: bytes, seed: int = 0) -> int:
    """Returns the *signed* 32-bit murmur3 hash (Java int semantics)."""
    c1 = 0xCC9E2D51
    c2 = 0x1B873593
    h1 = seed & _MASK32
    n = len(data)
    rounded = n & ~0x3

    for i in range(0, rounded, 4):
        k1 = int.from_bytes(data[i : i + 4], "little")
        k1 = (k1 * c1) & _MASK32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _MASK32
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _MASK32

    k1 = 0
    tail = n & 3
    if tail >= 3:
        k1 ^= data[rounded + 2] << 16
    if tail >= 2:
        k1 ^= data[rounded + 1] << 8
    if tail >= 1:
        k1 ^= data[rounded]
        k1 = (k1 * c1) & _MASK32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _MASK32
        h1 ^= k1

    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _MASK32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _MASK32
    h1 ^= h1 >> 16

    # Java int is signed.
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


def murmur3_hash(routing: str) -> int:
    """ES Murmur3HashFunction.hash(String): UTF-16 code units as LE bytes.

    Python's utf-16-le encoding emits exactly Java's char sequence,
    including surrogate pairs for non-BMP code points.
    """
    return murmurhash3_x86_32(routing.encode("utf-16-le"), 0)


def calculate_num_routing_shards(num_shards: int) -> int:
    """MetadataCreateIndexService.calculateNumRoutingShards for 7.0+ indices:
    the partition space is num_shards * 2^numSplits (≥1 split, target 1024)
    so indices can later be split in place."""
    log2_max = 10  # log2(1024)
    log2_num = (num_shards - 1).bit_length()  # ceil(log2(num_shards))
    num_splits = max(1, log2_max - log2_num)
    return num_shards << num_splits


def shard_id(routing: str, num_shards: int, routing_num_shards: int | None = None) -> int:
    """doc→shard as IndexRouting does for 7.0+ indices:
    floorMod(murmur3(routing), routing_num_shards) / routing_factor,
    where routing_factor = routing_num_shards / num_shards.

    Python's % on ints already matches Java's Math.floorMod for negative
    hashes.
    """
    if routing_num_shards is None:
        routing_num_shards = calculate_num_routing_shards(num_shards)
    if routing_num_shards % num_shards != 0:
        # IndexMetadata validates routingFactor * numShards == routingNumShards
        raise ValueError(
            f"the number of routing shards [{routing_num_shards}] must be a "
            f"multiple of the number of shards [{num_shards}]"
        )
    routing_factor = routing_num_shards // num_shards
    return (murmur3_hash(routing) % routing_num_shards) // routing_factor
