from .murmur3 import murmur3_hash, shard_id
from .smallfloat import int_to_byte4, byte4_to_int

__all__ = ["murmur3_hash", "shard_id", "int_to_byte4", "byte4_to_int"]
