"""Native (C++) runtime components, loaded via ctypes.

Reference analog: the reference's load-bearing native layer —
libs/simdvec C kernels, libzstd bindings, and Lucene's ForUtil postings
block decode (SURVEY.md §2.5). The TPU compute path is JAX/Pallas; the
HOST-side hot loops that the reference implements natively get C++
here: the postings varint/delta codec (on-disk form of posting tiles,
decoded once at index load).

The shared library builds on demand with g++ (cached next to the
sources); hosts without a toolchain fall back to the NumPy/Python
implementation with identical semantics (parity-tested).
"""

from .codec import (
    native_available,
    tiles_decode,
    tiles_encode,
    vb_decode,
    vb_encode,
)

__all__ = [
    "native_available",
    "tiles_encode",
    "tiles_decode",
    "vb_encode",
    "vb_decode",
]
