"""ctypes bindings + build-on-demand for native/postings_codec.cpp,
with a pure-NumPy fallback of identical semantics."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    return os.path.join(here, "native", "postings_codec.cpp")


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "_libpostings.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        src = _source_path()
        lib = _lib_path()
        try:
            if not os.path.exists(src):
                return None
            if (
                not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)
            ):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", src, "-o", lib],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            dll = ctypes.CDLL(lib)
            for name, argtypes in (
                ("vb_encode", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]),
                ("vb_decode", [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_void_p, ctypes.c_int64]),
                ("tiles_encode", [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_void_p]),
                ("tiles_decode", [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int64]),
            ):
                fn = getattr(dll, name)
                fn.argtypes = argtypes
                fn.restype = ctypes.c_int64
            _LIB = dll
        except (OSError, subprocess.SubprocessError):
            _LIB = None
        return _LIB


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# zigzag varints (LEB128)
# ---------------------------------------------------------------------------


def _zz_enc(v: np.ndarray) -> np.ndarray:
    return ((v.astype(np.int64) << 1) ^ (v.astype(np.int64) >> 31)).astype(
        np.uint64
    )


def _py_vb_encode(vals: np.ndarray) -> bytes:
    out = bytearray()
    for u in _zz_enc(vals.astype(np.int32)):
        u = int(u)
        while u >= 0x80:
            out.append((u & 0x7F) | 0x80)
            u >>= 7
        out.append(u)
    return bytes(out)


def _py_vb_decode(data: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.int32)
    p = 0
    ln = len(data)
    for i in range(n):
        u = 0
        shift = 0
        while True:
            if p >= ln or shift > 28:
                raise ValueError("corrupt varint stream")
            b = data[p]
            p += 1
            u |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        out[i] = np.int32((u >> 1) ^ -(u & 1))
    return out


def vb_encode(vals: np.ndarray) -> bytes:
    vals = np.ascontiguousarray(vals, np.int32)
    lib = _load()
    if lib is None:
        return _py_vb_encode(vals)
    out = np.empty(len(vals) * 5, np.uint8)
    n = lib.vb_encode(
        vals.ctypes.data, len(vals), out.ctypes.data
    )
    return out[:n].tobytes()


def vb_decode(data: bytes, n: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        return _py_vb_decode(data, n)
    buf = np.frombuffer(data, np.uint8)
    out = np.empty(n, np.int32)
    used = lib.vb_decode(buf.ctypes.data, len(buf), out.ctypes.data, n)
    if used < 0:
        raise ValueError("corrupt varint stream")
    return out


# ---------------------------------------------------------------------------
# tile delta codec (doc-id rows: absolute first value, ascending deltas,
# -1 padding kept absolute)
# ---------------------------------------------------------------------------


def _py_tiles_encode(tiles: np.ndarray) -> bytes:
    out = bytearray()
    for row in tiles:
        prev = 0
        first = True
        for v in row.tolist():
            if v < 0:
                enc = -1
            elif first:
                enc = v
                prev = v
                first = False
            else:
                if v < prev:
                    # an unsorted row must fail LOUDLY: its negative
                    # delta would alias the -1 padding sentinel and
                    # round-trip silently corrupted
                    raise ValueError(
                        "tiles_encode: doc ids not ascending within row"
                    )
                enc = v - prev
                prev = v
            u = ((enc << 1) ^ (enc >> 31)) & 0xFFFFFFFF
            while u >= 0x80:
                out.append((u & 0x7F) | 0x80)
                u >>= 7
            out.append(u)
    return bytes(out)


def _py_tiles_decode(data: bytes, n_tiles: int, width: int) -> np.ndarray:
    out = np.empty((n_tiles, width), np.int32)
    p = 0
    ln = len(data)
    for t in range(n_tiles):
        prev = 0
        first = True
        for i in range(width):
            u = 0
            shift = 0
            while True:
                if p >= ln or shift > 28:
                    raise ValueError("corrupt tile stream")
                b = data[p]
                p += 1
                u |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            v = int(np.int32((u >> 1) ^ -(u & 1)))
            if v == -1:
                out[t, i] = -1
            elif first:
                out[t, i] = v
                prev = v
                first = False
            else:
                prev += v
                out[t, i] = prev
    return out


def tiles_encode(tiles: np.ndarray) -> bytes:
    tiles = np.ascontiguousarray(tiles, np.int32)
    lib = _load()
    if lib is None:
        return _py_tiles_encode(tiles)
    n_tiles, width = tiles.shape
    out = np.empty(tiles.size * 5, np.uint8)
    n = lib.tiles_encode(
        tiles.ctypes.data, n_tiles, width, out.ctypes.data
    )
    if n < 0:
        raise ValueError("tiles_encode: doc ids not ascending within row")
    return out[:n].tobytes()


def tiles_decode(data: bytes, n_tiles: int, width: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        return _py_tiles_decode(data, n_tiles, width)
    buf = np.frombuffer(data, np.uint8)
    out = np.empty((n_tiles, width), np.int32)
    used = lib.tiles_decode(
        buf.ctypes.data, len(buf), out.ctypes.data, n_tiles, width
    )
    if used < 0:
        raise ValueError("corrupt tile stream")
    return out
