"""Plugin SPI: extension points for queries, processors, analysis, REST.

Reference analogs (SURVEY.md §1 L9): org.elasticsearch.plugins —
SearchPlugin.getQueries, IngestPlugin.getProcessors,
AnalysisPlugin.getTokenFilters/getAnalyzers, ActionPlugin.getRestHandlers,
loaded by PluginsService during NodeConstruction. The TPU-native
framework loads plugins from Python classes (programmatically or via the
ES_TPU_PLUGINS env var, "module.path:ClassName" comma-separated) and
installs their registrations into the live registries.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple


class Plugin:
    """Extension-point surface. Subclass and override any hook.

    Hook contracts:
      * get_query_parsers() → {query_name: parser(params) -> dsl.Query}
      * get_processors() → {processor_type: Processor subclass}
      * get_token_filters() → {filter_type: factory(cfg) -> TokenFilter}
      * get_analyzers() → {analyzer_name: Analyzer instance}
      * get_rest_handlers() → [(method, path_pattern, handler)] where
        handler(cluster, body, params, qs) -> (status, payload)
      * get_script_contexts() → {name: callable} merged into the script
        sandbox's global bindings
    """

    name: str = "plugin"

    def get_query_parsers(self) -> Dict[str, Callable]:
        return {}

    def get_processors(self) -> Dict[str, type]:
        return {}

    def get_token_filters(self) -> Dict[str, Callable]:
        return {}

    def get_analyzers(self) -> Dict[str, object]:
        return {}

    def get_rest_handlers(self) -> List[Tuple[str, str, Callable]]:
        return []

    def get_script_contexts(self) -> Dict[str, Callable]:
        return {}


class PluginsService:
    """Loads + installs plugins into the live registries
    (PluginsService + NodeConstruction's SPI consumption)."""

    def __init__(self):
        self.plugins: List[Plugin] = []
        self._lock = threading.Lock()
        # REST handlers registered by plugins. RestActions reads this at
        # CONSTRUCTION only — plugins must be installed before the REST
        # server starts (exactly the reference's constraint: PluginsService
        # loads during NodeConstruction, never after).
        self.rest_handlers: List[Tuple[str, str, Callable]] = []
        self._loaded_specs: set = set()

    def install(self, plugin: Plugin) -> None:
        with self._lock:
            # validate EVERYTHING before mutating any registry: a plugin
            # that fails halfway must not leave orphaned registrations
            self._validate(plugin)
            self._apply(plugin)
            self.plugins.append(plugin)
            self._loaded_specs.add(getattr(plugin, "_spec", plugin.name))

    def load_spec(self, spec: str) -> Optional[Plugin]:
        """Loads "module.path:ClassName" and installs it (idempotent:
        an already-loaded spec is skipped)."""
        if spec in self._loaded_specs:
            return None
        mod_name, _, cls_name = spec.partition(":")
        if not cls_name:
            raise ValueError(
                f"plugin spec [{spec}] must be module.path:ClassName"
            )
        mod = importlib.import_module(mod_name)
        cls = getattr(mod, cls_name)
        plugin = cls()
        if not isinstance(plugin, Plugin):
            raise TypeError(f"[{spec}] is not a Plugin subclass")
        plugin._spec = spec
        self.install(plugin)
        return plugin

    def load_env(self, env: str = "ES_TPU_PLUGINS") -> List[Plugin]:
        specs = [s.strip() for s in os.environ.get(env, "").split(",") if s.strip()]
        out = []
        for s in specs:
            p = self.load_spec(s)
            if p is not None:
                out.append(p)
        return out

    def _validate(self, plugin: Plugin) -> None:
        from .analysis.analyzer import AnalysisRegistry
        from .ingest.service import PROCESSOR_TYPES, Processor
        from .search import dsl

        for qname in plugin.get_query_parsers():
            if qname in dsl._PARSERS:
                raise ValueError(
                    f"plugin [{plugin.name}] redefines query [{qname}]"
                )
        for ptype, cls in plugin.get_processors().items():
            if not (isinstance(cls, type) and issubclass(cls, Processor)):
                raise TypeError(
                    f"processor [{ptype}] must subclass ingest Processor"
                )
            if ptype in PROCESSOR_TYPES:
                raise ValueError(
                    f"plugin [{plugin.name}] redefines processor [{ptype}]"
                )
        for fname in plugin.get_token_filters():
            if fname in AnalysisRegistry._FILTERS:
                raise ValueError(
                    f"plugin [{plugin.name}] redefines token filter [{fname}]"
                )
        for aname in plugin.get_analyzers():
            if aname in AnalysisRegistry.EXTRA_ANALYZERS:
                raise ValueError(
                    f"plugin [{plugin.name}] redefines analyzer [{aname}]"
                )

    def _apply(self, plugin: Plugin) -> None:
        """Registers everything; callers must have run _validate first."""
        from .analysis.analyzer import AnalysisRegistry
        from .ingest.service import PROCESSOR_TYPES
        from .search import dsl

        dsl._PARSERS.update(plugin.get_query_parsers())
        PROCESSOR_TYPES.update(plugin.get_processors())
        AnalysisRegistry._FILTERS.update(plugin.get_token_filters())
        AnalysisRegistry.EXTRA_ANALYZERS.update(plugin.get_analyzers())
        # REST handlers (consumed by RestActions at construction)
        self.rest_handlers.extend(plugin.get_rest_handlers())
        # script bindings
        if plugin.get_script_contexts():
            from .script import service as script_mod

            script_mod._SAFE_BUILTINS.update(plugin.get_script_contexts())

    def info(self) -> List[dict]:
        return [
            {
                "name": p.name,
                "queries": sorted(p.get_query_parsers()),
                "processors": sorted(p.get_processors()),
                "token_filters": sorted(p.get_token_filters()),
                "analyzers": sorted(p.get_analyzers()),
                "rest_handlers": [
                    f"{m} {path}" for m, path, _ in p.get_rest_handlers()
                ],
            }
            for p in self.plugins
        ]


# process-wide registry (the node's PluginsService)
plugins_service = PluginsService()
