from . import dsl
from .executor import NumpyExecutor, ShardReader, TopDocs, Hit
from .executor_jax import JaxExecutor

__all__ = ["dsl", "NumpyExecutor", "JaxExecutor", "ShardReader", "TopDocs", "Hit"]
