"""Cross-request micro-batching dispatcher — the serving-path bridge to
the batched TPU kernels.

Reference analog: there is none in Elasticsearch — Lucene scores one
query per thread. This is the north-star departure (BASELINE.json:
"score query batches in parallel"): concurrent `_search` requests whose
query compiles to a flat weighted-term plan are collected into shared
fixed-shape kernel launches per (segment, field) instead of B separate
launches. The dispatcher uses continuous batching: while one batch is
executing on device, arriving requests queue; the worker drains the
whole queue the moment it frees up, so there is no linger timer and no
added idle latency for a lone request.

Launch shapes ride a pad-bucket LADDER (common/settings.batch_buckets,
default 1/4/8/16/32): each dispatched group pads its query rows to the
smallest compiled bucket >= its occupancy instead of the full BPAD
width, so a batch of 3 pays a 4-wide launch and a lone query a 1-wide
one — the continuous-batching half of the tail-latency work (the PR 6
admission layer is the QoS half). Lone queries arriving on an idle
worker additionally take a depth-1 EXPRESS LANE: dispatched immediately
at bucket 1 and collected before the next dequeue, skipping the
in-flight ring entirely. Every ladder bucket of a kernel family is
eagerly warmed on that family's first dispatch (`_maybe_warm`, gated by
ES_TPU_BUCKET_WARMUP), so bucket selection never compiles on the
steady-state hot path.

Collection mode follows ES semantics (QueryPhase + WANDScorer:
totalHitsThreshold defaults to 10_000): unless the caller asks for
exact totals (`track_total_hits: true`), block-max pruning is the
DEFAULT — hot-term postings blocks that cannot reach the top-k floor
are never gathered. Pruning is engaged per shard only when the capped
total can still be reported truthfully (some term's doc_freq minus the
shard's deleted docs already proves ≥ cap matches); the response then
carries relation "gte" exactly like Lucene's TotalHits.GREATER_THAN_OR_
EQUAL_TO.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple, Union

import numpy as np

from ..common.faults import faults
from ..common.settings import batch_buckets, bucket_for, bucket_warmup
from ..index.mapping import SPARSE_VECTOR, TEXT
from ..ops import scoring
from ..ops.scoring import BPAD
from . import dsl
from .admission import admission
from .executor import Hit, TopDocs
from .failures import SearchTimeoutError

MAX_BATCH = BPAD

# every live QueryBatcher (tier-1 leak fixture: a CLOSED batcher must
# leave no worker threads behind)
live_batchers: "weakref.WeakSet[QueryBatcher]" = weakref.WeakSet()

# bounded dispatcher queue: ES's search threadpool has a bounded queue
# (default 1000) and rejects overflow with EsRejectedExecutionException
# (HTTP 429) rather than buffering unboundedly
QUEUE_CAPACITY = 2048


class EsRejectedExecutionError(Exception):
    """search queue overflow → HTTP 429 (EsRejectedExecutionException).
    Deliberately NOT a RuntimeError: the shard search path treats
    RuntimeError as 'batcher closed, fall back to unbatched', which
    would defeat the backpressure."""

    status = 429
    err_type = "es_rejected_execution_exception"


@dataclass(frozen=True)
class MatchPlan:
    """A query reduced to flat weighted terms over one text field."""

    field: str
    terms: Tuple[str, ...]
    msm: int  # minimum matching terms (1 = OR, len(terms) = AND)
    boost: float
    # None = exact totals required; 0 = totals not tracked at all
    # (track_total_hits: false); N > 0 = totals capped at N (the ES
    # default is 10_000)
    tth_cap: Optional[int]

    @property
    def wand_ok(self) -> bool:
        """Pruning is sound only for pure disjunctions without an exact
        total requirement (WANDScorer: minShouldMatch == 1)."""
        return self.msm == 1 and self.tth_cap is not None


def extract_match_plan(
    query, mappings, analysis, tth: Union[bool, int] = 10_000
) -> Optional[MatchPlan]:
    """Returns a MatchPlan when `query` is a match query over a text
    field (the hot REST shape), else None → normal executor path."""
    if not isinstance(query, dsl.MatchQuery):
        return None
    mf = mappings.get(query.field)
    if mf is None or mf.type != TEXT:
        return None
    analyzer_name = query.analyzer or mf.search_analyzer or mf.analyzer
    try:
        terms = analysis.get(analyzer_name).terms(query.query)
    except ValueError:
        return None
    if not terms:
        return None
    if query.operator == "and":
        msm = len(terms)
    else:
        msm = max(
            1, dsl.parse_minimum_should_match(query.minimum_should_match, len(terms))
        )
    if tth is True:
        cap: Optional[int] = None
    elif tth is False:
        cap = 0
    else:
        cap = max(1, int(tth))
    return MatchPlan(
        field=query.field,
        terms=tuple(terms),
        msm=msm,
        boost=query.boost,
        tth_cap=cap,
    )


@dataclass(frozen=True)
class FieldGroup:
    """One field's flat term list: (term, boost_multiplier, counted).
    `counted` terms contribute to the match-count threshold (bool MUST
    clauses); uncounted terms only score (bool SHOULD next to a must)."""

    field: str
    terms: Tuple[Tuple[str, float, bool], ...]


@dataclass(frozen=True)
class ServePlan:
    """A bool / multi_match query reduced to per-field weighted-term
    groups for the multi-field fused kernel (round-5 extension of
    MatchPlan; BASELINE configs 2 and 3)."""

    groups: Tuple[FieldGroup, ...]
    msm: int  # threshold over counted terms
    combine: str  # "sum" (bool, most_fields) | "max_tie" (best_fields)
    tie: float
    boost: float

    @property
    def fields(self) -> Tuple[str, ...]:
        return tuple(g.field for g in self.groups)


@dataclass(frozen=True)
class KnnPlan:
    """A bare top-level knn section (no filter/threshold): batched
    brute-force matmul per segment (BASELINE config 4), or — when `ann`
    carries a resolved search/ann.AnnSpec — the IVF probed path over
    the same launch/merge plumbing. `ann` rides the group key, so exact
    and probed jobs (or different probe widths) never share a launch."""

    field: str
    vector: Tuple[float, ...]
    k: int
    num_candidates: int
    boost: float
    ann: Optional[object] = None


@dataclass(frozen=True)
class SparsePlan:
    """A bare `sparse_vector` query: batched impact-tile launches per
    segment (ops/impact.py) with impact-ordered block-max pruning.
    Query weights arrive boost-folded (float32, exactly as the host
    oracle folds them) and term-sorted — the canonical accumulation
    order both paths share, which is what keeps the fp32 device path
    bit-equal to the oracle. `spec` (search/sparse.SparseSpec) rides
    the group key, so int8 and fp32 servings never share a launch."""

    field: str
    terms: Tuple[str, ...]
    weights: Tuple[float, ...]
    spec: object


def extract_sparse_plan(query, mappings) -> Optional[SparsePlan]:
    """Returns a SparsePlan when `query` is a bare sparse_vector query
    over a sparse_vector field with a resolved SparseSpec (the hot REST
    shape), else None → normal executor path (host oracle)."""
    if not isinstance(query, dsl.SparseVectorQuery):
        return None
    mf = mappings.get(query.field)
    if mf is None or mf.type != SPARSE_VECTOR:
        return None
    spec = getattr(query, "sparse", None)
    if spec is None:
        return None
    boost = np.float32(query.boost)
    items = sorted(query.query_vector.items())
    return SparsePlan(
        field=query.field,
        terms=tuple(t for t, _ in items),
        weights=tuple(
            float(np.float32(boost * np.float32(w))) for _, w in items
        ),
        spec=spec,
    )


def _clause_terms(q, mappings, analysis) -> Optional[Tuple[str, List[str], float]]:
    """(field, analyzed terms, boost) for a match/term clause on a text
    field, or None when the clause can't ride the fused plan."""
    if isinstance(q, dsl.MatchQuery):
        mf = mappings.get(q.field)
        if mf is None or mf.type != TEXT:
            return None
        if q.minimum_should_match is not None:
            return None
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        try:
            terms = analysis.get(analyzer_name).terms(q.query)
        except ValueError:
            return None
        if not terms or (q.operator == "and" and len(terms) > 1):
            # a multi-term AND clause needs clause-local counting the
            # flat plan can't express
            return None
        return q.field, terms, q.boost
    if isinstance(q, dsl.TermQuery):
        mf = mappings.get(q.field)
        if mf is None or mf.type != TEXT:
            return None
        return q.field, [dsl.term_token(q.value)], q.boost
    return None


def extract_serve_plan(
    query, mappings, analysis
) -> Optional[ServePlan]:
    """Reduces a bool (must/should of single-field text clauses) or a
    multi_match (best_fields/most_fields, operator=or) to a ServePlan
    for the multi-field fused kernel. None → normal executor path.

    Count semantics (the flat-plan subset of BooleanQuery):
      * must clauses must be single-term → each term counted, msm = #must
      * should clauses score only (uncounted) when musts exist; with no
        must, all terms counted and msm = minimum_should_match (default
        1), rejecting multi-term clauses when msm > 1 (clause-level vs
        term-level counting diverges there).
    """
    if isinstance(query, dsl.TermQuery):
        # a bare term on a text field is a one-term plan — without this
        # it would take the unbatched path and pay the full per-segment
        # mask download (VERDICT r3 weak #3)
        got = _clause_terms(query, mappings, analysis)
        if got is None:
            return None
        field, terms, _ = got
        return ServePlan(
            groups=(
                FieldGroup(field=field, terms=((terms[0], 1.0, True),)),
            ),
            msm=1,
            combine="sum",
            tie=0.0,
            boost=query.boost,
        )
    if isinstance(query, dsl.BoolQuery):
        if query.must_not or query.filter:
            return None
        if query.must and query.minimum_should_match is not None:
            return None  # msm-on-should next to must: clause-level count
        groups: Dict[str, List[Tuple[str, float, bool]]] = {}
        n_counted = 0
        if query.must:
            for c in query.must:
                got = _clause_terms(c, mappings, analysis)
                if got is None or len(got[1]) != 1:
                    return None  # multi-term must → clause-local OR
                field, terms, cb = got
                groups.setdefault(field, []).append((terms[0], cb, True))
                n_counted += 1
            for c in query.should:
                got = _clause_terms(c, mappings, analysis)
                if got is None:
                    return None
                field, terms, cb = got
                for t in terms:
                    groups.setdefault(field, []).append((t, cb, False))
            msm = n_counted
        else:
            if not query.should:
                return None
            msm_req = dsl.parse_minimum_should_match(
                query.minimum_should_match, len(query.should)
            )
            if query.minimum_should_match is not None and msm_req <= 0:
                # explicit msm of 0 means every doc matches (the oracle
                # applies no count mask) — not expressible here
                return None
            multi_ok = msm_req <= 1
            for c in query.should:
                got = _clause_terms(c, mappings, analysis)
                if got is None:
                    return None
                field, terms, cb = got
                if len(terms) > 1 and not multi_ok:
                    return None
                for t in terms:
                    groups.setdefault(field, []).append((t, cb, True))
            msm = max(1, msm_req)
        if not groups:
            return None
        return ServePlan(
            groups=tuple(
                FieldGroup(field=f, terms=tuple(ts))
                for f, ts in groups.items()
            ),
            msm=msm,
            combine="sum",
            tie=0.0,
            boost=query.boost,
        )
    if isinstance(query, dsl.MultiMatchQuery):
        if query.type not in ("best_fields", "most_fields"):
            return None
        if query.operator == "and":
            return None
        from .executor import expand_match_fields

        groups_l: List[FieldGroup] = []
        for field, fboost in expand_match_fields(mappings, query.fields):
            mf = mappings.get(field)
            if mf is None or mf.type != TEXT:
                return None
            analyzer_name = mf.search_analyzer or mf.analyzer
            try:
                terms = analysis.get(analyzer_name).terms(query.query)
            except ValueError:
                return None
            if not terms:
                continue
            groups_l.append(
                FieldGroup(
                    field=field,
                    terms=tuple((t, fboost, True) for t in terms),
                )
            )
        if not groups_l:
            return None
        return ServePlan(
            groups=tuple(groups_l),
            msm=1,
            combine=(
                "sum" if query.type == "most_fields" else "max_tie"
            ),
            tie=float(query.tie_breaker or 0.0),
            boost=query.boost,
        )
    return None


def split_filtered_bool(query):
    """(scoring-only bool, filter clauses) when `query` is a bool whose
    filter clauses can be peeled off into a cached bitset while the
    scoring part keeps its exact semantics; None otherwise.

    The split is semantics-preserving only when the effective
    minimum_should_match does not depend on the filters' presence:
    with must clauses (or an explicit msm) the default is identical
    either way; a should-only bool with filters defaults to msm 0,
    which the stripped bool would flip to 1 — not splittable."""
    if not isinstance(query, dsl.BoolQuery) or not query.filter:
        return None
    if query.must_not:
        return None
    if not (query.must or query.should):
        return None  # pure filter: constant-score, generic path covers it
    if not query.must and query.minimum_should_match is None:
        return None
    stripped = dsl.BoolQuery(
        boost=query.boost,
        must=list(query.must),
        should=list(query.should),
        filter=[],
        must_not=[],
        minimum_should_match=query.minimum_should_match,
    )
    return stripped, list(query.filter)


def extract_knn_plan(knn_sections, mappings) -> Optional[KnnPlan]:
    """A single bare knn section (no filter, no similarity threshold)
    rides the batched matmul launch. A dims mismatch stays OFF the
    shared launch so one malformed request can't fail a whole group."""
    if knn_sections is None or len(knn_sections) != 1:
        return None
    sec = knn_sections[0]
    if sec.filter is not None or sec.similarity is not None:
        return None
    mf = mappings.get(sec.field)
    dims = getattr(mf, "dims", None) if mf is not None else None
    if dims is not None and len(sec.query_vector) != int(dims):
        return None
    return KnnPlan(
        field=sec.field,
        vector=tuple(float(x) for x in sec.query_vector),
        k=int(sec.k),
        num_candidates=int(sec.num_candidates),
        boost=float(sec.boost),
        ann=getattr(sec, "ann", None),
    )


class _Job:
    """A submitted query: the batcher's FUTURE handle. `submit_nowait`
    returns one immediately; `QueryBatcher.wait(job)` blocks for the
    result. One request thread can hold several in-flight jobs (the
    hybrid BM25 + kNN legs) and collect them in any order."""

    __slots__ = (
        "executor", "kind", "plan", "k", "query", "event", "result",
        "error", "deadline", "t_enq", "prof",
    )

    def __init__(
        self, executor, plan, k: int, kind: str = "match", query=None,
        deadline: Optional[float] = None, prof=None,
    ):
        self.executor = executor
        self.kind = kind  # "match" | "serve" | "knn"
        self.plan = plan
        self.k = k
        self.query = query  # parsed Query node for per-segment fallback
        self.event = threading.Event()
        self.result: Optional[TopDocs] = None
        self.error: Optional[BaseException] = None
        # monotonic deadline (the shard's search-timeout budget): a job
        # still queued past it is dropped at dequeue, never dispatched
        self.deadline = deadline
        self.t_enq = time.monotonic()
        # "profile": true — a shared mutable dict the dispatch/collect
        # phases write per-family timing into (None = unprofiled; the
        # submitter owns the dict and reads it after wait())
        self.prof = prof

    def done(self) -> bool:
        return self.event.is_set()


class _BatchCtx:
    """One dispatched batch in the worker's in-flight ring: the jobs it
    carries plus the async serve/knn groups awaiting collect."""

    __slots__ = ("batch", "pending")

    def __init__(self, batch: List[_Job]):
        self.batch = batch
        self.pending: List[Tuple] = []  # (key, jobs, fam, pend, dev_ids)


WORKERS = 6  # parallel dispatcher pipelines (the device tunnel overlaps
# concurrent round trips — see ops/scoring.py module comment)


class QueryBatcher:
    """Dispatcher pipelines per index: REST worker threads submit jobs
    and block; workers score whole groups in shared one-round-trip
    launches. Several workers run concurrently so device round trips
    overlap (continuous batching × pipelining).

    Submission is a FUTURE API: `submit_nowait()` returns a job handle
    immediately and `wait(handle)` collects, so one request can hold
    several legs in flight at once (hybrid BM25 + kNN). Workers split
    serve/kNN groups into an async device-dispatch phase and a blocking
    collect phase, so the legs' kernels launch back-to-back with no
    host sync between them."""

    def __init__(
        self,
        max_batch: int = MAX_BATCH,
        workers: int = WORKERS,
        queue_capacity: int = QUEUE_CAPACITY,
        pipeline_depth: Optional[int] = None,
    ):
        from ..common.settings import pipeline_depth as _default_depth

        # pad-bucket launch ladder (ES_TPU_BATCH_BUCKETS): dispatched
        # groups pad to the smallest bucket >= occupancy; the top of
        # the ladder bounds how many jobs one batch may carry
        self.buckets = batch_buckets(BPAD)
        # eager per-family bucket warmup on first dispatch (mutable per
        # instance; tier-1 pins the env off, tests re-arm per batcher)
        self.warmup_enabled = bucket_warmup()
        self.max_batch = min(max_batch, BPAD, self.buckets[-1])
        self.workers = workers
        # in-flight ring bound per worker (ES_TPU_PIPELINE_DEPTH):
        # depth=1 is the classic dispatch→collect loop; depth=2 double-
        # buffers so batch N+1's kernels launch while batch N's hits are
        # built on the host. Mutable at runtime (bench A/B runs).
        self.pipeline_depth = (
            max(1, int(pipeline_depth))
            if pipeline_depth is not None
            else _default_depth()
        )
        self._queue: "queue.Queue[_Job]" = queue.Queue(maxsize=queue_capacity)
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._lock = threading.Lock()
        # MFU/roofline accounting (guarded by self._lock): estimated
        # useful flops dispatched, wall time with >= 1 batch in flight
        # on device (union of dispatch→collect intervals), and time
        # workers spent blocked on device→host downloads
        self._flops = 0
        self._ring_inflight = 0
        self._busy_t0 = 0.0
        self._device_busy_s = 0.0
        self._host_stall_s = 0.0
        live_batchers.add(self)
        # observability: how many launches / jobs / batched jobs
        self.stats = {
            "launches": 0,
            "jobs": 0,
            "max_batch_seen": 0,
            "pruned_jobs": 0,
            "fused_jobs": 0,
            "rejected": 0,
            # a fused-slot overflow silently falling to the chunked/
            # fallback path would hide a Zipf-tail regression (VERDICT
            # r3 weak #9) — count it
            "fused_overflow_jobs": 0,
            # times a kNN group and a text (match/serve) group were in
            # flight on device simultaneously — the observable proof
            # that hybrid legs overlap instead of serializing
            "hybrid_overlap_events": 0,
            # overload protection: jobs dropped at dequeue because
            # their deadline budget was already spent (never launched)
            # and jobs cancelled while still queued (task cancel)
            "shed_dead_jobs": 0,
            "cancelled_jobs": 0,
            # continuous batching: lone queries dispatched depth-1 on an
            # idle worker (bucket-1 launch, collected before the next
            # dequeue — the interactive-latency fast path)
            "express_lane_hits": 0,
            # device-aggregations job family (size:0/agg bodies riding
            # the dispatch/collect pipeline as segment-sum launches)
            "agg_jobs": 0,
            # second-stage rerank job family (rescore bodies riding the
            # dispatch/collect pipeline as maxsim launches between
            # merge and fetch)
            "rerank_jobs": 0,
            # learned-sparse job family (bare sparse_vector bodies
            # riding the dispatch/collect pipeline as impact-tile
            # launches with block-max pruning)
            "sparse_jobs": 0,
        }
        # per-bucket launch histogram + occupancy sums (guarded by
        # self._lock; surfaced via batching_stats() → _nodes/stats):
        # padding waste becomes a measured number instead of a guess
        self._bucket_launches: Dict[int, int] = {}
        self._occ_jobs = 0
        self._occ_slots = 0
        # (family-signature) keys whose bucket ladder is already warmed,
        # plus a count of warm loops still running (the warm runs on the
        # worker AFTER the triggering group's waiters complete, so it is
        # asynchronous to every caller; wait_warm_idle() lets tests and
        # benchmarks quiesce before probing compile caches)
        self._warmed: set = set()
        self._warm_inflight = 0
        # family → groups currently dispatched-but-not-collected,
        # across ALL workers (guarded by self._lock)
        self._inflight = {
            "text": 0, "knn": 0, "agg": 0, "rerank": 0, "sparse": 0,
        }
        # per-device roofline accounting (straggler visibility): device
        # id → [inflight_groups, busy_t0, busy_s, flops]; single-device
        # groups attribute to device 0, mesh groups to every device in
        # the mesh (guarded by self._lock)
        self._devs: Dict[int, list] = {}
        # per-worker profiling scratch: while a profiled group
        # dispatches, `group_flops` accumulates the flops the group's
        # launches report via _add_flops (thread-local — each worker
        # dispatches one group at a time)
        self._tl = threading.local()

    def _ensure_thread(self):
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            while len(self._threads) < self.workers:
                t = threading.Thread(
                    target=self._run,
                    name=f"query-batcher-{len(self._threads)}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

    def close(self):
        self._closed = True
        # fail anything still queued so no submitter blocks forever —
        # BEFORE posting wake sentinels, so the drain cannot eat them
        # and leave a worker blocked in queue.get() forever
        self._drain_queue(RuntimeError("query batcher closed"))
        for _ in self._threads:
            try:
                self._queue.put_nowait(None)  # wake blocked workers
            except queue.Full:  # pragma: no cover - submitters raced
                break
        # wait the workers out (bounded): a daemon worker still inside
        # a device dispatch when the interpreter finalizes takes the
        # process down with a C++ terminate, not a Python exception
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        self._threads = []

    def _drain_queue(self, err: BaseException):
        while True:
            try:
                j = self._queue.get_nowait()
            except queue.Empty:
                break
            if j is not None and not j.event.is_set():
                j.error = err
                j.event.set()

    # ---- client side (async future API) ----

    def submit_nowait(
        self, executor, plan, k: int, kind: str = "match", query=None,
        deadline: Optional[float] = None, prof=None,
    ) -> _Job:
        """Enqueues a job and returns its future handle WITHOUT waiting.
        Raises EsRejectedExecutionError (429) on queue overflow — the
        async path gets the same backpressure as the blocking one. A
        request thread submits every leg it needs first, then collects
        with `wait(handle)`, so independent legs (hybrid BM25 + kNN)
        execute concurrently. `deadline` (monotonic seconds) is the
        shard's timeout budget: a job still queued past it is dropped
        at dequeue instead of dispatched dead."""
        if self._closed:
            raise RuntimeError("query batcher closed")
        job = _Job(executor, plan, k, kind=kind, query=query,
                   deadline=deadline, prof=prof)
        self._ensure_thread()
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            with self._lock:
                self.stats["rejected"] += 1
            raise EsRejectedExecutionError(
                f"rejected execution: search queue capacity "
                f"[{self._queue.maxsize}] reached"
            )
        if self._closed:
            # lost the race with close(): make sure nobody hangs
            self.close()
        return job

    # historical name; same semantics (the return value was always a
    # handle — submit_nowait formalizes it as the public future API)
    submit = submit_nowait

    def execute(
        self, executor, plan, k: int, kind: str = "match", query=None
    ) -> TopDocs:
        job = self.submit_nowait(executor, plan, k, kind=kind, query=query)
        return self.wait(job)

    @staticmethod
    def wait(job: _Job, timeout: Optional[float] = None) -> TopDocs:
        if not job.event.wait(timeout):
            raise TimeoutError("batched query did not complete in time")
        if job.error is not None:
            raise job.error
        return job.result

    def wait_or_cancel(
        self, job: _Job, timeout: Optional[float] = None
    ) -> TopDocs:
        """wait() that never abandons the job on timeout: a bare
        wait(timeout) leaves a timed-out job queued, where it can later
        dispatch into a waiter that already gave up — wasted device work
        and a completion nobody reads. Here the timeout cancels the job
        first (the dequeue-time gate then drops it — it never launches)
        and only then propagates TimeoutError."""
        try:
            return self.wait(job, timeout)
        except TimeoutError:
            self.cancel(
                job,
                error=TimeoutError(
                    "batched query did not complete in time"
                ),
            )
            raise

    def cancel(self, job: _Job, error: Optional[BaseException] = None) -> bool:
        """Fails a still-pending job's waiter (a task cancel landing
        before dispatch): the dequeue-time gate then drops the job from
        the queue, so it never launches. Returns False when the job
        already completed. A job whose dispatch already started still
        runs on device, but its waiter is failed and the completion
        paths leave the error in place (error wins in wait())."""
        if job.event.is_set():
            return False
        if error is None:
            from ..tasks import TaskCancelledException

            error = TaskCancelledException(
                "task cancelled [search job cancelled before dispatch]"
            )
        with self._lock:
            self.stats["cancelled_jobs"] += 1
        job.error = error
        job.event.set()  # wake AFTER the stats update (observable order)
        return True

    def _admit_job(self, j: _Job) -> bool:
        """Dequeue-time gate: cancelled jobs (waiter already failed) are
        dropped, and a job whose deadline budget is already spent fails
        its waiter with a timeout instead of dispatching dead — the
        overload-protection contract that queued work past its deadline
        never reaches the device."""
        if j.event.is_set():
            return False
        if j.deadline is not None and time.monotonic() > j.deadline:
            with self._lock:
                self.stats["shed_dead_jobs"] += 1
            j.error = SearchTimeoutError(
                "batched query deadline expired while queued"
            )
            j.event.set()  # wake AFTER the stats update (observable order)
            return False
        return True

    # ---- worker side (pipelined: dispatch ring + deferred collect) ----

    def _run(self):
        # bounded in-flight ring: each entry is a dispatched batch whose
        # serve/knn device results have not been collected yet. With
        # pipeline_depth=1 this is exactly the classic loop (dispatch,
        # then immediately collect); with depth=2 the worker dispatches
        # batch N+1 while batch N's kernels are still on device and
        # collects N afterwards, so the device never waits for the
        # host-side hit building of the previous batch.
        inflight: Deque[_BatchCtx] = deque()
        try:
            while not self._closed:
                if inflight:
                    # never block on the queue while batches are in
                    # flight: their waiters come first when idle
                    try:
                        job = self._queue.get_nowait()
                    except queue.Empty:
                        self._collect_batch(inflight.popleft())
                        continue
                else:
                    job = self._queue.get()
                if job is None:
                    continue
                if self._closed:
                    if not job.event.is_set():
                        job.error = RuntimeError("query batcher closed")
                        job.event.set()
                    continue
                if not self._admit_job(job):
                    continue
                batch = [job]
                while len(batch) < self.max_batch:
                    try:
                        j = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if j is not None and self._admit_job(j):
                        batch.append(j)
                if len(batch) == 1 and not inflight:
                    # express lane: a lone query on an idle worker skips
                    # the in-flight ring — dispatch at bucket 1, collect
                    # before the next dequeue. Depth-1 semantics for the
                    # latency-critical empty-queue case; under load the
                    # drain above yields batch > 1 and the ring engages.
                    with self._lock:
                        self.stats["express_lane_hits"] += 1
                    self._collect_batch(
                        self._dispatch_batch(batch, express=True)
                    )
                    continue
                inflight.append(self._dispatch_batch(batch))
                while len(inflight) >= max(1, self.pipeline_depth):
                    self._collect_batch(inflight.popleft())
        finally:
            # the dispatcher thread is exiting (close() or a crash
            # outside the per-group guard): nobody may block forever —
            # in-flight batches fail their waiters instead of hanging
            err = RuntimeError("query batcher closed")
            while inflight:
                ctx = inflight.popleft()
                for _, jobs, fam, _, dev_ids in ctx.pending:
                    self._exit_kind(fam)
                    self._dev_exit(dev_ids)
                for j in ctx.batch:
                    if not j.event.is_set():
                        j.error = err
                        j.event.set()
                self._ring_exit()
            self._drain_queue(RuntimeError("query batcher worker exited"))
            if self._closed:
                # the drain above may have eaten peers' wake sentinels:
                # cascade one forward so every blocked worker exits
                try:
                    self._queue.put_nowait(None)
                except queue.Full:  # pragma: no cover
                    pass

    def _dispatch_batch(
        self, batch: List[_Job], express: bool = False
    ) -> "_BatchCtx":
        """Groups a batch and launches all its device work. serve/knn
        groups dispatch asynchronously (collected later by
        _collect_batch); match groups run dispatch+collect fused (their
        pruning rounds are host-dependent) AFTER the async dispatches,
        so their host syncs overlap the in-flight serve/knn kernels
        instead of stalling them. Never raises: failures surface to the
        affected jobs' waiters."""
        ctx = _BatchCtx(batch)
        self._ring_enter()
        try:
            # congestion signal for the admission layer's AIMD limit:
            # the worst enqueue→dispatch wait in this batch (the
            # "queue delay vs target" the adaptive limit steers on)
            now = time.monotonic()
            admission.observe_queue_delay(
                max(now - j.t_enq for j in batch)
            )
            with self._lock:
                self.stats["jobs"] += len(batch)
                self.stats["max_batch_seen"] = max(
                    self.stats["max_batch_seen"], len(batch)
                )
            # group jobs that can share launches (same reader
            # generation, plan family, and top-k compile bucket);
            # mesh_* families group whole-index query batches on the
            # MeshExecutor (B queries × all shards in one SPMD program)
            groups: Dict[Tuple, List[_Job]] = {}
            for j in batch:
                kb = 16 if j.k <= 16 else scoring.next_bucket(j.k, 16)
                if j.kind == "match":
                    key = (id(j.executor), "m", j.plan.field, kb)
                elif j.kind == "serve":
                    key = (
                        id(j.executor), "s", j.plan.fields,
                        j.plan.combine, j.plan.tie, kb,
                    )
                elif j.kind == "mesh_match":
                    # a fused mesh rescore rides the plan (rescore_sig
                    # None for plain match): different specs / page
                    # sizes never share an SPMD launch
                    key = (
                        id(j.executor), "Mm", j.plan.field,
                        getattr(j.plan, "rescore_sig", None), kb,
                    )
                elif j.kind == "mesh_serve":
                    key = (
                        id(j.executor), "Ms", j.plan.fields,
                        j.plan.combine, j.plan.tie, kb,
                    )
                elif j.kind == "mesh_knn":
                    key = (id(j.executor), "Mk", j.plan.field, j.plan.ann, kb)
                elif j.kind == "mesh_sparse":
                    key = (
                        id(j.executor), "Mv", j.plan.field, j.plan.spec, kb,
                    )
                elif j.kind == "agg":
                    # device-aggregations family: jobs group by the
                    # compiled plan's structural signature so identical
                    # dashboard shapes share one dispatch slot
                    key = (id(j.executor), "a", j.plan.sig, kb)
                elif j.kind == "rerank":
                    # second-stage rerank family: jobs share a maxsim
                    # launch when model, padded window/query-token
                    # shapes, static window, and blend weights agree
                    key = (id(j.executor), "r", j.plan.sig, kb)
                elif j.kind == "sparse":
                    # learned-sparse family: the frozen SparseSpec rides
                    # the key so int8 and fp32 servings of one field
                    # never share a launch
                    key = (id(j.executor), "v", j.plan.field, j.plan.spec, kb)
                elif j.kind == "mesh_agg":
                    key = (id(j.executor), "Ma", j.plan.sig, kb)
                else:  # knn (exact and IVF-probed jobs never share;
                    # kb stays LAST — dispatch reads it as key[-1])
                    key = (id(j.executor), "k", j.plan.field, j.plan.ann, kb)
                groups.setdefault(key, []).append(j)
            ordered = sorted(
                groups.items(), key=lambda kv: kv[0][1] == "m"
            )
            for key, jobs in ordered:
                kind, kb = key[1], key[-1]
                mesh = kind in ("Mm", "Ms", "Mk", "Ma", "Mv")
                if kind in ("k", "Mk"):
                    fam = "knn"
                elif kind in ("a", "Ma"):
                    fam = "agg"
                elif kind == "r":
                    fam = "rerank"
                elif kind in ("v", "Mv"):
                    fam = "sparse"
                else:
                    fam = "text"
                # pad-bucket ladder: the group's launch width is the
                # smallest compiled bucket covering its occupancy —
                # mesh groups pick theirs internally (the data-axis
                # divisibility constraint lives there)
                rows = None if mesh else bucket_for(len(jobs), self.buckets)
                dev_ids: Tuple[int, ...] = (0,)
                dev_entered = False
                self._enter_kind(fam)
                dispatched = False
                # "profile": true — arm the per-group scratch only when
                # a job in the group carries a prof dict (zero cost on
                # the unprofiled path beyond this any())
                prof_on = any(j.prof is not None for j in jobs)
                if prof_on:
                    self._tl.group_flops = 0
                    t_prof = time.perf_counter_ns()
                try:
                    if not mesh:
                        self._dev_enter(dev_ids)
                        dev_entered = True
                    # fault site: an injected dispatch failure surfaces
                    # to exactly this group's waiters, not the batch
                    faults.check(
                        "batcher.dispatch", family=fam, jobs=len(jobs),
                        mesh=int(mesh),
                    )
                    if kind == "m":
                        # record BEFORE dispatch: match groups complete
                        # their waiters inside _run_group, and a waiter
                        # must never observe its own launch missing
                        # from the histogram — the profile mark rides a
                        # callback for the same reason (it must land
                        # before the events fire, and before warm loops:
                        # bucket warming is compile time, not this
                        # query's time)
                        self._record_bucket(rows, len(jobs))
                        cb = None
                        if prof_on:
                            cb = (lambda j=jobs, r=rows, t=t_prof, n=now,
                                  e=express: self._prof_mark(j, r, t, n, e))
                        self._run_group(jobs, key[2], kb, rows=rows,
                                        prof_cb=cb)
                        self._maybe_warm(key, jobs, kb, rows)
                    elif kind == "s":
                        self._record_bucket(rows, len(jobs))
                        ctx.pending.append(
                            (key, jobs, fam,
                             self._dispatch_serve_group(jobs, kb, rows=rows),
                             dev_ids)
                        )
                        dispatched = True
                        if prof_on:
                            self._prof_mark(jobs, rows, t_prof, now,
                                            express)
                        self._maybe_warm(key, jobs, kb, rows)
                    elif kind == "k":
                        self._record_bucket(rows, len(jobs))
                        ctx.pending.append(
                            (key, jobs, fam,
                             self._dispatch_knn_group(jobs, rows=rows),
                             dev_ids)
                        )
                        dispatched = True
                        if prof_on:
                            self._prof_mark(jobs, rows, t_prof, now,
                                            express)
                        self._maybe_warm(key, jobs, kb, rows)
                    elif kind == "a":
                        ctx.pending.append(
                            (key, jobs, fam,
                             self._dispatch_agg_group(jobs), dev_ids)
                        )
                        dispatched = True
                        if prof_on:
                            self._prof_mark(jobs, rows, t_prof, now,
                                            express)
                    elif kind == "r":
                        self._record_bucket(rows, len(jobs))
                        ctx.pending.append(
                            (key, jobs, fam,
                             self._dispatch_rerank_group(jobs, rows=rows),
                             dev_ids)
                        )
                        dispatched = True
                        if prof_on:
                            self._prof_mark(jobs, rows, t_prof, now,
                                            express)
                    elif kind == "v":
                        self._record_bucket(rows, len(jobs))
                        ctx.pending.append(
                            (key, jobs, fam,
                             self._dispatch_sparse_group(jobs, kb,
                                                         rows=rows),
                             dev_ids)
                        )
                        dispatched = True
                        if prof_on:
                            self._prof_mark(jobs, rows, t_prof, now,
                                            express)
                        self._maybe_warm(key, jobs, kb, rows)
                    else:
                        mex = jobs[0].executor
                        if kind == "Mm":
                            pend = mex.dispatch_match(jobs, kb)
                        elif kind == "Ms":
                            pend = mex.dispatch_serve(jobs, kb)
                        elif kind == "Ma":
                            pend = mex.dispatch_agg(jobs)
                        elif kind == "Mv":
                            pend = mex.dispatch_sparse(jobs, kb)
                        else:
                            pend = mex.dispatch_knn(jobs, kb)
                        # the busy window opens on the devices the
                        # snapshot actually spans
                        dev_ids = mex.device_ids
                        self._dev_enter(dev_ids)
                        dev_entered = True
                        with self._lock:
                            self.stats["launches"] += 1
                            self.stats["fused_jobs"] += len(jobs)
                        self._add_flops(pend["flops"], dev_ids)
                        self._record_bucket(
                            pend.get("rows", BPAD), len(jobs)
                        )
                        ctx.pending.append((key, jobs, fam, pend, dev_ids))
                        dispatched = True
                        if prof_on:
                            self._prof_mark(
                                jobs, pend.get("rows", BPAD), t_prof,
                                now, express,
                            )
                except BaseException as e:  # surface to waiters
                    for j in jobs:
                        if not j.event.is_set():
                            j.error = e
                            j.event.set()
                finally:
                    if prof_on:
                        self._tl.group_flops = None
                    if not dispatched:
                        self._exit_kind(fam)
                        if dev_entered:
                            self._dev_exit(dev_ids)
        except BaseException as e:
            # stats/grouping crash between dequeue and the per-group
            # guard: already-dequeued jobs are not in the queue, so the
            # finally-drain can't reach them — fail them here so no
            # submitter blocks forever (already-dispatched groups still
            # collect normally)
            for j in batch:
                if not j.event.is_set():
                    j.error = e
                    j.event.set()
        return ctx

    def _collect_batch(self, ctx: "_BatchCtx"):
        """Host side of one dispatched batch: transfer the merged device
        results and finish the waiters. Never raises."""
        try:
            for key, jobs, fam, pend, dev_ids in ctx.pending:
                kind = key[1]
                prof_on = any(j.prof is not None for j in jobs)
                tc0 = time.perf_counter_ns() if prof_on else 0
                try:
                    # fault site: a collect-phase failure (device→host
                    # transfer) fails this group's waiters only
                    faults.check(
                        "batcher.collect", family=fam, jobs=len(jobs),
                        mesh=int(kind in ("Mm", "Ms", "Mk", "Mv")),
                    )
                    if kind == "s":
                        self._collect_serve_group(jobs, key[-1], pend)
                    elif kind == "k":
                        self._collect_knn_group(jobs, pend)
                    elif kind == "a":
                        self._collect_agg_group(jobs, pend)
                    elif kind == "r":
                        self._collect_rerank_group(jobs, pend)
                    elif kind == "v":
                        self._collect_sparse_group(jobs, key[-1], pend)
                    elif kind in ("Mm", "Ms"):
                        t0 = time.perf_counter()
                        jobs[0].executor.collect_match(jobs, pend)
                        self._add_stall(time.perf_counter() - t0)
                    elif kind == "Mk":
                        t0 = time.perf_counter()
                        jobs[0].executor.collect_knn(jobs, pend)
                        self._add_stall(time.perf_counter() - t0)
                    elif kind == "Ma":
                        t0 = time.perf_counter()
                        jobs[0].executor.collect_agg(jobs, pend)
                        self._add_stall(time.perf_counter() - t0)
                    elif kind == "Mv":
                        t0 = time.perf_counter()
                        jobs[0].executor.collect_sparse(jobs, pend)
                        self._add_stall(time.perf_counter() - t0)
                    else:
                        self._collect_knn_group(jobs, pend)
                    if prof_on:
                        self._prof_collect(jobs, tc0)
                except BaseException as e:
                    for j in jobs:
                        if not j.event.is_set():
                            j.error = e
                            j.event.set()
                finally:
                    self._exit_kind(fam)
                    self._dev_exit(dev_ids)
        finally:
            ctx.pending = []
            self._ring_exit()

    # ---- pipeline accounting (MFU/roofline) ----

    def _ring_enter(self):
        with self._lock:
            self._ring_inflight += 1
            if self._ring_inflight == 1:
                self._busy_t0 = time.perf_counter()

    def _ring_exit(self):
        with self._lock:
            self._ring_inflight -= 1
            if self._ring_inflight == 0:
                self._device_busy_s += time.perf_counter() - self._busy_t0

    def _add_flops(self, n: int, dev_ids: Tuple[int, ...] = (0,)):
        n = int(n)
        gf = getattr(self._tl, "group_flops", None)
        if gf is not None:
            # a profiled group is dispatching on this worker: credit the
            # flops to it as well as to the node-level roofline counters
            self._tl.group_flops = gf + n
        with self._lock:
            self._flops += n
            if dev_ids:
                share = n // len(dev_ids)
                for i, did in enumerate(dev_ids):
                    d = self._devs.setdefault(did, [0, 0.0, 0.0, 0])
                    d[3] += share + (n - share * len(dev_ids) if i == 0 else 0)

    # ---- per-request profiling ("profile": true) ----

    def _prof_mark(self, jobs, rows, t0_ns, now_mono, express=False):
        """Writes the dispatch-side breakdown of one profiled group into
        every carrying job's prof dict: wall time of the launch, queue
        wait, the group's flops (even share — the launch is shared),
        pad bucket, batch width, and express-lane membership. Entries
        are built aside and dict-swapped in so a reader that races the
        write never observes a half-built entry."""
        dt = time.perf_counter_ns() - t0_ns
        fl = int(getattr(self._tl, "group_flops", 0) or 0)
        self._tl.group_flops = None
        n = max(len(jobs), 1)
        for j in jobs:
            p = j.prof
            if p is None:
                continue
            fams = p.setdefault("families", {})
            prev = fams.get(j.kind)
            e = dict(prev) if prev else {
                "launches": 0, "dispatch_ns": 0, "collect_ns": 0,
                "queue_wait_ns": 0, "flops": 0, "bucket": 0,
                "batch_jobs": 0, "express_lane": False, "pruned": False,
            }
            e["launches"] += 1
            e["dispatch_ns"] += dt
            e["queue_wait_ns"] += max(0, int((now_mono - j.t_enq) * 1e9))
            e["flops"] += fl // n
            e["bucket"] = int(rows or 0)
            e["batch_jobs"] = n
            if express:
                e["express_lane"] = True
            if p.get("pruned_jobs"):
                e["pruned"] = True
            fams[j.kind] = e

    def _prof_collect(self, jobs, t0_ns):
        """Collect-side twin of _prof_mark: adds the device→host
        transfer + host-merge wall time of one profiled group."""
        dt = time.perf_counter_ns() - t0_ns
        for j in jobs:
            p = j.prof
            if p is None:
                continue
            fams = p.setdefault("families", {})
            prev = fams.get(j.kind)
            e = dict(prev) if prev else {
                "launches": 0, "dispatch_ns": 0, "collect_ns": 0,
                "queue_wait_ns": 0, "flops": 0, "bucket": 0,
                "batch_jobs": 0, "express_lane": False, "pruned": False,
            }
            e["collect_ns"] += dt
            if p.get("pruned_jobs"):
                e["pruned"] = True
            fams[j.kind] = e

    def _add_stall(self, seconds: float):
        with self._lock:
            self._host_stall_s += seconds

    # ---- continuous-batching accounting + bucket warmup ----

    def _record_bucket(self, rows: int, njobs: int):
        """One dispatched group: `rows` padded launch width, `njobs`
        real query rows. avg_occupancy = Σjobs / Σslots measures the
        padding waste the bucket ladder leaves behind."""
        rows = int(rows)
        with self._lock:
            self._bucket_launches[rows] = (
                self._bucket_launches.get(rows, 0) + 1
            )
            self._occ_jobs += njobs
            self._occ_slots += rows

    def batching_stats(self) -> dict:
        """The continuous-batching block for `_nodes/stats`: per-bucket
        launch histogram, occupancy sums (raw, so windows can diff),
        and express-lane hits."""
        with self._lock:
            hist = {
                str(b): n
                for b, n in sorted(self._bucket_launches.items())
            }
            jobs, slots = self._occ_jobs, self._occ_slots
            express = self.stats["express_lane_hits"]
        return {
            "buckets": list(self.buckets),
            "launches_by_bucket": hist,
            "occupancy_jobs": jobs,
            "occupancy_slots": slots,
            "avg_occupancy": round(jobs / slots, 4) if slots else 0.0,
            "express_lane_hits": express,
        }

    def _maybe_warm(self, key, jobs: List[_Job], kb: int, rows: int):
        """Eagerly compiles the remaining ladder buckets of this group's
        kernel family the first time the family dispatches, by running
        one dummy job (cloned from the live group's plan) through the
        real group path at every other bucket. Steady-state bucket
        selection then never compiles. Best-effort and stat-silent
        (record=False): warm launches appear in no histogram, flop or
        fault accounting. Gated by ES_TPU_BUCKET_WARMUP / the
        `warmup_enabled` attribute (tier-1 pins it off)."""
        if not self.warmup_enabled or len(self.buckets) <= 1:
            return
        kind = key[1]
        warm_key: Tuple = key
        if kind == "m":
            # the match kernels specialize on the count plane too
            warm_key = key + (any(j.plan.msm > 1 for j in jobs),)
        elif kind == "k":
            # the kNN candidate page is a compile bucket of its own
            warm_key = key + (
                scoring.next_bucket(
                    max(j.plan.num_candidates for j in jobs), 16
                ),
            )
        with self._lock:
            if warm_key in self._warmed:
                return
            self._warmed.add(warm_key)
            self._warm_inflight += 1
        try:
            if kind == "m":
                j0 = next((j for j in jobs if j.plan.msm > 1), jobs[0])
            elif kind == "k":
                j0 = max(jobs, key=lambda j: j.plan.num_candidates)
            else:
                j0 = jobs[0]
            for b in self.buckets:
                if b == rows:
                    continue
                dummy = [
                    _Job(j0.executor, j0.plan, j0.k, kind=j0.kind,
                         query=j0.query)
                ]
                try:
                    if kind == "m":
                        self._run_group(dummy, key[2], kb, rows=b,
                                        record=False)
                    elif kind == "s":
                        pend = self._dispatch_serve_group(
                            dummy, kb, rows=b, record=False
                        )
                        self._collect_serve_group(dummy, kb, pend,
                                                  record=False)
                    elif kind == "v":
                        pend = self._dispatch_sparse_group(
                            dummy, kb, rows=b, record=False
                        )
                        self._collect_sparse_group(dummy, kb, pend,
                                                   record=False)
                    else:
                        pend = self._dispatch_knn_group(
                            dummy, rows=b, record=False
                        )
                        self._collect_knn_group(dummy, pend, record=False)
                except BaseException:
                    # warmup is opportunistic: a failed bucket just
                    # compiles lazily on its first live hit instead
                    pass
        finally:
            with self._lock:
                self._warm_inflight -= 1

    def wait_warm_idle(self, timeout: float = 60.0) -> bool:
        """Blocks until no bucket-warmup loop is running (the warm is
        asynchronous to the triggering request — its waiters complete
        BEFORE the remaining ladder buckets compile). Test/benchmark
        hook: compile-cache probes must quiesce first or they race the
        warm tail. Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._warm_inflight == 0:
                    return True
            time.sleep(0.01)
        return False

    # ---- per-device busy windows (straggler visibility) ----

    def _dev_enter(self, dev_ids: Tuple[int, ...]):
        now = time.perf_counter()
        with self._lock:
            for did in dev_ids:
                d = self._devs.setdefault(did, [0, 0.0, 0.0, 0])
                d[0] += 1
                if d[0] == 1:
                    d[1] = now

    def _dev_exit(self, dev_ids: Tuple[int, ...]):
        now = time.perf_counter()
        with self._lock:
            for did in dev_ids:
                d = self._devs.get(did)
                if d is None:
                    continue
                d[0] -= 1
                if d[0] == 0:
                    d[2] += now - d[1]

    def device_stats(self) -> list:
        """Per-device roofline rows [{id, device_busy_ms, flops, mfu}]
        so one straggler chip is visible next to the aggregate MFU.
        Busy time is the union of this device's group dispatch→collect
        windows; flops split evenly across a mesh group's devices."""
        from ..common.settings import peak_flops

        now = time.perf_counter()
        out = []
        with self._lock:
            for did in sorted(self._devs):
                inflight, t0, busy, flops = self._devs[did]
                if inflight > 0:
                    busy += now - t0
                out.append(
                    {
                        "id": did,
                        "device_busy_ms": round(busy * 1000.0, 3),
                        "flops": int(flops),
                        "mfu": (
                            flops / (busy * peak_flops()) if busy > 0 else 0.0
                        ),
                    }
                )
        return out

    def pipeline_stats(self) -> dict:
        """Snapshot of the serving-pipeline roofline counters.

        device_busy_ms approximates accelerator-occupied wall time as
        the union of dispatch→collect intervals across workers (an
        upper bound: host work inside a match group's pruning round is
        included). mfu = estimated useful flops / (device_busy ·
        ES_TPU_PEAK_FLOPS) — flop formulas in ops/scoring.py."""
        from ..common.settings import peak_flops

        with self._lock:
            busy = self._device_busy_s
            if self._ring_inflight > 0:
                busy += time.perf_counter() - self._busy_t0
            flops = self._flops
            stall = self._host_stall_s
            inflight = self._ring_inflight
        return {
            "depth": self.pipeline_depth,
            "in_flight": inflight,
            "device_busy_ms": round(busy * 1000.0, 3),
            "host_stall_ms": round(stall * 1000.0, 3),
            "flops": int(flops),
            "mfu": (
                flops / (busy * peak_flops()) if busy > 0 else 0.0
            ),
        }

    def _run_group(self, jobs: List[_Job], field: str, kb: int,
                   rows: Optional[int] = None, record: bool = True,
                   prof_cb=None):
        """`rows` is the group's padded launch width (a ladder bucket >=
        len(jobs); default BPAD); `record=False` (bucket warmup) skips
        all stats/flop accounting. `prof_cb` (profiled groups) fires
        after device work completes but BEFORE waiter events are set, so
        a profiled request never observes its own launch missing."""
        ex = jobs[0].executor
        reader = ex.reader
        nj = len(jobs)
        rows = rows or BPAD
        staging = getattr(ex, "staging_slab", None)
        # shard-level pruning eligibility: a capped total may only be
        # shortcut to (cap, gte) when ≥ cap live matches are guaranteed
        # up front (doc_freq of some term minus deleted docs)
        prune: List[bool] = []
        for j in jobs:
            ok = j.plan.wand_ok
            if ok and j.plan.tth_cap:
                max_df = max(
                    (ex.shard_df(field, t) for t in j.plan.terms), default=0
                )
                ok = max_df - ex.deleted_count >= j.plan.tth_cap
            prune.append(ok)
        with_cnt = any(j.plan.msm > 1 for j in jobs)
        # per-segment candidate buffers STAY on device; one merge kernel
        # + one packed download replaces the per-segment host syncs
        dev_items: List[Tuple] = []  # (si, s_dev, d_dev, tot_dev)
        pruned_flags = [False] * nj
        empty_i = np.empty(0, np.int64)
        empty_w = np.empty(0, np.float32)
        for si in range(len(reader.segments)):
            n_docs = reader.segments[si].num_docs
            # ---- fused single-round-trip path (large segments) ----
            fs = ex.fused_scorer(si, field)
            if fs is not None:
                fplans = [
                    ex.fused_plan(
                        fs, si, field, j.plan.terms, j.plan.boost, j.plan.msm
                    )
                    for j in jobs
                ]
                if all(p is not None for p in fplans):
                    pend = fs.search_async(
                        fplans, kb, with_cnt, staging=staging, rows=rows
                    )
                    if record:
                        with self._lock:
                            self.stats["launches"] += 1
                            self.stats["fused_jobs"] += nj
                        self._add_flops(sum(
                            scoring.text_plan_flops(
                                len(p[0]), len(p[2]), n_docs
                            )
                            for p in fplans
                        ))
                    dev_items.append((si, *fs.device_result(pend)))
                    continue
                if record:
                    with self._lock:
                        self.stats["fused_overflow_jobs"] += sum(
                            1 for p in fplans if p is None
                        )
            # ---- chunked path (small segments / slot overflow) ----
            bmx = ex.block_index(si, field)
            cs = ex.chunked_scorer(si, field)
            if bmx is None or cs is None:
                continue
            acc, cnt = cs.new_acc(with_cnt, rows=rows)
            a_tiles: List[np.ndarray] = []
            a_w: List[np.ndarray] = []
            deferred: List[list] = []
            for ji, j in enumerate(jobs):
                plans = bmx.plan(list(j.plan.terms), j.plan.boost)
                tl, wl, hots = [], [], []
                for p in plans:
                    if prune[ji] and p.hot:
                        hots.append(p)
                    else:
                        tl.append(
                            np.arange(
                                p.tile_start, p.tile_start + p.tile_count, dtype=np.int64
                            )
                        )
                        wl.append(np.full(p.tile_count, p.weight, np.float32))
                if not tl and hots:
                    # the essential set must be non-empty or θ is -inf
                    # and nothing prunes: promote the cheapest hot term
                    hots.sort(key=lambda p: p.tile_count)
                    p = hots.pop(0)
                    tl.append(
                        np.arange(
                            p.tile_start, p.tile_start + p.tile_count, dtype=np.int64
                        )
                    )
                    wl.append(np.full(p.tile_count, p.weight, np.float32))
                a_tiles.append(np.concatenate(tl) if tl else empty_i)
                a_w.append(np.concatenate(wl) if wl else empty_w)
                deferred.append(hots)
            acc, cnt = cs.score_into(acc, cnt, a_tiles, a_w, staging=staging)
            if record:
                with self._lock:
                    self.stats["launches"] += 1
                self._add_flops(scoring.text_plan_flops(
                    sum(len(t) for t in a_tiles), 0, 0
                ))
            if any(deferred):
                # ---- the threshold broadcast + survival test (the one
                # host-dependent round: only runs when pruning engages) ----
                t0 = time.perf_counter()
                theta, accmax = cs.threshold(acc, kb)
                if record:
                    self._add_stall(time.perf_counter() - t0)
                b_tiles: List[np.ndarray] = []
                b_w: List[np.ndarray] = []
                for ji, hots in enumerate(deferred):
                    tl, wl = [], []
                    if hots:
                        sum_bounds = np.zeros(bmx.tiling.n_blocks, np.float32)
                        for p in hots:
                            sum_bounds += bmx.block_bounds(p)
                        potential = accmax[ji] + sum_bounds
                        for p in hots:
                            kept = bmx.surviving_tiles(p, potential, theta[ji])
                            if len(kept) < p.tile_count:
                                pruned_flags[ji] = True
                            if len(kept):
                                tl.append(kept)
                                wl.append(
                                    np.full(len(kept), p.weight, np.float32)
                                )
                    b_tiles.append(np.concatenate(tl) if tl else empty_i)
                    b_w.append(np.concatenate(wl) if wl else empty_w)
                acc, cnt = cs.score_into(
                    acc, cnt, b_tiles, b_w, staging=staging
                )
                if record:
                    with self._lock:
                        self.stats["launches"] += 1
                    self._add_flops(scoring.text_plan_flops(
                        sum(len(t) for t in b_tiles), 0, 0
                    ))
            msm = np.ones(rows, np.int32)
            msm[:nj] = [j.plan.msm for j in jobs]
            dev_items.append(
                (si, *cs.finalize_device(acc, cnt, msm, kb))
            )
        # device-side cross-segment merge: ONE top-k kernel + ONE packed
        # download per group (score desc, (segment, doc) asc — identical
        # ordering to the old host sort, selection only → float-exact)
        if dev_items:
            t0 = time.perf_counter()
            ms, mseg, mdoc, mtot = scoring.merge_segment_topk(dev_items, kb)
            if record:
                self._add_stall(time.perf_counter() - t0)
        else:
            ms = np.full((nj, 0), -np.inf, np.float32)
            mseg = mdoc = np.zeros((nj, 0), np.int32)
            mtot = np.zeros((nj, 0), np.int64)
        if prof_cb is not None:
            prof_cb()
        for ji, j in enumerate(jobs):
            finite = np.isfinite(ms[ji])
            hits = [
                Hit(
                    score=float(s),
                    segment=int(si),
                    local_doc=int(d),
                    doc_id=reader.segments[int(si)].doc_ids[int(d)],
                )
                for s, si, d in zip(
                    ms[ji][finite][: j.k],
                    mseg[ji][finite][: j.k],
                    mdoc[ji][finite][: j.k],
                )
            ]
            total = int(mtot[ji].sum())
            relation = "eq"
            if pruned_flags[ji]:
                if record:
                    with self._lock:
                        self.stats["pruned_jobs"] += 1
                if record and j.prof is not None:
                    j.prof["pruned_jobs"] = (
                        j.prof.get("pruned_jobs", 0) + 1
                    )
                # pruned tiles mean the collected count is a lower bound —
                # never report it as exact, even at tth_cap == 0 where the
                # REST layer omits totals (internal consumers of TopDocs
                # would otherwise see an exact-looking undercount)
                relation = "gte"
                if j.plan.tth_cap:
                    # eligibility proof guaranteed ≥ cap live matches
                    total = max(total, j.plan.tth_cap)
            j.result = TopDocs(
                total=total,
                hits=hits,
                max_score=hits[0].score if hits else None,
                relation=relation,
            )
            j.event.set()

    # ---- dispatch/collect pairs (device work launches in dispatch;
    # only collect blocks on host transfers) ----

    def _enter_kind(self, fam: str):
        with self._lock:
            self._inflight[fam] += 1
            if self._inflight["knn"] and self._inflight["text"]:
                self.stats["hybrid_overlap_events"] += 1

    def _exit_kind(self, fam: str):
        with self._lock:
            self._inflight[fam] -= 1

    def _dispatch_serve_group(self, jobs: List[_Job], kb: int,
                              rows: Optional[int] = None,
                              record: bool = True) -> List[Tuple]:
        """Launches the multi-field fused kernels for ServePlan jobs
        (bool / multi_match) on every eligible segment WITHOUT host
        sync. Segments without a fused scorer (below FUSED_MIN_DOCS) or
        jobs overflowing slot budgets are marked for the per-job
        fallback, which runs at collect time. `rows` pads the launch to
        one ladder bucket; `record=False` (warmup) mutes stats."""
        ex = jobs[0].executor
        nj = len(jobs)
        rows = rows or BPAD
        staging = getattr(ex, "staging_slab", None)
        plan0 = jobs[0].plan
        fields = plan0.fields
        items: List[Tuple] = []
        for si in range(len(ex.reader.segments)):
            fs = ex.fused_scorer_mf(si, fields)
            fplans = None
            if fs is not None:
                fplans = []
                for j in jobs:
                    sections = []
                    for g in j.plan.groups:
                        parts = ex.fused_parts(si, g.field)
                        sec = (
                            ex.fused_plan_field(
                                si, g.field, parts, g.terms, j.plan.boost
                            )
                            if parts is not None
                            else None
                        )
                        if sec is None:
                            sections = None
                            break
                        sections.append(sec)
                    fplans.append(
                        (sections, j.plan.msm) if sections is not None else None
                    )
            if fs is not None and all(p is not None for p in fplans):
                pend = fs.search_async(
                    fplans, kb, plan0.combine, plan0.tie, staging=staging,
                    rows=rows,
                )
                if record:
                    with self._lock:
                        self.stats["launches"] += 1
                        self.stats["fused_jobs"] += nj
                    n_docs = ex.reader.segments[si].num_docs
                    self._add_flops(sum(
                        scoring.text_plan_flops(
                            len(sec[0]), len(sec[2]), n_docs
                        )
                        for sections, _ in fplans
                        for sec in sections
                    ))
                items.append(("fused", si, fs, pend))
            else:
                if record and fs is not None and fplans is not None:
                    with self._lock:
                        self.stats["fused_overflow_jobs"] += sum(
                            1 for p in fplans if p is None
                        )
                items.append(("fallback", si, None, None))
        return items

    def _collect_serve_group(self, jobs: List[_Job], kb: int, items,
                             record: bool = True):
        """Host side of the serve group: one device-side merge + packed
        download covers every fused segment; fallback segments (below
        FUSED_MIN_DOCS / slot overflow) run per job on the host and join
        the final merge. Totals are exact (the fused program scores
        exactly — no pruning on this path)."""
        ex = jobs[0].executor
        reader = ex.reader
        per_job_cands: List[List[Tuple[float, int, int]]] = [[] for _ in jobs]
        totals = np.zeros(len(jobs), np.int64)
        fused_items = [
            (si, *fs.device_result(pend))
            for tag, si, fs, pend in items
            if tag == "fused"
        ]
        if fused_items:
            t0 = time.perf_counter()
            ms, mseg, mdoc, mtot = scoring.merge_segment_topk(
                fused_items, kb
            )
            if record:
                self._add_stall(time.perf_counter() - t0)
            for ji in range(len(jobs)):
                finite = np.isfinite(ms[ji])
                for s, si, d in zip(
                    ms[ji][finite], mseg[ji][finite], mdoc[ji][finite]
                ):
                    per_job_cands[ji].append((float(s), int(si), int(d)))
                totals[ji] += int(mtot[ji].sum())
        for tag, si, fs, pend in items:
            if tag != "fallback":
                continue
            for ji, j in enumerate(jobs):
                s1, d1, t1 = ex.segment_topk(j.query, si, kb)
                if record:
                    with self._lock:
                        self.stats["launches"] += 1
                self._collect(
                    [j], [per_job_cands[ji]], totals[ji: ji + 1],
                    si, s1[None, :], d1[None, :], np.array([t1]),
                )
        self._finish_jobs(jobs, per_job_cands, totals, reader)

    def _dispatch_agg_group(self, jobs: List[_Job]) -> List[Tuple]:
        """Launches the device-aggregation plans (search/aggs_device
        segment-sum kernels) for a group of same-signature agg jobs
        WITHOUT host sync; downloads happen at collect. Per-job failure
        isolation: one body's injected fault or column surprise fails
        only that job's waiter (the shard path then reruns it on the
        host collector), not its group."""
        out: List[Tuple] = []
        for j in jobs:
            try:
                pend = j.plan.dispatch()
            except BaseException as e:
                out.append(("err", e))
                continue
            with self._lock:
                self.stats["launches"] += 1
                self.stats["agg_jobs"] += 1
            self._add_flops(j.plan.flops_estimate())
            out.append(("ok", pend))
        return out

    def _collect_agg_group(self, jobs: List[_Job], pends: List[Tuple]):
        for j, (tag, pend) in zip(jobs, pends):
            if j.event.is_set():
                continue
            if tag == "err":
                j.error = pend
                j.event.set()
                continue
            try:
                t0 = time.perf_counter()
                j.result = j.plan.collect(pend)  # (TopDocs, partials)
                self._add_stall(time.perf_counter() - t0)
            except BaseException as e:
                j.error = e
            j.event.set()

    def _dispatch_rerank_group(self, jobs: List[_Job],
                               rows: Optional[int] = None) -> Tuple:
        """Launches one maxsim rescore kernel for a group of same-sig
        rerank jobs (search/rescorer.RerankPlan) WITHOUT host sync; the
        one packed download happens at collect. The `rerank.score`
        fault site fires here — an injected error surfaces to exactly
        this group's waiters, whose requests then keep their
        first-stage ranking (the deterministic rerank fallback). A
        missing column (HBM degrade-to-skip) completes the group with a
        "skip" marker instead of device work."""
        from ..ops import rerank as rerank_ops

        ex = jobs[0].executor
        plan0 = jobs[0].plan
        nj = len(jobs)
        rows = rows or BPAD
        faults.check("rerank.score", field=plan0.field, jobs=nj)
        col = ex.rerank_column(plan0.model)
        if col is None:
            return ("skip", None, 0.0)
        wb, qb = plan0.wb, plan0.qb
        dims = col["dims"]
        staging = getattr(ex, "staging_slab", None)
        if staging is not None:
            qtoks = staging("rerank_q", (rows, qb, dims), np.float32)
            qvalid = staging("rerank_qv", (rows, qb), np.bool_)
            docs = staging("rerank_d", (rows, wb), np.int32)
            first = staging("rerank_s", (rows, wb), np.float32)
            valid = staging("rerank_v", (rows, wb), np.bool_)
        else:
            qtoks = np.zeros((rows, qb, dims), np.float32)
            qvalid = np.zeros((rows, qb), bool)
            docs = np.zeros((rows, wb), np.int32)
            first = np.zeros((rows, wb), np.float32)
            valid = np.zeros((rows, wb), bool)
        # staging buffers are reused: fully rewrite every plane
        qtoks[:] = 0.0
        qvalid[:] = False
        docs[:] = 0
        first[:] = -np.inf
        valid[:] = False
        for ji, j in enumerate(jobs):
            p = j.plan
            qtoks[ji, : len(p.qtoks)] = p.qtoks
            qvalid[ji, : len(p.qtoks)] = True
            w = len(p.first)
            docs[ji, :w] = p.gdocs.astype(np.int32)
            first[ji, :w] = p.first
            valid[ji, :w] = True
        t0 = time.perf_counter()
        out = rerank_ops.maxsim_rescore_batch(
            qtoks, qvalid, col["starts"], col["counts"], col["toks"],
            col["scales"], docs, first, valid,
            plan0.spec.query_weight, plan0.spec.rescore_query_weight,
            col["tmax"], plan0.win_static,
        )
        with self._lock:
            self.stats["launches"] += 1
            self.stats["rerank_jobs"] += nj
        self._add_flops(
            rerank_ops.rerank_flops(nj, qb, wb, col["tmax"], dims)
        )
        return ("ok", out, t0)

    def _collect_rerank_group(self, jobs: List[_Job], pend: Tuple):
        """Host side: the ONE packed download, then each waiter gets
        its (scores, perm, kernel_ms) triple — the shard applies the
        permutation to its first-stage TopDocs before fetch."""
        from ..ops import rerank as rerank_ops

        tag, out, t0 = pend
        if tag == "skip":
            for j in jobs:
                if not j.event.is_set():
                    j.result = ("skip", None, None, 0.0)
                    j.event.set()
            return
        t1 = time.perf_counter()
        scores, perm = rerank_ops.unpack_rescore(out)
        self._add_stall(time.perf_counter() - t1)
        kernel_ms = (time.perf_counter() - t0) * 1000.0
        for ji, j in enumerate(jobs):
            if j.event.is_set():
                continue
            w = len(j.plan.first)
            j.result = ("ok", scores[ji][:w], perm[ji][:w], kernel_ms)
            j.event.set()

    def _dispatch_knn_group(self, jobs: List[_Job],
                            rows: Optional[int] = None,
                            record: bool = True) -> List[Tuple]:
        """Launches the batched brute-force kNN matmul per segment
        (BASELINE config 4); results stay on device until collect.
        `rows` pads the query-row dimension to one ladder bucket."""
        ex = jobs[0].executor
        reader = ex.reader
        nj = len(jobs)
        rows = rows or BPAD
        staging = getattr(ex, "staging_slab", None)
        field = jobs[0].plan.field
        spec = jobs[0].plan.ann  # shared: ann rides the group key
        items: List[Tuple] = []
        for si, seg in enumerate(reader.segments):
            if seg.vectors.get(field) is None:
                continue
            vf = seg.vectors[field]
            n = seg.num_docs
            # IVF tier: probe-path failures (the `ann.probe` fault
            # site, HBM degrade) fall back DETERMINISTICALLY to the
            # exact brute-force launch below; segments under the
            # small-segment floor never build an index and stay exact
            idx = None
            if spec is not None and getattr(ex, "ann_index", None):
                from . import ann as ann_mod

                try:
                    if record:
                        faults.check("ann.probe", field=field, segment=si)
                    idx = ex.ann_index(si, field, spec)
                except BaseException:
                    ann_mod.note("exact_fallbacks")
                    idx = None
            dims = int(vf.vectors.shape[1])
            if staging is not None:
                q = staging("knn_q", (rows, dims), np.float32)
                valid = staging("knn_valid", (rows,), np.bool_)
                valid[:] = False  # stale rows are masked, not re-scored
            else:
                q = np.zeros((rows, dims), np.float32)
                valid = np.zeros(rows, bool)
            for ji, j in enumerate(jobs):
                q[ji] = np.asarray(j.plan.vector, np.float32)
                valid[ji] = True
            kc = min(
                max(
                    scoring.next_bucket(
                        max(min(j.plan.num_candidates, n) for j in jobs), 16
                    ),
                    16,
                ),
                max(n, 1),
            )
            live = reader.live_docs[si]
            if idx is not None:
                from ..ops import ivf

                cand = None
                if live is not None or not bool(vf.exists.all()):
                    cand = vf.exists
                    if live is not None:
                        cand = cand & np.asarray(live)
                s, d = ivf.ann_topk_batch(
                    idx, np.asarray(q), np.asarray(valid), cand,
                    spec.nprobe, kc, quantized=spec.quantized,
                )
                if record:
                    from . import ann as ann_mod

                    ann_mod.note_search(spec.nprobe, idx.nlist, jobs=nj)
                    with self._lock:
                        self.stats["launches"] += 1
                        self.stats["fused_jobs"] += nj
                    self._add_flops(
                        ivf.ann_flops(
                            nj, idx.nlist, spec.nprobe, idx.cmax, dims
                        )
                    )
                items.append((si, n, s, d))
                continue
            vectors, exists = ex.device_segments[si].vectors[field]
            cand_mask = exists
            if live is not None:
                cand_mask = cand_mask & np.asarray(live)
            s, d, _ = scoring.knn_topk_batch(
                np.asarray(q), np.asarray(valid),
                vectors, cand_mask, vf.similarity, kc,
            )
            if record:
                with self._lock:
                    self.stats["launches"] += 1
                    self.stats["fused_jobs"] += nj
                self._add_flops(scoring.knn_flops(nj, n, dims))
            items.append((si, n, s, d))
        return items

    def _collect_knn_group(self, jobs: List[_Job], items,
                           record: bool = True):
        """Per-segment top num_candidates, then a global per-job k cut —
        the coordinator merge of DfsPhase.executeKnnVectorQuery. The
        per-segment candidate buffers never leave the device: one merge
        kernel applies the per-(job, segment) num_candidates rank cut
        and selects the global winners in a single packed download.
        Boost multiplies AFTER selection on the host (a per-job
        strictly-positive constant cannot change the order), so scores
        are float-identical to the host merge; a job carrying a zero or
        negative boost would reorder, so that group merges on host."""
        if record:
            faults.check("knn.collect", jobs=len(jobs))
        reader = jobs[0].executor.reader
        per_job_cands: List[List[Tuple[float, int, int]]] = [[] for _ in jobs]
        if items and all(j.plan.boost > 0.0 for j in jobs):
            # the device buffers' row bucket; padded query rows keep
            # nc=0 (their scores are -inf anyway)
            rows = int(items[0][2].shape[0])
            nc_rows = np.zeros((rows, len(items)), np.int32)
            for ii, (si, n, _, _) in enumerate(items):
                for ji, j in enumerate(jobs):
                    nc_rows[ji, ii] = min(j.plan.num_candidates, n)
            k_out = max(max(j.k, 1) for j in jobs)
            t0 = time.perf_counter()
            ms, mseg, mdoc, counts = scoring.knn_merge_segment_topk(
                [(si, s, d) for si, _, s, d in items], nc_rows, k_out
            )
            if record:
                self._add_stall(time.perf_counter() - t0)
            for ji, j in enumerate(jobs):
                finite = np.isfinite(ms[ji])
                cap = min(j.plan.k, j.k)
                boost = j.plan.boost
                hits = [
                    Hit(
                        score=float(s) * boost,
                        segment=int(si),
                        local_doc=int(d),
                        doc_id=reader.segments[int(si)].doc_ids[int(d)],
                    )
                    for s, si, d in zip(
                        ms[ji][finite][:cap],
                        mseg[ji][finite][:cap],
                        mdoc[ji][finite][:cap],
                    )
                ]
                j.result = TopDocs(
                    total=min(int(counts[ji]), j.plan.k),
                    hits=hits,
                    max_score=hits[0].score if hits else None,
                    relation="eq",
                )
                j.event.set()
            return
        for si, n, s, d in items:
            s = np.asarray(s)
            d = np.asarray(d)
            for ji, j in enumerate(jobs):
                nc = min(j.plan.num_candidates, n)
                row_s, row_d = s[ji][:nc], d[ji][:nc]
                finite = np.isfinite(row_s)
                boost = j.plan.boost
                for sc, doc in zip(row_s[finite], row_d[finite]):
                    per_job_cands[ji].append(
                        (float(sc) * boost, si, int(doc))
                    )
        # global k cut; totals = number of winners (knn semantics)
        totals = np.asarray(
            [min(len(per_job_cands[ji]), j.plan.k)
             for ji, j in enumerate(jobs)],
            np.int64,
        )
        self._finish_jobs(
            jobs, per_job_cands, totals, reader,
            page_caps=[j.plan.k for j in jobs],
        )

    def _dispatch_sparse_group(self, jobs: List[_Job], kb: int,
                               rows: Optional[int] = None,
                               record: bool = True) -> List[Tuple]:
        """Launches the impact-tile kernels (ops/impact.py) for a group
        of same-(field, spec) sparse_vector jobs on every segment
        carrying the column. Two-phase per segment: phase A scores each
        query term's FIRST tile (where impact ordering puts the term
        maxima), one theta download, then the surviving block-max tile
        list scores into a fresh accumulator whose finalize triple
        stays ON DEVICE until collect. The `sparse.score` fault site
        fires per segment — an injected error (like an HBM degrade or
        missing column) falls back DETERMINISTICALLY to the host dense
        oracle for that segment at collect time, exact answers
        included."""
        from ..ops import impact as impact_ops
        from . import sparse as sparse_mod

        ex = jobs[0].executor
        reader = ex.reader
        nj = len(jobs)
        rows = rows or BPAD
        staging = getattr(ex, "staging_slab", None)
        plan0 = jobs[0].plan
        field = plan0.field
        spec = plan0.spec
        items: List[Tuple] = []
        for si, seg in enumerate(reader.segments):
            sfh = (getattr(seg, "sparse", None) or {}).get(field)
            if sfh is None or not sfh.n_tiles:
                continue
            sc = None
            try:
                if record:
                    faults.check("sparse.score", field=field, segment=si)
                sc = ex.impact_scorer(si, field, spec.quantized)
            except BaseException:
                sc = None
            if sc is None:
                if record:
                    sparse_mod.note("fallbacks", nj)
                items.append(("fallback", si, None))
                continue
            # int8 serving prunes against the DEQUANTIZED tile maxima
            # (tile_qmax): a dequantized slot can exceed the fp32 tile
            # max by up to scale/2, so the fp32 bounds alone would be
            # unsound against quantized scores
            bound = sfh.tile_qmax if spec.quantized else sfh.tile_max
            bms = []
            prunable = []
            for j in jobs:
                tids, tws, bws, _, _ = impact_ops.impact_tile_lists(
                    sfh, j.plan.terms, j.plan.weights, spec.quantized
                )
                bms.append(
                    impact_ops.SparseBlockMax(
                        sfh.term_tile_start, sfh.term_tile_count,
                        bound, tids, tws, bws,
                    )
                )
                # block-max upper bounds assume non-negative tile
                # weights; a negative query weight keeps the job exact
                # but unpruned
                prunable.append(bool((tws >= 0).all()))
            thetas = np.full(len(jobs), -np.inf, np.float32)
            if any(
                p and bm.n_tail_tiles for p, bm in zip(prunable, bms)
            ):
                acc, cnt = sc.new_acc(rows)
                acc, cnt = sc.score_into(
                    acc, cnt,
                    [bm.phase_a()[0] for bm in bms],
                    [bm.phase_a()[1] for bm in bms],
                    staging=staging,
                )
                th, _ = sc.threshold(acc, kb)
                for ji in range(len(jobs)):
                    if prunable[ji]:
                        thetas[ji] = th[ji]
            tile_lists: List[np.ndarray] = []
            weight_lists: List[np.ndarray] = []
            pruned_flags = np.zeros(len(jobs), bool)
            tiles_scored = 0
            tiles_pruned = 0
            for ji, bm in enumerate(bms):
                t, w, dropped = bm.kept(float(thetas[ji]))
                tile_lists.append(t)
                weight_lists.append(w)
                pruned_flags[ji] = dropped > 0
                tiles_scored += len(t)
                tiles_pruned += dropped
            acc, cnt = sc.new_acc(rows)
            acc, cnt = sc.score_into(
                acc, cnt, tile_lists, weight_lists, staging=staging
            )
            pend = sc.finalize_device(acc, cnt, kb)
            if record:
                sparse_mod.note_search(
                    nj, spec.quantized, tiles_scored, tiles_pruned
                )
                with self._lock:
                    self.stats["launches"] += 1
                    self.stats["sparse_jobs"] += nj
                self._add_flops(impact_ops.sparse_flops(tiles_scored))
            items.append(("dev", si, (pend, pruned_flags)))
        return items

    def _collect_sparse_group(self, jobs: List[_Job], kb: int, items,
                              record: bool = True):
        """Host side of the sparse group: one device-side merge + one
        packed download covers every device segment; fallback segments
        (fault / degrade) run per job through the executor's generic
        per-segment top-k — which routes SparseVectorQuery to the host
        dense oracle — and join the final merge. Hits are exact either
        way; totals turn relation "gte" when block-max pruning dropped
        tiles (the dropped docs provably score below the kth best, but
        they are no longer counted)."""
        ex = jobs[0].executor
        reader = ex.reader
        per_job_cands: List[List[Tuple[float, int, int]]] = [
            [] for _ in jobs
        ]
        totals = np.zeros(len(jobs), np.int64)
        pruned_any = np.zeros(len(jobs), bool)
        dev_items = []
        for tag, si, payload in items:
            if tag != "dev":
                continue
            pend, pruned_flags = payload
            dev_items.append((si, *pend))
            pruned_any |= pruned_flags
        if dev_items:
            t0 = time.perf_counter()
            ms, mseg, mdoc, mtot = scoring.merge_segment_topk(
                dev_items, kb
            )
            if record:
                self._add_stall(time.perf_counter() - t0)
            for ji in range(len(jobs)):
                finite = np.isfinite(ms[ji])
                for s, si, d in zip(
                    ms[ji][finite], mseg[ji][finite], mdoc[ji][finite]
                ):
                    per_job_cands[ji].append((float(s), int(si), int(d)))
                totals[ji] += int(mtot[ji].sum())
        for tag, si, _payload in items:
            if tag != "fallback":
                continue
            for ji, j in enumerate(jobs):
                s1, d1, t1 = ex.segment_topk(j.query, si, kb)
                if record:
                    with self._lock:
                        self.stats["launches"] += 1
                self._collect(
                    [j], [per_job_cands[ji]], totals[ji : ji + 1],
                    si, s1[None, :], d1[None, :], np.array([t1]),
                )
        for ji, j in enumerate(jobs):
            cands = per_job_cands[ji]
            cands.sort(key=lambda c: (-c[0], c[1], c[2]))
            page = cands[: j.k]
            hits = [
                Hit(
                    score=s,
                    segment=si,
                    local_doc=d,
                    doc_id=reader.segments[si].doc_ids[d],
                )
                for s, si, d in page
            ]
            relation = "eq"
            if pruned_any[ji]:
                if record:
                    with self._lock:
                        self.stats["pruned_jobs"] += 1
                if record and j.prof is not None:
                    j.prof["pruned_jobs"] = (
                        j.prof.get("pruned_jobs", 0) + 1
                    )
                relation = "gte"
            j.result = TopDocs(
                total=int(totals[ji]),
                hits=hits,
                max_score=hits[0].score if hits else None,
                relation=relation,
            )
            j.event.set()

    def _finish_jobs(self, jobs, per_job_cands, totals, reader,
                     page_caps=None):
        """Exact (non-pruned) cross-segment merge: score desc,
        (segment, doc) asc. page_caps optionally bounds the candidate
        pool before the per-job k cut (knn's global num_candidates)."""
        for ji, j in enumerate(jobs):
            cands = per_job_cands[ji]
            cands.sort(key=lambda c: (-c[0], c[1], c[2]))
            if page_caps is not None:
                cands = cands[: page_caps[ji]]
            page = cands[: j.k]
            hits = [
                Hit(
                    score=s,
                    segment=si,
                    local_doc=d,
                    doc_id=reader.segments[si].doc_ids[d],
                )
                for s, si, d in page
            ]
            j.result = TopDocs(
                total=int(totals[ji]),
                hits=hits,
                max_score=hits[0].score if hits else None,
                relation="eq",
            )
            j.event.set()

    @staticmethod
    def _collect(jobs, per_job_cands, totals, si, s, d, t):
        for ji in range(len(jobs)):
            srow = s[ji]
            drow = d[ji]
            finite = np.isfinite(srow)
            for sc, doc in zip(srow[finite], drow[finite]):
                per_job_cands[ji].append((float(sc), si, int(doc)))
            totals[ji] += int(t[ji])
