"""Cross-request micro-batching dispatcher — the serving-path bridge to
the batched TPU kernels.

Reference analog: there is none in Elasticsearch — Lucene scores one
query per thread. This is the north-star departure (BASELINE.json:
"score query batches in parallel"): concurrent `_search` requests whose
query compiles to a flat weighted-term plan are collected into ONE
[B, T, 128] kernel launch per (segment, field) instead of B separate
launches. The dispatcher uses continuous batching: while one batch is
executing on device, arriving requests queue; the worker drains the
whole queue the moment it frees up, so there is no linger timer and no
added idle latency for a lone request.

When a request does not need exact totals (track_total_hits: false) the
group is scored through the block-max WAND scorer (ops/wand.py) instead
— same results for top-k, a fraction of the HBM traffic.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.mapping import TEXT
from ..ops import scoring
from . import dsl
from .executor import Hit, TopDocs

MAX_BATCH = 64


@dataclass(frozen=True)
class MatchPlan:
    """A query reduced to flat weighted terms over one text field."""

    field: str
    terms: Tuple[str, ...]
    msm: int  # minimum matching terms (1 = OR, len(terms) = AND)
    boost: float
    wand_ok: bool  # caller does not need exact totals → pruning allowed


def extract_match_plan(
    query, mappings, analysis, tth_capped: bool
) -> Optional[MatchPlan]:
    """Returns a MatchPlan when `query` is a match query over a text
    field (the hot REST shape), else None → normal executor path."""
    if not isinstance(query, dsl.MatchQuery):
        return None
    mf = mappings.get(query.field)
    if mf is None or mf.type != TEXT:
        return None
    analyzer_name = query.analyzer or mf.search_analyzer or mf.analyzer
    try:
        terms = analysis.get(analyzer_name).terms(query.query)
    except ValueError:
        return None
    if not terms:
        return None
    if query.operator == "and":
        msm = len(terms)
    else:
        msm = max(
            1, dsl.parse_minimum_should_match(query.minimum_should_match, len(terms))
        )
    wand_ok = tth_capped and query.boost == 1.0 and msm == 1
    return MatchPlan(
        field=query.field,
        terms=tuple(terms),
        msm=msm,
        boost=query.boost,
        wand_ok=wand_ok,
    )


class _Job:
    __slots__ = ("executor", "plan", "k", "event", "result", "error")

    def __init__(self, executor, plan: MatchPlan, k: int):
        self.executor = executor
        self.plan = plan
        self.k = k
        self.event = threading.Event()
        self.result: Optional[TopDocs] = None
        self.error: Optional[BaseException] = None


class QueryBatcher:
    """One dispatcher thread per index: REST worker threads submit jobs
    and block; the worker scores whole groups in single launches."""

    def __init__(self, max_batch: int = MAX_BATCH):
        self.max_batch = max_batch
        self._queue: "queue.Queue[_Job]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()
        # observability: how many launches / jobs / batched jobs
        self.stats = {"launches": 0, "jobs": 0, "max_batch_seen": 0}

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="query-batcher", daemon=True
                )
                self._thread.start()

    def close(self):
        self._closed = True
        if self._thread is not None:
            self._queue.put(None)  # wake the worker
        # fail anything still queued so no submitter blocks forever
        while True:
            try:
                j = self._queue.get_nowait()
            except queue.Empty:
                break
            if j is not None:
                j.error = RuntimeError("query batcher closed")
                j.event.set()

    # ---- client side ----

    def submit(self, executor, plan: MatchPlan, k: int) -> _Job:
        if self._closed:
            raise RuntimeError("query batcher closed")
        job = _Job(executor, plan, k)
        self._ensure_thread()
        self._queue.put(job)
        if self._closed:
            # lost the race with close(): make sure nobody hangs
            self.close()
        return job

    def execute(self, executor, plan: MatchPlan, k: int) -> TopDocs:
        job = self.submit(executor, plan, k)
        return self.wait(job)

    @staticmethod
    def wait(job: _Job) -> TopDocs:
        job.event.wait()
        if job.error is not None:
            raise job.error
        return job.result

    # ---- worker side ----

    def _run(self):
        while not self._closed:
            job = self._queue.get()
            if job is None:
                continue
            if self._closed:
                job.error = RuntimeError("query batcher closed")
                job.event.set()
                continue
            batch = [job]
            while len(batch) < self.max_batch:
                try:
                    j = self._queue.get_nowait()
                except queue.Empty:
                    break
                if j is not None:
                    batch.append(j)
            self.stats["jobs"] += len(batch)
            self.stats["max_batch_seen"] = max(
                self.stats["max_batch_seen"], len(batch)
            )
            # group jobs that can share one launch
            groups: Dict[Tuple, List[_Job]] = {}
            for j in batch:
                kb = max(16, scoring.next_bucket(j.k, 16))
                key = (id(j.executor), j.plan.field, kb, j.plan.wand_ok)
                groups.setdefault(key, []).append(j)
            for (eid, field, kb, wand), jobs in groups.items():
                try:
                    self._run_group(jobs, field, kb, wand)
                except BaseException as e:  # surface to all waiters
                    for j in jobs:
                        j.error = e
                        j.event.set()

    def _run_group(self, jobs: List[_Job], field: str, kb: int, wand: bool):
        ex = jobs[0].executor
        reader = ex.reader
        n_segments = len(reader.segments)
        # per segment: one batched launch over all jobs in the group
        per_job_cands: List[List[Tuple[float, int, int]]] = [[] for _ in jobs]
        totals = np.zeros(len(jobs), np.int64)
        # pad the batch dimension to a power-of-two bucket too, or every
        # distinct concurrent batch size would trigger its own XLA
        # compile (the scorer's contract is one compile per (B, T) pair)
        B = scoring.next_bucket(len(jobs), 1)
        for si in range(n_segments):
            if wand:
                scorer = ex.wand_scorer(si, field, kb)
                if scorer is not None:
                    term_lists = [list(j.plan.terms) for j in jobs]
                    term_lists += [[] for _ in range(B - len(jobs))]
                    s, d, t, _stats = scorer.search_batch(term_lists)
                    self.stats["launches"] += 1
                    self._collect(jobs, per_job_cands, totals, si, s, d, t)
                    continue
                # fall through (deleted docs present / no postings)
            scorer = ex.batched_scorer(si, field, kb)
            if scorer is None:
                continue
            tiles = [
                ex.term_tiles(si, field, list(j.plan.terms), j.plan.boost)
                for j in jobs
            ]
            T = scoring.next_bucket(max((len(t[0]) for t in tiles), default=1))
            ti = np.zeros((B, T), np.int32)
            tw = np.zeros((B, T), np.float32)
            tv = np.zeros((B, T), bool)
            for bi, (idx, w) in enumerate(tiles):
                t = len(idx)
                ti[bi, :t] = idx
                tw[bi, :t] = w
                tv[bi, :t] = True
            msm = np.ones(B, np.int32)
            msm[: len(jobs)] = [j.plan.msm for j in jobs]
            res = scorer(ti, tw, tv, msm)
            self.stats["launches"] += 1
            self._collect(
                jobs,
                per_job_cands,
                totals,
                si,
                np.asarray(res.scores),
                np.asarray(res.docs),
                np.asarray(res.totals),
            )
        # merge across segments per job: score desc, (segment, doc) asc
        for bi, j in enumerate(jobs):
            cands = per_job_cands[bi]
            cands.sort(key=lambda c: (-c[0], c[1], c[2]))
            page = cands[: j.k]
            hits = [
                Hit(
                    score=s,
                    segment=si,
                    local_doc=d,
                    doc_id=reader.segments[si].doc_ids[d],
                )
                for s, si, d in page
            ]
            j.result = TopDocs(
                total=int(totals[bi]),
                hits=hits,
                max_score=hits[0].score if hits else None,
            )
            j.event.set()

    @staticmethod
    def _collect(jobs, per_job_cands, totals, si, s, d, t):
        for bi in range(len(jobs)):
            srow = s[bi]
            drow = d[bi]
            finite = np.isfinite(srow)
            for sc, doc in zip(srow[finite], drow[finite]):
                per_job_cands[bi].append((float(sc), si, int(doc)))
            totals[bi] += int(t[bi])
