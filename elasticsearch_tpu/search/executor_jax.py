"""JAX/TPU shard executor — the production scoring path.

Mirrors the NumPy oracle (executor.py) node for node, but evaluates on
device arrays: postings tiles live in HBM, leaves score via the jitted
gather→BM25→scatter kernel in ops/scoring.py, compounds compose dense
masks/scores with elementwise jnp ops, and collection is lax.top_k.
Tests enforce hit-for-hit parity with the oracle.

Per-segment arrays are uploaded once and cached (`DeviceSegment`) — the
analog of Lucene's "open a reader once, search many times", and the
north star's "posting lists block-decoded once into HBM-resident arrays".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..index.mapping import DATE, KEYWORD, TEXT, parse_date_millis
from ..index.segment import Segment
from ..models import bm25
from ..ops import scoring
from . import dsl
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    ExistsQuery,
    KnnQueryWrapper,
    KnnSection,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhraseQuery,
    MatchQuery,
    MultiMatchQuery,
    Query,
    QueryParseError,
    RangeQuery,
    TermQuery,
    TermsQuery,
)
from .executor import Hit, NumpyExecutor, ShardReader, TopDocs, _coerce_numeric


class DevicePostings:
    def __init__(self, pf, device=None):
        self.doc_ids = jax.device_put(pf.doc_ids, device)
        self.tfs = jax.device_put(pf.tfs, device)


class DeviceSegment:
    """Device-resident mirror of a Segment's hot arrays."""

    def __init__(self, seg: Segment, device=None):
        self.seg = seg
        self.device = device
        self.postings: Dict[str, DevicePostings] = {}
        self.numerics: Dict[str, Tuple[jax.Array, jax.Array]] = {}
        self.vectors: Dict[str, Tuple[jax.Array, jax.Array]] = {}
        for fname, pf in seg.postings.items():
            self.postings[fname] = DevicePostings(pf, device)
        for fname, nf in seg.numerics.items():
            self.numerics[fname] = (
                jax.device_put(nf.values, device),
                jax.device_put(nf.exists, device),
            )
        for fname, vf in seg.vectors.items():
            mat = vf.unit_vectors if vf.similarity == "cosine" else vf.vectors
            self.vectors[fname] = (
                jax.device_put(mat, device),
                jax.device_put(vf.exists, device),
            )


class JaxExecutor:
    """Walks the query tree producing dense device (mask, scores) pairs."""

    def __init__(
        self,
        reader: ShardReader,
        k1: float = bm25.DEFAULT_K1,
        b: float = bm25.DEFAULT_B,
        device=None,
    ):
        self.reader = reader
        self.k1 = k1
        self.b = b
        self.device = device
        self.device_segments = [DeviceSegment(s, device) for s in reader.segments]
        # the oracle is reused for stats, weights, and host-only nodes
        # (match_phrase position verification)
        self._oracle = NumpyExecutor(reader, k1, b)
        self._inv_norm_cache: Dict[Tuple[int, str], jax.Array] = {}

    # ---- per-(segment, field) dense inverse-norm array ----

    def _inv_norm(self, si: int, field: str, n: int) -> jax.Array:
        key = (si, field)
        arr = self._inv_norm_cache.get(key)
        if arr is None:
            cache = self._oracle._field_cache(field)
            pf = self.reader.segments[si].postings.get(field)
            mf = self.reader.mappings.get(field)
            if pf is None:
                host = np.zeros(n, np.float32)
            elif mf is not None and mf.type != TEXT:
                # omitted norms → encodedNorm 1 for every doc
                host = np.full(n, cache[1], np.float32)
            else:
                host = cache[pf.norms.astype(np.int64)]
            arr = jax.device_put(host, self.device)
            self._inv_norm_cache[key] = arr
        return arr

    # ---- entry point (mirrors NumpyExecutor.search) ----

    def search(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> TopDocs:
        return self.execute(query, size, from_, knn, min_score)[0]

    def execute(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> Tuple[TopDocs, List[np.ndarray]]:
        knn_sets = [self._knn_topk_global(sec) for sec in (knn or [])]
        per_segment: List[Tuple[np.ndarray, np.ndarray]] = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if query is None and not knn_sets:
                q: Optional[Query] = MatchAllQuery()
            else:
                q = query
            if q is not None:
                mask, scores = self._exec(q, si)
            else:
                mask = jnp.zeros(n, bool)
                scores = jnp.zeros(n, jnp.float32)
            for ks in knn_sets:
                kmask, kscores = ks[si]
                scores = jnp.where(kmask, scores + kscores, scores)
                mask = mask | kmask
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & jnp.asarray(live)
            if min_score is not None:
                mask = mask & (scores >= jnp.float32(min_score))
            per_segment.append((np.asarray(mask), np.asarray(scores)))

        # global collection (same ordering as the oracle): score desc,
        # (segment, doc) asc — vectorized over the matching docs only
        total = int(sum(m.sum() for m, _ in per_segment))
        cand_scores: List[np.ndarray] = []
        cand_seg: List[np.ndarray] = []
        cand_doc: List[np.ndarray] = []
        for si, (mask, scores) in enumerate(per_segment):
            idx = np.nonzero(mask)[0]
            if len(idx):
                cand_scores.append(scores[idx].astype(np.float64))
                cand_seg.append(np.full(len(idx), si, np.int64))
                cand_doc.append(idx.astype(np.int64))
        masks = [m for m, _ in per_segment]
        if not cand_scores:
            return TopDocs(total=total, hits=[], max_score=None), masks
        s = np.concatenate(cand_scores)
        sg = np.concatenate(cand_seg)
        dc = np.concatenate(cand_doc)
        need = from_ + size
        if need < len(s):
            part = np.argpartition(-s, need)[: need + 1]
            # keep enough candidates to break ties deterministically: take
            # everything scoring >= the partition's lowest kept score
            thresh = s[part].min()
            keep = np.nonzero(s >= thresh)[0]
            s, sg, dc = s[keep], sg[keep], dc[keep]
        order = np.lexsort((dc, sg, -s))
        max_score = float(s[order[0]])
        top = order[from_ : from_ + size]
        hits = [
            Hit(
                score=float(s[i]),
                segment=int(sg[i]),
                local_doc=int(dc[i]),
                doc_id=self.reader.segments[int(sg[i])].doc_ids[int(dc[i])],
            )
            for i in top
        ]
        return TopDocs(total=total, hits=hits, max_score=max_score), masks

    # ---- node dispatch ----

    def _exec(self, q: Query, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        if isinstance(q, MatchAllQuery):
            return jnp.ones(n, bool), jnp.full(n, np.float32(q.boost), jnp.float32)
        if isinstance(q, MatchNoneQuery):
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if isinstance(q, MatchQuery):
            return self._exec_match(q, si)
        if isinstance(q, TermQuery):
            return self._exec_term(q, si)
        if isinstance(q, TermsQuery):
            return self._exec_terms(q, si)
        if isinstance(q, RangeQuery):
            return self._exec_range(q, si)
        if isinstance(q, ExistsQuery):
            # host-computed masks are cheap and static; reuse oracle
            hm, hs = self._oracle._exec(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        if isinstance(q, BoolQuery):
            return self._exec_bool(q, si)
        if isinstance(q, ConstantScoreQuery):
            m, _ = self._exec(q.filter_query, si)
            return m, jnp.where(m, jnp.float32(q.boost), 0.0)
        if isinstance(q, MultiMatchQuery):
            return self._exec_multi_match(q, si)
        if isinstance(q, MatchPhraseQuery):
            # positions are host-side in round 1 → oracle result uploaded
            hm, hs = self._oracle._exec(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        if isinstance(q, KnnQueryWrapper):
            hm, hs = self._oracle._exec_knn(q.knn, si, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        if isinstance(q, dsl.DisMaxQuery):
            masks, scores = [], []
            for sub in q.queries:
                m, s = self._exec(sub, si)
                masks.append(m)
                scores.append(jnp.where(m, s, 0.0))
            mask = jnp.stack(masks).any(axis=0)
            mat = jnp.stack(scores)
            best = mat.max(axis=0)
            total = best + jnp.float32(q.tie_breaker) * (mat.sum(axis=0) - best)
            return mask, jnp.where(mask, total * jnp.float32(q.boost), 0.0)
        # term-expansion and scripted-function nodes run host-side via the
        # oracle (the reference keeps MultiTermQuery rewrites on the CPU
        # too — expansion is dictionary work, not scoring work)
        hm, hs = self._oracle._exec(q, seg)
        return jnp.asarray(hm), jnp.asarray(hs)

    # ---- text leaves via the tile kernel ----

    def _field_terms_scored(
        self, si: int, field: str, terms: List[str], boost: float
    ) -> Tuple[jax.Array, jax.Array]:
        """(scores, match_counts) for a list of terms in one field."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        pf = seg.postings.get(field)
        dp = self.device_segments[si].postings.get(field)
        if pf is None or dp is None:
            return jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.int32)
        tile_idx: List[int] = []
        tile_w: List[float] = []
        for t in terms:
            tid = pf.term_id(t)
            if tid < 0:
                continue
            start = int(pf.term_tile_start[tid])
            count = int(pf.term_tile_count[tid])
            w = np.float32(boost) * np.float32(self._oracle._term_weight(field, t))
            tile_idx.extend(range(start, start + count))
            tile_w.extend([float(w)] * count)
        if not tile_idx:
            return jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.int32)
        idx, w, v = scoring.pad_tiles(
            np.asarray(tile_idx, np.int32), np.asarray(tile_w, np.float32)
        )
        rows_doc = dp.doc_ids[jnp.asarray(idx)]
        rows_tf = dp.tfs[jnp.asarray(idx)]
        inv_norm = self._inv_norm(si, field, n)
        scores, cnt = scoring.score_tiles(
            rows_doc, rows_tf, jnp.asarray(w), jnp.asarray(v), inv_norm, n
        )
        return scores, cnt

    def _exec_match(self, q: MatchQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type != TEXT:
            return self._exec_term(
                TermQuery(field=q.field, value=q.query, boost=q.boost), si
            )
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        terms = self.reader.analysis.get(analyzer_name).terms(q.query)
        if not terms:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        scores, cnt = self._field_terms_scored(si, q.field, terms, q.boost)
        if q.operator == "and":
            mask = cnt >= len(terms)
        else:
            msm = max(1, dsl.parse_minimum_should_match(q.minimum_should_match, len(terms)))
            mask = cnt >= msm
        return mask, jnp.where(mask, scores, 0.0)

    def _exec_term(self, q: TermQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field == "_id":
            hm, hs = self._oracle._exec_term(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type in (TEXT, KEYWORD):
            value = q.value
            if isinstance(value, bool):
                value = "true" if value else "false"
            scores, cnt = self._field_terms_scored(si, q.field, [str(value)], q.boost)
            mask = cnt >= 1
            return mask, jnp.where(mask, scores, 0.0)
        dn = self.device_segments[si].numerics.get(q.field)
        if dn is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        values, exists = dn
        target = _coerce_numeric(mf.type, q.value)
        mask = exists & (values == target)
        return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)

    def _exec_terms(self, q: TermsQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field != "_id" and mf is not None and mf.type in (TEXT, KEYWORD):
            # one combined kernel launch for all values (constant-score,
            # so only the match counts matter)
            vals = [
                ("true" if v else "false") if isinstance(v, bool) else str(v)
                for v in q.values
            ]
            _, cnt = self._field_terms_scored(si, q.field, vals, 1.0)
            mask = cnt >= 1
            return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)
        if q.field != "_id" and mf is not None:
            dn = self.device_segments[si].numerics.get(q.field)
            if dn is None:
                return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
            values, exists = dn
            targets = np.array(
                [_coerce_numeric(mf.type, v) for v in q.values], np.float64
            )
            mask = exists & jnp.isin(values, jnp.asarray(targets))
            return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)
        m = jnp.zeros(n, bool)
        for v in q.values:
            tm, _ = self._exec_term(TermQuery(field=q.field, value=v), si)
            m = m | tm
        return m, jnp.where(m, jnp.float32(q.boost), 0.0)

    def _exec_range(self, q: RangeQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type in (TEXT, KEYWORD):
            hm, hs = self._oracle._exec_range(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        dn = self.device_segments[si].numerics.get(q.field)
        if dn is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        values, exists = dn
        mask = exists
        conv = (lambda v: parse_date_millis(v)) if mf.type == DATE else float
        if q.gte is not None:
            mask = mask & (values >= conv(q.gte))
        if q.gt is not None:
            mask = mask & (values > conv(q.gt))
        if q.lte is not None:
            mask = mask & (values <= conv(q.lte))
        if q.lt is not None:
            mask = mask & (values < conv(q.lt))
        return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)

    def _exec_bool(self, q: BoolQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mask = jnp.ones(n, bool)
        scores = jnp.zeros(n, jnp.float32)
        for c in q.must:
            m, s = self._exec(c, si)
            mask = mask & m
            scores = scores + s
        for c in q.filter:
            m, _ = self._exec(c, si)
            mask = mask & m
        if q.should:
            sscores = jnp.zeros(n, jnp.float32)
            match_count = jnp.zeros(n, jnp.int32)
            for c in q.should:
                m, s = self._exec(c, si)
                sscores = sscores + jnp.where(m, s, 0.0)
                match_count = match_count + m.astype(jnp.int32)
            default_msm = 0 if (q.must or q.filter) else 1
            msm = (
                dsl.parse_minimum_should_match(q.minimum_should_match, len(q.should))
                if q.minimum_should_match is not None
                else default_msm
            )
            if msm > 0:
                mask = mask & (match_count >= msm)
            scores = scores + jnp.where(match_count > 0, sscores, 0.0)
        for c in q.must_not:
            m, _ = self._exec(c, si)
            mask = mask & ~m
        if q.boost != 1.0:
            scores = scores * jnp.float32(q.boost)
        return mask, jnp.where(mask, scores, 0.0)

    def _exec_multi_match(self, q: MultiMatchQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        from .executor import expand_match_fields

        seg = self.reader.segments[si]
        n = seg.num_docs
        fields = expand_match_fields(self.reader.mappings, q.fields)
        if not fields:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        per_field = [
            self._exec_match(
                MatchQuery(field=fn, query=q.query, operator=q.operator, boost=q.boost * fb),
                si,
            )
            for fn, fb in fields
        ]
        masks = jnp.stack([m for m, _ in per_field])
        score_mat = jnp.stack([s for _, s in per_field])
        mask = masks.any(axis=0)
        if q.type == "best_fields":
            best = score_mat.max(axis=0)
            if q.tie_breaker:
                rest = score_mat.sum(axis=0) - best
                total = best + jnp.float32(q.tie_breaker) * rest
            else:
                total = best
        else:
            total = score_mat.sum(axis=0)
        return mask, jnp.where(mask, total, 0.0)

    # ---- knn (device matmul + global top-k cut) ----

    def _knn_topk_global(self, sec: KnnSection) -> List[Tuple[jax.Array, jax.Array]]:
        per_seg = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            dv = self.device_segments[si].vectors.get(sec.field)
            if dv is None:
                per_seg.append(
                    (jnp.zeros(n, bool), jnp.zeros(n, jnp.float32), None)
                )
                continue
            vectors, exists = dv
            vf = seg.vectors[sec.field]
            q = jnp.asarray(np.asarray(sec.query_vector, np.float32))[None, :]
            cand_mask = exists
            if sec.filter is not None:
                fm, _ = self._exec(sec.filter, si)
                cand_mask = cand_mask & fm
            live = self.reader.live_docs[si]
            if live is not None:
                cand_mask = cand_mask & jnp.asarray(live)
            k = min(sec.num_candidates, n)
            top_s, top_d = scoring.knn_topk(q, vectors, cand_mask, vf.similarity, k)
            per_seg.append((cand_mask, top_s[0], top_d[0]))
        # global k cut across segments
        entries = []
        for si, item in enumerate(per_seg):
            if len(item) == 3 and item[2] is not None:
                _, top_s, top_d = item
                s_host = np.asarray(top_s)
                d_host = np.asarray(top_d)
                for s, d in zip(s_host, d_host):
                    if np.isfinite(s) and (
                        sec.similarity is None or s >= sec.similarity
                    ):
                        entries.append((-float(s), si, int(d)))
        entries.sort()
        keep = entries[: sec.k]
        out = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            mask = np.zeros(n, bool)
            scores = np.zeros(n, np.float32)
            for negs, ksi, d in keep:
                if ksi == si:
                    mask[d] = True
                    scores[d] = -negs * sec.boost
            out.append((jnp.asarray(mask), jnp.asarray(scores)))
        return out
