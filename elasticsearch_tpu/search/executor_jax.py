"""JAX/TPU shard executor — the production scoring path.

Mirrors the NumPy oracle (executor.py) node for node, but evaluates on
device arrays: postings tiles live in HBM, leaves score via the jitted
gather→BM25→scatter kernel in ops/scoring.py, compounds compose dense
masks/scores with elementwise jnp ops, and collection is lax.top_k.
Tests enforce hit-for-hit parity with the oracle.

Per-segment arrays are uploaded once and cached (`DeviceSegment`) — the
analog of Lucene's "open a reader once, search many times", and the
north star's "posting lists block-decoded once into HBM-resident arrays".
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..index.mapping import DATE, KEYWORD, TEXT, parse_date_millis
from ..index.segment import Segment
from ..models import bm25
from ..ops import scoring
from . import dsl
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    ExistsQuery,
    KnnQueryWrapper,
    KnnSection,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhraseQuery,
    MatchQuery,
    MultiMatchQuery,
    Query,
    QueryParseError,
    RangeQuery,
    TermQuery,
    TermsQuery,
)
from .executor import Hit, NumpyExecutor, ShardReader, TopDocs, _coerce_numeric

# segments below this size score through the shared-shape chunked path;
# above it the per-segment fused program + dense hot rows pay off
FUSED_MIN_DOCS = 100_000
# HBM budget for dense hot-term tf rows, bytes (uint8 per doc per term)
DENSE_ROWS_HBM_BUDGET = 512 * 1024 * 1024


class DevicePostings:
    def __init__(self, pf, device=None):
        self.doc_ids = jax.device_put(pf.doc_ids, device)
        self.tfs = jax.device_put(pf.tfs, device)


def _tree_nbytes(v) -> int:
    if hasattr(v, "nbytes"):
        return int(v.nbytes)
    if isinstance(v, (tuple, list)):
        return sum(_tree_nbytes(x) for x in v)
    if hasattr(v, "__dict__"):
        return sum(
            int(x.nbytes) for x in vars(v).values() if hasattr(x, "nbytes")
        )
    return 0


class _LazyDeviceMap:
    """Per-field device uploads, materialized on first use. Uploading
    every field of every segment eagerly (round 2) burns HBM and makes
    executor regeneration after refresh O(index) instead of O(touched
    fields). Every upload charges the HBM ledger; `charge` is a
    (category, nbytes, breaker) recorder owned by the executor so
    close() can release exactly what was charged."""

    def __init__(self, names, build, charge=None, category="other"):
        self._names = set(names)
        self._build = build
        self._cache: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._charge = charge
        self._category = category

    def get(self, name, default=None):
        if name not in self._names:
            return default
        v = self._cache.get(name)
        if v is None:
            with self._lock:
                v = self._cache.get(name)
                if v is None:
                    v = self._build(name)
                    if self._charge is not None:
                        self._charge(self._category, _tree_nbytes(v), False)
                    self._cache[name] = v
        return v

    def __getitem__(self, name):
        v = self.get(name)
        if v is None:
            raise KeyError(name)
        return v


class DeviceSegment:
    """Device-resident mirror of a Segment's hot arrays (lazy per field)."""

    def __init__(self, seg: Segment, device=None, charge=None):
        self.seg = seg
        self.device = device
        self.postings = _LazyDeviceMap(
            seg.postings, lambda f: DevicePostings(seg.postings[f], device),
            charge=charge, category="postings",
        )
        self.numerics = _LazyDeviceMap(
            seg.numerics,
            lambda f: (
                jax.device_put(seg.numerics[f].values, device),
                jax.device_put(seg.numerics[f].exists, device),
            ),
            charge=charge, category="doc_values",
        )

        def _vec(f):
            vf = seg.vectors[f]
            mat = vf.unit_vectors if vf.similarity == "cosine" else vf.vectors
            if charge is not None:
                # vectors are the big uploads: trip the breaker BEFORE
                # shipping them (HierarchyCircuitBreakerService
                # .addEstimateBytesAndMaybeBreak)
                charge(
                    "vectors",
                    int(mat.nbytes) + int(vf.exists.nbytes),
                    True,
                    precheck_only=True,
                )
            out = (
                jax.device_put(mat, device),
                jax.device_put(vf.exists, device),
            )
            if charge is not None:
                charge("vectors", _tree_nbytes(out), False)
            return out

        self.vectors = _LazyDeviceMap(seg.vectors, _vec)
        # multi-value ordinal CSR for device range/terms masks
        self.ordinals = _LazyDeviceMap(
            seg.ordinals,
            lambda f: (
                jax.device_put(seg.ordinals[f].mv_ords, device),
                jax.device_put(seg.ordinals[f].mv_offsets.astype(np.int32), device),
            ),
            charge=charge, category="doc_values",
        )
        self._adopt_charge = charge

    def adopt_from(self, other: "DeviceSegment") -> None:
        """Cross-generation reuse: a refresh appends segments but never
        mutates existing ones, so the NEW executor generation adopts the
        previous generation's device uploads for every already-uploaded
        field of this (same, immutable) segment instead of re-shipping
        them over the tunnel. Adopted bytes are re-charged to THIS
        executor's ledger records — the old executor's close() releases
        its own — so accounting stays per-generation while the arrays
        are shared."""
        for mine, theirs, cat in (
            (self.postings, other.postings, "postings"),
            (self.numerics, other.numerics, "doc_values"),
            (self.vectors, other.vectors, "vectors"),
            (self.ordinals, other.ordinals, "doc_values"),
        ):
            with theirs._lock:
                items = dict(theirs._cache)
            for k, v in items.items():
                if k in mine._names and k not in mine._cache:
                    mine._cache[k] = v
                    if self._adopt_charge is not None:
                        self._adopt_charge(cat, _tree_nbytes(v), False)


class JaxExecutor:
    """Walks the query tree producing dense device (mask, scores) pairs."""

    def __init__(
        self,
        reader: ShardReader,
        k1: float = bm25.DEFAULT_K1,
        b: float = bm25.DEFAULT_B,
        device=None,
        reuse_from: "Optional[JaxExecutor]" = None,
    ):
        self.reader = reader
        self.k1 = k1
        self.b = b
        self.device = device
        # HBM ledger integration: every device upload is charged and
        # released when the executor is discarded (reader generation
        # change); see common/memory.py
        self._charges: List[Tuple[str, int]] = []
        self._charges_lock = threading.Lock()
        self._closed = False
        # filter-bitset cache identity (set by IndexService._executor);
        # None disables the node-level cache (bare test executors)
        self.cache_ctx = None
        self.device_segments = [
            DeviceSegment(s, device, charge=self._charge)
            for s in reader.segments
        ]
        if reuse_from is not None:
            # NRT generation lifecycle: segments are immutable and a
            # refresh only appends, so the new generation adopts the
            # old one's device uploads for unchanged segments — the
            # swap re-uploads only the NEW segment's columns
            prev = {
                id(ds.seg): ds for ds in reuse_from.device_segments
            }
            for ds in self.device_segments:
                old = prev.get(id(ds.seg))
                if old is not None:
                    ds.adopt_from(old)
        # the oracle is reused for stats, weights, and host-only nodes
        # (match_phrase position verification)
        self._oracle = NumpyExecutor(reader, k1, b)
        self._inv_norm_cache: Dict[Tuple[int, str], jax.Array] = {}
        self._id_maps: Dict[int, Dict[str, int]] = {}
        # block-max / chunked-scorer caches keyed (si, field): reused
        # across requests for the lifetime of this executor (= one reader
        # generation). The underlying tilings + device arrays are cached
        # on the immutable segments and survive executor regeneration.
        self._block_indexes: Dict[Tuple[int, str], object] = {}
        self._chunked_scorers: Dict[Tuple[int, str], object] = {}
        self._fused_scorers: Dict[Tuple[int, str], object] = {}
        self._fused_parts: Dict[Tuple[int, str], object] = {}
        self._fused_mf: Dict[Tuple[int, tuple], object] = {}
        self._sort_rank_cache: Dict[Tuple[int, str, bool], tuple] = {}
        self._entry_docs_dev_cache: Dict[Tuple[int, str], object] = {}
        # device-aggregations engine caches (search/aggs_device.py):
        # per-(segment, field) column exactness profiles plus the int32
        # offset / value-ordinal agg columns (charged to the `aggs`
        # HbmLedger category, released with the executor on generation
        # bump — exactly the invalidation the agg plans need)
        self._agg_profiles: Dict[Tuple[int, str], object] = {}
        self._agg_cols: Dict[tuple, object] = {}
        # IVF ANN tier (ops/ivf.py, search/ann.py): per-(segment, field,
        # build-shape) cluster indexes, built lazily per executor
        # generation — the same invalidation as the agg tables — and
        # charged to the `ann` HbmLedger category. None caches a miss
        # (small segment / budget degrade) so the exact path is chosen
        # without re-locking per batch.
        self._ann_indexes: Dict[tuple, object] = {}
        # learned-sparse serving (ops/impact.py, search/sparse.py):
        # per-(segment, field, storage-mode) ImpactScorers over the
        # impact-ordered postings column, charged to the `impacts`
        # HbmLedger category; an upload that would not fit degrades to
        # the host dense oracle (None cached)
        self._impact_scorers: Dict[tuple, object] = {}
        # second-stage reranker columns (search/rescorer.py): per-model
        # shard-level concatenated `rank_vectors` token arrays, built
        # lazily per executor generation and charged to the `rerank`
        # HbmLedger category; a build that would not fit DEGRADES TO
        # SKIP (None cached — the request keeps its first-stage order)
        self._rerank_columns: Dict[tuple, object] = {}
        self._seg_weights: Dict[Tuple[int, str], np.ndarray] = {}
        self._df_maps: Dict[str, Dict[str, int]] = {}
        self._shard_dfs: Dict[Tuple[str, str], int] = {}
        self._deleted_count: Optional[int] = None
        # cache-miss builds are guarded so concurrent batcher workers
        # can't duplicate a dense hot-row build (each one is up to
        # DENSE_ROWS_HBM_BUDGET of HBM) or a tiling/compile; RLock
        # because fused_scorer → _inv_norm/_segment_weights nest
        self._build_lock = threading.RLock()
        # persistent padded staging slabs: per-(family, shape) rings of
        # reusable query-operand buffers (fused plan uploads, kNN query
        # rows, chunk tile planes) handed out round-robin to the batcher
        # instead of fresh allocations every batch; bytes ride the
        # `serving` ledger category
        self._staging_slabs: Dict[tuple, list] = {}
        self._staging_lock = threading.Lock()

    # ---- per-(segment, field) dense inverse-norm array ----

    def _charge(
        self, category: str, nbytes: int, breaker: bool,
        precheck_only: bool = False,
    ) -> None:
        from ..common.memory import hbm_ledger

        if precheck_only:
            if breaker and nbytes and not hbm_ledger.would_fit(nbytes):
                from ..common.memory import CircuitBreakingException

                hbm_ledger.stats_counters["tripped"] += 1
                raise CircuitBreakingException(
                    f"[hbm] Data too large for [{category}]: "
                    f"{nbytes} bytes would exceed the budget",
                    bytes_wanted=nbytes,
                    limit=hbm_ledger.budget,
                )
            return
        with self._charges_lock:
            if self._closed:
                # a pinned scroll/PIT context kept using this executor
                # after its generation was replaced: don't record bytes
                # nobody will ever release
                return
            hbm_ledger.add(category, nbytes, breaker=False)
            self._charges.append((category, nbytes))

    def staging_slab(self, family: str, shape, dtype=np.int32) -> np.ndarray:
        """A reusable pre-allocated query-operand buffer for the serving
        hot path (batcher dispatch). Buffers are handed out from a
        fixed-size ring per (family, shape, dtype) so a buffer is never
        rewritten while an earlier batch's upload can still be reading
        it: the ring is sized to cover every dispatcher worker at full
        pipeline depth with one spare each. Callers must fully rewrite
        the regions they use (pack_plans/score_into do)."""
        key = (family, tuple(int(x) for x in shape), np.dtype(dtype).str)
        with self._staging_lock:
            entry = self._staging_slabs.get(key)
            if entry is None:
                from ..common.settings import pipeline_depth
                from .batcher import WORKERS

                ring = max(2, WORKERS * (pipeline_depth() + 1))
                bufs = [np.zeros(shape, dtype) for _ in range(ring)]
                self._charge(
                    "serving", int(sum(b.nbytes for b in bufs)), False
                )
                entry = [0, bufs]
                self._staging_slabs[key] = entry
            i, bufs = entry
            entry[0] = (i + 1) % len(bufs)
            return bufs[i]

    def close(self) -> None:
        """Releases this executor's HBM ledger charges (the device
        arrays themselves are freed by JAX when the references die)."""
        from ..common.memory import hbm_ledger

        with self._charges_lock:
            self._closed = True
            charges, self._charges = self._charges, []
        for category, nbytes in charges:
            hbm_ledger.release(category, nbytes)

    def prewarm(self, settings=None) -> None:
        """Generation-lifecycle prewarm (the NRT refresher calls this
        right after a generation swap, BEFORE queries observe the new
        executor): uploads the serving-hot device columns and builds
        the per-generation serving caches — postings tilings +
        block-max indexes + chunked scorers, inverse norms, vector
        columns, the IVF indexes (when `index.knn.type: ivf`) and the
        rerank token columns — so the first query after a refresh pays
        neither uploads nor k-means. Best-effort by design: any failure
        (HBM breaker, fault injection) leaves the lazy path to do what
        it always did."""
        from ..index.mapping import RANK_VECTORS

        settings = settings or {}
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if n == 0:
                continue
            for fname in seg.postings:
                try:
                    self.device_segments[si].postings.get(fname)
                    self._inv_norm(si, fname, n)
                    self.block_index(si, fname)
                    self.chunked_scorer(si, fname)
                except Exception:
                    pass
            for fname in seg.vectors:
                try:
                    self.device_segments[si].vectors.get(fname)
                except Exception:
                    pass  # breaker: the lazy path degrades identically
                if str(settings.get("knn.type", "exact")) == "ivf":
                    try:
                        from . import ann as ann_mod

                        class _Sec:
                            nprobe = None

                        spec = ann_mod.resolve(settings, _Sec(), False)
                        if spec is not None:
                            self.ann_index(si, fname, spec)
                    except Exception:
                        pass
            for fname in getattr(seg, "sparse", None) or {}:
                try:
                    quant = (
                        str(settings.get("sparse.quantization", "int8"))
                        == "int8"
                    )
                    self.impact_scorer(si, fname, quant)
                except Exception:
                    pass
        for fname, mf in list(self.reader.mappings.fields.items()):
            if getattr(mf, "type", None) == RANK_VECTORS:
                try:
                    from ..models import rerank as rerank_model

                    model = rerank_model.resolve_model(
                        self.reader.mappings, settings, fname
                    )
                    if model is not None:
                        self.rerank_column(model)
                except Exception:
                    pass

    # ---- filter-context evaluation via the device bitset cache ----

    def filter_mask(self, q: Query, si: int) -> jax.Array:
        """Match mask of one filter-context clause on one segment. On
        the jax backend cached bitsets are DEVICE-RESIDENT boolean
        arrays (HBM, `query_cache` ledger category) that the scoring
        kernels consume directly — a hit skips the whole filter
        sub-tree evaluation."""
        ctx = self.cache_ctx
        if ctx is None or not dsl.is_cacheable_filter(q):
            return self._exec(q, si)[0]
        from .query_cache import filter_cache

        fkey = dsl.canonical_key(q)
        cached = filter_cache.get(ctx, si, fkey)
        if cached is not None:
            return cached
        mask = self._exec(q, si)[0]
        if mask.dtype != jnp.bool_:
            mask = mask.astype(jnp.bool_)
        mask = jax.device_put(mask, self.device)
        filter_cache.put(ctx, si, fkey, mask, int(mask.nbytes))
        return mask

    def combined_filter_mask(self, fclauses, si: int) -> jax.Array:
        """AND of the (cached) filter bitsets and the live-docs bitmap —
        the combined mask the scoring kernels take as their live
        operand."""
        mask = None
        for c in fclauses:
            m = self.filter_mask(c, si)
            mask = m if mask is None else (mask & m)
        live = self.reader.live_docs[si]
        if live is not None:
            l = jnp.asarray(live)
            mask = l if mask is None else (mask & l)
        if mask is None:
            mask = jnp.ones(self.reader.segments[si].num_docs, bool)
        return mask

    # ---- bitset-masked plan serving (the filtered-bool hot path) ----

    def search_plan_filtered(
        self, stripped, fclauses, k: int, tth, mappings, analysis
    ) -> Optional[TopDocs]:
        """Serving path for a bool query whose filter clauses resolve to
        cached bitsets: the scoring part reduces to a flat Match/Serve
        plan and the combined bitset rides the fused kernels' live-mask
        operand (ops/scoring.py) — filter re-evaluation is skipped
        entirely on a warm cache. Returns None when the scoring part
        can't be planned (caller falls back to the generic tree walk,
        which also consumes the cached bitsets)."""
        from .batcher import extract_match_plan, extract_serve_plan

        mplan = None
        splan = None
        if (
            isinstance(stripped, dsl.BoolQuery)
            and len(stripped.must) == 1
            and not stripped.should
            and stripped.boost == 1.0
            and isinstance(stripped.must[0], MatchQuery)
        ):
            # single-must match: the single-field fused/chunked engine
            # (with block-max pruning when totals are untracked)
            mplan = extract_match_plan(
                stripped.must[0], mappings, analysis, tth
            )
        if mplan is None:
            splan = extract_serve_plan(stripped, mappings, analysis)
            if splan is None:
                return None
        kb = 16 if k <= 16 else scoring.next_bucket(k, 16)
        cands: List[Tuple[float, int, int]] = []
        total = 0
        pruned = False
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if n == 0:
                continue
            base = self.combined_filter_mask(fclauses, si)
            if mplan is not None:
                got = self._match_segment_filtered(mplan, si, base, kb)
            else:
                got = self._serve_segment_filtered(splan, si, base, kb)
            if got is None:
                # small segment / slot overflow: dense scoring with the
                # bitset masked straight into the top-k kernel
                mask, sc = self._exec(stripped, si)
                mask = mask & base
                s, d = scoring.topk_hits(sc, mask, min(kb, n))
                got = (
                    np.asarray(s),
                    np.asarray(d),
                    int(np.asarray(mask.sum())),
                    False,
                )
            s, d, tot, seg_pruned = got
            pruned = pruned or seg_pruned
            total += tot
            finite = np.isfinite(s)
            for sc_, doc in zip(s[finite], d[finite]):
                cands.append((float(sc_), si, int(doc)))
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        page = cands[:k]
        hits = [
            Hit(
                score=s,
                segment=si,
                local_doc=d,
                doc_id=self.reader.segments[si].doc_ids[d],
            )
            for s, si, d in page
        ]
        return TopDocs(
            total=total,
            hits=hits,
            max_score=hits[0].score if hits else None,
            # pruned tiles make the collected count a lower bound
            relation="gte" if pruned else "eq",
        )

    def _match_segment_filtered(self, plan, si: int, base, kb: int):
        """(scores[k], docs[k], total, pruned) for one MatchPlan on one
        segment, the filter bitset masking the kernels; None → dense
        fallback."""
        field = plan.field
        n = self.reader.segments[si].num_docs
        kk = min(kb, n)
        fs = self.fused_scorer(si, field)
        if fs is not None:
            fplan = self.fused_plan(
                fs, si, field, plan.terms, plan.boost, plan.msm
            )
            if fplan is not None:
                # single-request path: a 1-row launch (the smallest
                # ladder bucket), not the full padded width
                s, d, tot = fs.search(
                    [fplan], kk, plan.msm > 1, live=base, rows=1
                )
                return s[0], d[0], int(tot[0]), False
        bmx = self.block_index(si, field)
        cs = self.chunked_scorer(si, field)
        if bmx is None or cs is None:
            return None
        # pruning only when totals are untracked: a term's doc_freq
        # can't prove >= cap FILTERED matches, so the unfiltered path's
        # capped-total shortcut is unsound here
        prune_ok = plan.wand_ok and plan.tth_cap == 0
        with_cnt = plan.msm > 1
        acc, cnt = cs.new_acc(with_cnt, rows=1)
        plans = bmx.plan(list(plan.terms), plan.boost)
        empty_i = np.empty(0, np.int64)
        empty_w = np.empty(0, np.float32)
        ess, hots = [], []
        for p in plans:
            (hots if (prune_ok and p.hot) else ess).append(p)
        if not ess and hots:
            # the essential set must be non-empty or θ is -inf
            hots.sort(key=lambda p: p.tile_count)
            ess.append(hots.pop(0))

        def tiles_of(ps):
            tl = [
                np.arange(
                    p.tile_start, p.tile_start + p.tile_count, dtype=np.int64
                )
                for p in ps
            ]
            wl = [np.full(p.tile_count, p.weight, np.float32) for p in ps]
            return (
                np.concatenate(tl) if tl else empty_i,
                np.concatenate(wl) if wl else empty_w,
            )

        t_ess, w_ess = tiles_of(ess)
        acc, cnt = cs.score_into(acc, cnt, [t_ess], [w_ess])
        pruned = False
        if hots:
            theta, accmax = cs.threshold(acc, kk, live=base)
            # blocks with zero filter-passing docs can never contribute
            # a candidate — mask them out of the survival test
            bl = np.asarray(base)
            bs = bmx.tiling.block_size
            nb = bmx.tiling.n_blocks
            padded = np.zeros(nb * bs, bool)
            padded[: len(bl)] = bl
            block_live = padded.reshape(nb, bs).any(axis=1)
            sum_bounds = np.zeros(nb, np.float32)
            for p in hots:
                sum_bounds += bmx.block_bounds(p)
            potential = accmax[0] + sum_bounds
            tl2, wl2 = [], []
            for p in hots:
                kept = bmx.surviving_tiles(
                    p, potential, theta[0], block_live=block_live
                )
                if len(kept) < p.tile_count:
                    pruned = True
                if len(kept):
                    tl2.append(kept)
                    wl2.append(np.full(len(kept), p.weight, np.float32))
            acc, cnt = cs.score_into(
                acc,
                cnt,
                [np.concatenate(tl2) if tl2 else empty_i],
                [np.concatenate(wl2) if wl2 else empty_w],
            )
        msm_arr = np.asarray([plan.msm], np.int32)
        s, d, tot = cs.finalize(acc, cnt, msm_arr, kk, live=base)
        return s[0], d[0], int(tot[0]), pruned

    def _serve_segment_filtered(self, plan, si: int, base, kb: int):
        """(scores[k], docs[k], total, pruned) for one ServePlan on one
        segment via the multi-field fused kernel with the bitset as its
        live operand; None → dense fallback."""
        n = self.reader.segments[si].num_docs
        kk = min(kb, n)
        fs = self.fused_scorer_mf(si, plan.fields)
        if fs is None:
            return None
        sections = []
        for g in plan.groups:
            parts = self.fused_parts(si, g.field)
            if parts is None:
                return None
            sec = self.fused_plan_field(si, g.field, parts, g.terms, plan.boost)
            if sec is None:
                return None
            sections.append(sec)
        s, d, tot = fs.search(
            [(sections, plan.msm)], kk, plan.combine, plan.tie, live=base,
            rows=1,
        )
        return s[0], d[0], int(tot[0]), False

    def _inv_norm(self, si: int, field: str, n: int) -> jax.Array:
        from .executor import DFS_STATS

        dfs = DFS_STATS.get()
        if dfs is not None and field in dfs.get("fields", {}):
            # DFS avgdl differs from the shard's — cached per request
            # (DFS_NORM_CACHE contextvar) so each (segment, field) norm
            # array uploads at most once per request
            from .executor import DFS_NORM_CACHE

            req_cache = DFS_NORM_CACHE.get()
            key = (id(self), si, field)
            if req_cache is not None:
                arr = req_cache.get(key)
                if arr is not None:
                    return arr
            cache = self._oracle._field_cache(field)  # ctx-aware
            pf = self.reader.segments[si].postings.get(field)
            mf = self.reader.mappings.get(field)
            if pf is None:
                host = np.zeros(n, np.float32)
            elif mf is not None and mf.type != TEXT:
                host = np.full(n, cache[1], np.float32)
            else:
                host = cache[pf.norms.astype(np.int64)]
            arr = jax.device_put(host, self.device)
            if req_cache is not None:
                req_cache[key] = arr
            return arr
        key = (si, field)
        arr = self._inv_norm_cache.get(key)
        if arr is None:
            with self._build_lock:
                arr = self._inv_norm_cache.get(key)
                if arr is not None:
                    return arr
                cache = self._oracle._field_cache(field)
                pf = self.reader.segments[si].postings.get(field)
                mf = self.reader.mappings.get(field)
                if pf is None:
                    host = np.zeros(n, np.float32)
                elif mf is not None and mf.type != TEXT:
                    # omitted norms → encodedNorm 1 for every doc
                    host = np.full(n, cache[1], np.float32)
                else:
                    host = cache[pf.norms.astype(np.int64)]
                arr = jax.device_put(host, self.device)
                self._charge("norms", int(host.nbytes), False)
                self._inv_norm_cache[key] = arr
        return arr

    # ---- entry point (mirrors NumpyExecutor.search) ----

    def search(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> TopDocs:
        return self.execute(query, size, from_, knn, min_score)[0]

    def execute(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> Tuple[TopDocs, List[np.ndarray]]:
        from .executor import PROFILE_CTX

        prof = PROFILE_CTX.get()
        t0 = time.perf_counter_ns() if prof is not None else 0
        knn_sets = [self._knn_topk_global(sec) for sec in (knn or [])]
        device_pairs: List[Tuple[jax.Array, jax.Array]] = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if query is None and not knn_sets:
                q: Optional[Query] = MatchAllQuery()
            else:
                q = query
            if q is not None:
                mask, scores = self._exec(q, si)
            else:
                mask = jnp.zeros(n, bool)
                scores = jnp.zeros(n, jnp.float32)
            for ks in knn_sets:
                kmask, kscores = ks[si]
                scores = jnp.where(kmask, scores + kscores, scores)
                mask = mask | kmask
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & jnp.asarray(live)
            if min_score is not None:
                mask = mask & (scores >= jnp.float32(min_score))
            device_pairs.append((mask, scores))
        if prof is not None:
            # phase boundary: everything queued so far is device work
            jax.block_until_ready([a for pair in device_pairs for a in pair])
            t1 = time.perf_counter_ns()
            prof["device_scoring_ns"] = prof.get("device_scoring_ns", 0) + (
                t1 - t0
            )
        per_segment: List[Tuple[np.ndarray, np.ndarray]] = [
            (np.asarray(m), np.asarray(s)) for m, s in device_pairs
        ]
        if prof is not None:
            t2 = time.perf_counter_ns()
            prof["device_transfer_ns"] = prof.get("device_transfer_ns", 0) + (
                t2 - t1
            )
            t0 = t2  # host merge starts here

        # global collection (same ordering as the oracle): score desc,
        # (segment, doc) asc — vectorized over the matching docs only
        total = int(sum(m.sum() for m, _ in per_segment))
        cand_scores: List[np.ndarray] = []
        cand_seg: List[np.ndarray] = []
        cand_doc: List[np.ndarray] = []
        for si, (mask, scores) in enumerate(per_segment):
            idx = np.nonzero(mask)[0]
            if len(idx):
                cand_scores.append(scores[idx].astype(np.float64))
                cand_seg.append(np.full(len(idx), si, np.int64))
                cand_doc.append(idx.astype(np.int64))
        masks = [m for m, _ in per_segment]
        if not cand_scores:
            if prof is not None:
                prof["host_merge_ns"] = prof.get("host_merge_ns", 0) + (
                    time.perf_counter_ns() - t0
                )
            return TopDocs(total=total, hits=[], max_score=None), masks
        s = np.concatenate(cand_scores)
        sg = np.concatenate(cand_seg)
        dc = np.concatenate(cand_doc)
        need = from_ + size
        if need < len(s):
            part = np.argpartition(-s, need)[: need + 1]
            # keep enough candidates to break ties deterministically: take
            # everything scoring >= the partition's lowest kept score
            thresh = s[part].min()
            keep = np.nonzero(s >= thresh)[0]
            s, sg, dc = s[keep], sg[keep], dc[keep]
        order = np.lexsort((dc, sg, -s))
        max_score = float(s[order[0]])
        top = order[from_ : from_ + size]
        hits = [
            Hit(
                score=float(s[i]),
                segment=int(sg[i]),
                local_doc=int(dc[i]),
                doc_id=self.reader.segments[int(sg[i])].doc_ids[int(dc[i])],
            )
            for i in top
        ]
        if prof is not None:
            prof["host_merge_ns"] = prof.get("host_merge_ns", 0) + (
                time.perf_counter_ns() - t0
            )
        return TopDocs(total=total, hits=hits, max_score=max_score), masks

    # ---- node dispatch ----

    def _exec(self, q: Query, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        if isinstance(q, MatchAllQuery):
            return jnp.ones(n, bool), jnp.full(n, np.float32(q.boost), jnp.float32)
        if isinstance(q, MatchNoneQuery):
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if isinstance(q, MatchQuery):
            return self._exec_match(q, si)
        if isinstance(q, TermQuery):
            return self._exec_term(q, si)
        if isinstance(q, TermsQuery):
            return self._exec_terms(q, si)
        if isinstance(q, RangeQuery):
            return self._exec_range(q, si)
        if isinstance(q, ExistsQuery):
            # host-computed masks are cheap and static; reuse oracle
            hm, hs = self._oracle._exec(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        if isinstance(q, BoolQuery):
            return self._exec_bool(q, si)
        if isinstance(q, ConstantScoreQuery):
            m = self.filter_mask(q.filter_query, si)
            return m, jnp.where(m, jnp.float32(q.boost), 0.0)
        if isinstance(q, MultiMatchQuery):
            return self._exec_multi_match(q, si)
        if isinstance(q, MatchPhraseQuery):
            return self._exec_phrase(q, si)
        if isinstance(q, KnnQueryWrapper):
            return self._exec_knn_query(q.knn, si)
        if isinstance(q, dsl.IdsQuery):
            return self._exec_ids(q, si)
        if isinstance(
            q, (dsl.PrefixQuery, dsl.WildcardQuery, dsl.RegexpQuery, dsl.FuzzyQuery)
        ):
            # MultiTermQuery constant-score rewrite: dictionary expansion
            # stays on the host (as the reference's rewrites do), but the
            # expanded terms score as ONE device kernel launch
            return self._exec_expanded(q, si)
        if isinstance(q, dsl.DisMaxQuery):
            masks, scores = [], []
            for sub in q.queries:
                m, s = self._exec(sub, si)
                masks.append(m)
                scores.append(jnp.where(m, s, 0.0))
            mask = jnp.stack(masks).any(axis=0)
            mat = jnp.stack(scores)
            best = mat.max(axis=0)
            total = best + jnp.float32(q.tie_breaker) * (mat.sum(axis=0) - best)
            return mask, jnp.where(mask, total * jnp.float32(q.boost), 0.0)
        # term-expansion and scripted-function nodes run host-side via the
        # oracle (the reference keeps MultiTermQuery rewrites on the CPU
        # too — expansion is dictionary work, not scoring work)
        hm, hs = self._oracle._exec(q, seg)
        return jnp.asarray(hm), jnp.asarray(hs)

    # ---- text leaves via the tile kernel ----

    def term_tiles(
        self, si: int, field: str, terms: List[str], boost: float
    ) -> Tuple[List[int], List[float]]:
        """Unpadded (tile indices, per-tile weights) for terms in one
        field of one segment — the host-side query plan the kernels eat."""
        pf = self.reader.segments[si].postings.get(field)
        tile_idx: List[int] = []
        tile_w: List[float] = []
        if pf is None:
            return tile_idx, tile_w
        for t in terms:
            tid = pf.term_id(t)
            if tid < 0:
                continue
            start = int(pf.term_tile_start[tid])
            count = int(pf.term_tile_count[tid])
            w = np.float32(boost) * np.float32(self._oracle._term_weight(field, t))
            tile_idx.extend(range(start, start + count))
            tile_w.extend([float(w)] * count)
        return tile_idx, tile_w

    def _field_terms_scored(
        self, si: int, field: str, terms: List[str], boost: float
    ) -> Tuple[jax.Array, jax.Array]:
        """(scores, match_counts) for a list of terms in one field."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        dp = self.device_segments[si].postings.get(field)
        if dp is None:
            return jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.int32)
        tile_idx, tile_w = self.term_tiles(si, field, terms, boost)
        if not tile_idx:
            return jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.int32)
        idx, w, v = scoring.pad_tiles(
            np.asarray(tile_idx, np.int32), np.asarray(tile_w, np.float32)
        )
        rows_doc = dp.doc_ids[jnp.asarray(idx)]
        rows_tf = dp.tfs[jnp.asarray(idx)]
        inv_norm = self._inv_norm(si, field, n)
        scores, cnt = scoring.score_tiles(
            rows_doc, rows_tf, jnp.asarray(w), jnp.asarray(v), inv_norm, n
        )
        return scores, cnt

    # ---- serving-path scorer plumbing (batcher entry points) ----

    def _segment_weights(self, si: int, field: str) -> np.ndarray:
        """float32[n_terms] SHARD-level BM25 idf per local term id of one
        segment (IndexSearcher.collectionStatistics — same stats the
        unpruned path uses, so batched/pruned scores match the oracle)."""
        key = (si, field)
        w = self._seg_weights.get(key)
        if w is None:
            with self._build_lock:
                w = self._seg_weights.get(key)
                if w is not None:
                    return w
                pf = self.reader.segments[si].postings[field]
                dc, _ = self.reader.field_stats(field)
                if len(self.reader.segments) == 1:
                    df = pf.term_df.astype(np.float64)
                else:
                    dfmap = self._df_map(field)
                    df = np.array([dfmap.get(t, 0) for t in pf.terms], np.float64)
                # same float path as bm25.idf (float64 math, float32 result)
                w = np.float32(np.log(1.0 + (dc - df + 0.5) / (df + 0.5)))
                self._seg_weights[key] = w
        return w

    def _df_map(self, field: str) -> Dict[str, int]:
        m = self._df_maps.get(field)
        if m is None:
            with self._build_lock:
                m = self._df_maps.get(field)
                if m is not None:
                    return m
                m = {}
                for seg in self.reader.segments:
                    pf = seg.postings.get(field)
                    if pf is not None:
                        for t, d in zip(pf.terms, pf.term_df.tolist()):
                            m[t] = m.get(t, 0) + int(d)
                self._df_maps[field] = m
        return m

    def shard_df(self, field: str, term: str) -> int:
        key = (field, term)
        df = self._shard_dfs.get(key)
        if df is None:
            df, _ = self.reader.term_stats(field, term)
            self._shard_dfs[key] = df
        return df

    @property
    def deleted_count(self) -> int:
        if self._deleted_count is None:
            self._deleted_count = int(
                sum(int((~l).sum()) for l in self.reader.live_docs if l is not None)
            )
        return self._deleted_count

    def block_index(self, si: int, field: str):
        """Cached BlockMaxIndex (shard-level stats over the segment's
        block-aligned tiling) — None when the field has no postings.

        Also the source of truth for the mesh serving stack
        (parallel/mesh_executor.MeshExecutor builds its per-entry tile
        plans and weights from this index, and its norm operands from
        `_inv_norm`), which is what keeps the SPMD path's scoring
        inputs identical to the sequential kernels'."""
        key = (si, field)
        if key in self._block_indexes:
            return self._block_indexes[key]
        with self._build_lock:
            if key in self._block_indexes:
                return self._block_indexes[key]
            from ..ops.wand import BlockMaxIndex, get_tiling

            seg = self.reader.segments[si]
            pf = seg.postings.get(field)
            if pf is None:
                bmx = None  # cache the miss: no re-lock per batch
            else:
                tiling = get_tiling(pf, seg.num_docs)
                bmx = BlockMaxIndex(
                    tiling,
                    self._segment_weights(si, field),
                    self._oracle._field_cache(field),
                )
            self._block_indexes[key] = bmx
            return bmx

    def chunked_scorer(self, si: int, field: str):
        """Cached fixed-shape ChunkedScorer over the block-aligned tiling
        of one segment (the batcher's launch engine)."""
        key = (si, field)
        if key in self._chunked_scorers:
            return self._chunked_scorers[key]
        with self._build_lock:
            if key in self._chunked_scorers:
                return self._chunked_scorers[key]
            bmx = self.block_index(si, field)
            if bmx is None:
                cs = None  # cache the miss: no re-lock per batch
            else:
                seg = self.reader.segments[si]
                cs = scoring.ChunkedScorer(
                    bmx.tiling.doc_ids,
                    bmx.tiling.tfs,
                    self._inv_norm(si, field, seg.num_docs),
                    self.reader.live_docs[si],
                    block_size=bmx.tiling.block_size,
                )
            self._chunked_scorers[key] = cs
            return cs

    def fused_scorer(self, si: int, field: str):
        """Cached single-round-trip FusedScorer for one large segment
        (ops/scoring.py module comment: on the measured hardware, one
        fused call with dense hot-term rows beats multi-phase pruning).
        None for small segments (the chunked path compiles shared shapes
        there) or fields without postings."""
        key = (si, field)
        if key in self._fused_scorers:
            return self._fused_scorers[key]
        with self._build_lock:
            return self._fused_scorer_build(key, si, field)

    def fused_parts(self, si: int, field: str):
        """Cached per-(segment, field) device arrays for fused scoring:
        dict(doc_ids, tfs, inv_norm, dense, hot_rank), or None when the
        field has no postings / the segment is below FUSED_MIN_DOCS.
        Shared by the single-field FusedScorer and the multi-field
        MultiFusedScorer so dense hot rows are built once per field."""
        key = (si, field)
        if key in self._fused_parts:
            return self._fused_parts[key]
        with self._build_lock:
            if key in self._fused_parts:
                return self._fused_parts[key]
            parts = self._fused_parts_build(si, field)
            self._fused_parts[key] = parts
            return parts

    def fused_scorer_mf(self, si: int, fields: tuple):
        """Cached MultiFusedScorer over one segment and a field tuple
        (the multi_match / bool serving engine); None when any field
        lacks parts."""
        key = (si, tuple(fields))
        if key in self._fused_mf:
            return self._fused_mf[key]
        with self._build_lock:
            if key in self._fused_mf:
                return self._fused_mf[key]
            parts = [self.fused_parts(si, f) for f in fields]
            if any(p is None for p in parts):
                fs = None
            else:
                fs = scoring.MultiFusedScorer(
                    fields, parts, self.reader.live_docs[si]
                )
            self._fused_mf[key] = fs
            return fs

    def _fused_scorer_build(self, key, si: int, field: str):
        if key in self._fused_scorers:
            return self._fused_scorers[key]
        parts = self.fused_parts(si, field)
        fs = None
        if parts is not None:
            fs = scoring.FusedScorer(
                parts["doc_ids"],
                parts["tfs"],
                parts["inv_norm"],
                self.reader.live_docs[si],
                parts["dense"],
            )
            fs.hot_rank = parts["hot_rank"]
        self._fused_scorers[key] = fs
        return fs

    def _fused_parts_build(self, si: int, field: str):
        seg = self.reader.segments[si]
        pf = seg.postings.get(field)
        if pf is not None and seg.num_docs >= FUSED_MIN_DOCS:
            n = seg.num_docs
            dp = self.device_segments[si].postings[field]
            n_terms = len(pf.terms)
            # per-term max tf (dense rows are uint8: terms with a larger
            # tf anywhere stay sparse for exactness)
            counts = pf.term_tile_count.astype(np.int64)
            starts = pf.term_tile_start.astype(np.int64)
            tile_of = (
                np.arange(int(counts.sum()), dtype=np.int64)
                - np.repeat(np.cumsum(counts) - counts, counts)
                + np.repeat(starts, counts)
            )
            term_of_tile = np.repeat(np.arange(n_terms, dtype=np.int64), counts)
            term_max_tf = np.zeros(n_terms, np.int64)
            np.maximum.at(term_max_tf, term_of_tile, pf.tile_max_tf[tile_of])
            hot_mask = (pf.term_df.astype(np.int64) >= max(1024, n // 128)) & (
                term_max_tf <= scoring.DENSE_TF_MAX
            )
            hot_ids = np.nonzero(hot_mask)[0]
            # HBM budget for dense rows (uint8 per doc per hot term):
            # the static per-field cap AND the live global ledger — when
            # HBM is tight the fused path degrades to sparse tiles (an
            # optimization lost, not correctness) and counts it
            from ..common.memory import hbm_ledger

            max_hot = max(0, DENSE_ROWS_HBM_BUDGET // max(n, 1))
            headroom = max(0, hbm_ledger.budget - hbm_ledger.used)
            max_hot = min(max_hot, headroom // max(n + 1, 1))
            if len(hot_ids) > max_hot:
                order = np.argsort(-pf.term_df[hot_ids])
                hot_ids = np.sort(hot_ids[order[:max_hot]])
                hbm_ledger.note_degraded()
            if len(hot_ids):
                sel = np.isin(term_of_tile, hot_ids)
                hot_tiles = tile_of[sel]
                rank_map = {int(t): r for r, t in enumerate(hot_ids)}
                rank_of_tile = np.array(
                    [rank_map[int(t)] for t in term_of_tile[sel]], np.int32
                )
                dense = scoring.build_dense_rows(
                    dp.doc_ids,
                    dp.tfs,
                    jnp.asarray(hot_tiles.astype(np.int32)),
                    jnp.asarray(rank_of_tile),
                    n_hot=len(hot_ids),
                    n_docs=n,
                )
                self._charge("dense_rows", _tree_nbytes(dense), False)
                hot_rank = rank_map
            else:
                dense = None
                hot_rank = {}
            return {
                "doc_ids": dp.doc_ids,
                "tfs": dp.tfs,
                "inv_norm": self._inv_norm(si, field, n),
                "dense": dense,
                "hot_rank": hot_rank,
            }
        return None

    def fused_plan_field(
        self, si: int, field: str, parts, terms_flagged, boost: float
    ):
        """One field's section of a MultiFusedScorer plan:
        (rare_tiles, rare_w_signed, hot_ranks, hot_w_signed) — weight
        sign marks whether a term counts toward the match threshold
        (positive = required/counted). terms_flagged: [(term, term_boost,
        counted)]. None on slot-budget overflow."""
        pf = self.reader.segments[si].postings.get(field)
        if pf is None:
            return (
                np.empty(0, np.int64), np.empty(0, np.float32),
                np.empty(0, np.int64), np.empty(0, np.float32),
            )
        weights = self._segment_weights(si, field)
        rt: list = []
        rw: list = []
        hr: list = []
        hw: list = []
        for t, tb, counted in terms_flagged:
            tid = pf.term_id(t)
            if tid < 0:
                continue
            w = float(weights[tid]) * boost * tb
            if w < 0.0:
                # a negative weight (e.g. field^-2) would corrupt the
                # sign-encoded count flag — exact path handles it
                return None
            if w == 0.0:
                # a zero weight can't carry the count flag in its sign;
                # nudge to the smallest positive float so required terms
                # still count (score contribution is ~0 either way)
                w = 1e-30
            if not counted:
                w = -w
            r = parts["hot_rank"].get(tid)
            if r is not None:
                hr.append(r)
                hw.append(w)
            else:
                s0 = int(pf.term_tile_start[tid])
                c = int(pf.term_tile_count[tid])
                rt.extend(range(s0, s0 + c))
                rw.extend([w] * c)
        if len(rt) > scoring.FUSED_T_RARE or len(hr) > scoring.FUSED_H:
            return None
        return (
            np.asarray(rt, np.int64),
            np.asarray(rw, np.float32),
            np.asarray(hr, np.int64),
            np.asarray(hw, np.float32),
        )

    def fused_plan(self, fs, si: int, field: str, terms, boost: float, msm: int):
        """(rare_tiles, rare_w, hot_ranks, hot_w, msm) for FusedScorer,
        or None when the query overflows the fixed slot budgets."""
        pf = self.reader.segments[si].postings[field]
        weights = self._segment_weights(si, field)
        rt: list = []
        rw: list = []
        hr: list = []
        hw: list = []
        for t in terms:
            tid = pf.term_id(t)
            if tid < 0:
                continue
            w = float(weights[tid]) * boost
            r = fs.hot_rank.get(tid)
            if r is not None:
                hr.append(r)
                hw.append(w)
            else:
                s0 = int(pf.term_tile_start[tid])
                c = int(pf.term_tile_count[tid])
                rt.extend(range(s0, s0 + c))
                rw.extend([w] * c)
        if len(rt) > fs.t_rare or len(hr) > fs.n_hot_slots:
            return None
        return (
            np.asarray(rt, np.int64),
            np.asarray(rw, np.float32),
            np.asarray(hr, np.int64),
            np.asarray(hw, np.float32),
            msm,
        )

    def _sort_ranks(self, si: int, field: str, desc: bool):
        """Device int32 rank column for one segment's numeric doc-value
        field: rank orders by (value, doc) asc — or (-value, doc) for
        desc — with missing docs ranked last by doc. Ranks are EXACT at
        any magnitude (dates included), unlike float32 keys on a TPU
        without x64; the global-ordinals idea applied to sort keys.
        Returns (device_ranks, host_sorted_values, n_have) or None."""
        key = (si, field, desc)
        cached = self._sort_rank_cache.get(key)
        if cached is not None:
            return cached
        with self._build_lock:
            cached = self._sort_rank_cache.get(key)
            if cached is not None:
                return cached
            seg = self.reader.segments[si]
            nf = seg.numerics.get(field)
            n = seg.num_docs
            if nf is None:
                ranks_host = np.arange(n, dtype=np.int32)
                sorted_vals = np.zeros(0)
                n_have = 0
            else:
                have = nf.exists
                vals = nf.values
                docs = np.arange(n)
                order_vals = -vals if desc else vals
                have_idx = docs[have]
                order = np.lexsort((have_idx, order_vals[have]))
                ranked = have_idx[order]
                missing = docs[~have]
                ranks_host = np.empty(n, np.int32)
                ranks_host[ranked] = np.arange(len(ranked), dtype=np.int32)
                ranks_host[missing] = np.arange(
                    len(ranked), n, dtype=np.int32
                )
                sorted_vals = np.sort(vals[have])
                n_have = int(len(ranked))
            arr = jax.device_put(ranks_host, self.device)
            self._charge("sort_ranks", int(ranks_host.nbytes), False)
            cached = (arr, sorted_vals, n_have)
            self._sort_rank_cache[key] = cached
            return cached

    def execute_sorted_device(
        self,
        query: Optional[Query],
        sort_specs,
        size: int = 10,
        search_after=None,
    ):
        """Device field-sorted collection for SINGLE numeric/date/bool
        sort keys (VERDICT r3 #6: sort keys live on device — collect
        the sorted top-k there and download k rows, not [n_docs]
        masks). Returns (TopDocs, svals) or None when the spec needs
        the oracle (multi-key, keyword keys, missing overrides,
        _score/_doc)."""
        if len(sort_specs) != 1:
            return None
        spec = sort_specs[0]
        field = spec["field"]
        if field in ("_score", "_doc"):
            return None
        mf = self.reader.mappings.get(field)
        if mf is None or not mf.is_numeric():
            return None
        if spec.get("missing") not in (None, "_last"):
            return None
        desc = spec.get("order", "asc") == "desc"
        after_v = None
        if search_after is not None:
            try:
                after_v = float(search_after[0])
            except (TypeError, ValueError):
                return None
        entries = []  # (rank_tuple, si, doc)
        total = 0
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if n == 0:
                continue
            got = self._sort_ranks(si, field, desc)
            ranks, sorted_vals, n_have = got
            if query is not None:
                mask, _ = self._exec(query, si)
            else:
                mask = jnp.ones(n, bool)
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & jnp.asarray(live)
            # hits.total reports the FULL query match count — the
            # search_after cursor narrows the page, never the total
            total += int(np.asarray(mask.sum()))
            if after_v is not None:
                # strictly-after in VALUE space (ties skipped, matching
                # the oracle): rank >= count of values <=/>= after
                if desc:
                    thr = n_have - int(
                        np.searchsorted(sorted_vals, after_v, side="left")
                    )
                else:
                    thr = int(
                        np.searchsorted(sorted_vals, after_v, side="right")
                    )
                mask = mask & (ranks >= jnp.int32(thr))
            kk = min(size, n)
            # smallest ranks win: top_k over negated ranks; masked docs
            # sink below every real rank
            neg = jnp.where(mask, -ranks, jnp.int32(-(2**31 - 1)))
            top_neg, top_d = jax.lax.top_k(neg, kk)
            host_neg = np.asarray(top_neg)
            host_d = np.asarray(top_d)
            for j in range(kk):
                if host_neg[j] == -(2**31 - 1):
                    continue
                entries.append((int(-host_neg[j]), si, int(host_d[j])))
        # cross-segment merge: segment-local ranks order identically to
        # values WITHIN a segment; across segments compare actual values
        nf_cols = [seg.numerics.get(field) for seg in self.reader.segments]

        def global_key(e):
            _, si, d = e
            nf = nf_cols[si]
            if nf is None or not nf.exists[d]:
                return (1, 0.0, si, d)  # missing last
            v = float(nf.values[d])
            return (0, -v if desc else v, si, d)

        entries.sort(key=global_key)
        page = entries[:size]
        hits = []
        svals = []
        for _, si, d in page:
            hits.append(
                Hit(
                    score=0.0,
                    segment=si,
                    local_doc=d,
                    doc_id=self.reader.segments[si].doc_ids[d],
                )
            )
            nf = nf_cols[si]
            if nf is None or not nf.exists[d]:
                svals.append([None])
            else:
                v = nf.values[d]
                svals.append(
                    [int(v)] if float(v).is_integer() else [float(v)]
                )
        return TopDocs(total=total, hits=hits, max_score=None), svals

    def _entry_docs_dev(self, si: int, field: str):
        """Device int32 doc index per multi-value ordinal entry (the
        CSR row-expansion), cached per (segment, field)."""
        key = (si, field)
        cached = self._entry_docs_dev_cache.get(key)
        if cached is not None:
            return cached
        with self._build_lock:
            cached = self._entry_docs_dev_cache.get(key)
            if cached is not None:
                return cached
            of = self.reader.segments[si].ordinals.get(field)
            if of is None:
                self._entry_docs_dev_cache[key] = None
                return None
            host = np.repeat(
                np.arange(self.reader.segments[si].num_docs, dtype=np.int32),
                np.diff(of.mv_offsets),
            )
            arr = jax.device_put(host, self.device)
            self._charge("doc_values", int(host.nbytes), False)
            self._entry_docs_dev_cache[key] = arr
            return arr

    def execute_with_terms_aggs(self, query, agg_nodes, k: int, tth):
        """Device query + keyword-terms aggregation in one pass
        (VERDICT r3 #6: terms bucketing = segment scatter-add on
        device, host reduce): per segment the downloads are k top-hit
        rows plus one compact count vector per agg — never the full
        [n_docs] masks. Returns (TopDocs, partials dict) or None when
        any agg needs the host collector."""
        from .aggs import _bkey, _int_param, _norm_order, _order_buckets

        for node in agg_nodes:
            if node.type != "terms" or node.subs:
                return None
            f = node.params.get("field")
            if f is None:
                return None
            mf = self.reader.mappings.get(f)
            if mf is None or mf.type != KEYWORD:
                return None
        # per-node global (term → count) accumulation across segments
        per_node_counts: List[Dict[str, int]] = [dict() for _ in agg_nodes]
        cands: List[Tuple[float, int, int]] = []
        total = 0
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if n == 0:
                continue
            if query is not None:
                mask, scores = self._exec(query, si)
            else:
                mask = jnp.ones(n, bool)
                scores = jnp.zeros(n, jnp.float32)
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & jnp.asarray(live)
            # device count vectors, one per agg node
            count_outs = []
            for node in agg_nodes:
                f = node.params["field"]
                of = seg.ordinals.get(f)
                entry_docs = self._entry_docs_dev(si, f)
                if of is None or entry_docs is None:
                    count_outs.append(None)
                    continue
                dof = self.device_segments[si].ordinals.get(f)
                mv_ords = dof[0] if dof is not None else jnp.asarray(of.mv_ords)
                # int32: segment doc counts are int32-bounded by design
                sel = mask[entry_docs].astype(jnp.int32)
                counts = jnp.zeros(len(of.ord_terms), jnp.int32).at[
                    mv_ords
                ].add(sel)
                count_outs.append(counts)
            s, d = scoring.topk_hits(scores, mask, min(k, n))
            host_s = np.asarray(s)
            host_d = np.asarray(d)
            total += int(np.asarray(mask.sum()))
            finite = np.isfinite(host_s)
            for sc, doc in zip(host_s[finite], host_d[finite]):
                cands.append((float(sc), si, int(doc)))
            for ni, counts in enumerate(count_outs):
                if counts is None:
                    continue
                host_counts = np.asarray(counts)
                of = seg.ordinals[agg_nodes[ni].params["field"]]
                agg = per_node_counts[ni]
                for o in np.nonzero(host_counts)[0]:
                    key = of.ord_terms[o]
                    agg[key] = agg.get(key, 0) + int(host_counts[o])
        # td (relevance order, exact totals)
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        page = cands[:k]
        hits = [
            Hit(
                score=s,
                segment=si,
                local_doc=d,
                doc_id=self.reader.segments[si].doc_ids[d],
            )
            for s, si, d in page
        ]
        td = TopDocs(
            total=total,
            hits=hits,
            max_score=hits[0].score if hits else None,
        )
        # partials in the host collector's wire shape (same reduce path)
        partials = {}
        for ni, node in enumerate(agg_nodes):
            counts = per_node_counts[ni]
            size = _int_param(node, "size", 10)
            shard_size = _int_param(
                node, "shard_size", max(int(size * 1.5) + 10, size)
            )
            order = _norm_order(node.params.get("order", {"_count": "desc"}))
            top = _order_buckets(counts, order)[:shard_size]
            shard_error = (
                top[-1][1] if len(counts) > shard_size and top else 0
            )
            partials[node.name] = {
                "t": "terms",
                "buckets": {
                    _bkey(key): {"key": key, "doc_count": cnt, "subs": {}}
                    for key, cnt in top
                },
                "sum_docs": sum(counts.values()),
                "size": size,
                "order": order,
                "shard_error": shard_error,
            }
        return td, partials

    def segment_topk(self, query: Query, si: int, k: int):
        """(scores[k], docs[k], total) for one parsed query on one
        segment — the batcher's per-segment fallback when a fused
        launch isn't available (small segment / slot overflow)."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        if n == 0:
            return (
                np.zeros(0, np.float32), np.zeros(0, np.int32), 0
            )
        mask, scores = self._exec(query, si)
        live = self.reader.live_docs[si]
        if live is not None:
            mask = mask & jnp.asarray(live)
        s, d = scoring.topk_hits(scores, mask, min(k, n))
        total = int(np.asarray(mask.sum()))
        return np.asarray(s), np.asarray(d), total

    def _exec_match(self, q: MatchQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type != TEXT:
            return self._exec_term(
                TermQuery(field=q.field, value=q.query, boost=q.boost), si
            )
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        terms = self.reader.analysis.get(analyzer_name).terms(q.query)
        if not terms:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        scores, cnt = self._field_terms_scored(si, q.field, terms, q.boost)
        if q.operator == "and":
            mask = cnt >= len(terms)
        else:
            msm = max(1, dsl.parse_minimum_should_match(q.minimum_should_match, len(terms)))
            mask = cnt >= msm
        return mask, jnp.where(mask, scores, 0.0)

    def _id_map(self, si: int) -> Dict[str, int]:
        """_id → local doc hash map per segment (built once; the analog
        of Lucene's per-segment terms dict on the _id field)."""
        m = self._id_maps.get(si)
        if m is None:
            m = {d: i for i, d in enumerate(self.reader.segments[si].doc_ids)}
            self._id_maps[si] = m
        return m

    def _exec_ids(self, q, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        idmap = self._id_map(si)
        mask = np.zeros(n, bool)
        for v in q.values:
            loc = idmap.get(str(v))
            if loc is not None:
                mask[loc] = True
        dmask = jnp.asarray(mask)
        return dmask, jnp.where(dmask, jnp.float32(q.boost), 0.0)

    def _exec_expanded(self, q, si: int) -> Tuple[jax.Array, jax.Array]:
        """prefix/wildcard/regexp/fuzzy: host term-dict expansion, then
        the expanded terms score as one device launch (constant score)."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        if isinstance(q, dsl.FuzzyQuery):
            terms = self._oracle._fuzzy_terms(q, seg)
        else:
            terms = self._oracle._expand_terms(q, seg)
        if not terms:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        _, cnt = self._field_terms_scored(si, q.field, terms, 1.0)
        mask = cnt >= 1
        return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)

    def _exec_knn_query(self, sec: KnnSection, si: int) -> Tuple[jax.Array, jax.Array]:
        """knn-as-a-query-node: per-segment num_candidates cut (mirrors
        NumpyExecutor._exec_knn), fully on device."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        dv = self.device_segments[si].vectors.get(sec.field)
        if dv is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        vectors, exists = dv
        vf = seg.vectors[sec.field]
        qv = jnp.asarray(np.asarray(sec.query_vector, np.float32))[None, :]
        scores = scoring.knn_scores(qv, vectors, vf.similarity)[0]
        mask = exists
        if sec.filter is not None:
            mask = mask & self.filter_mask(sec.filter, si)
        live = self.reader.live_docs[si]
        if live is not None:
            mask = mask & jnp.asarray(live)
        if sec.similarity is not None:
            mask = mask & (scores >= jnp.float32(sec.similarity))
        cand = min(sec.num_candidates, n)
        masked = jnp.where(mask, scores, -jnp.inf)
        kth = jax.lax.top_k(masked, cand)[0][-1]
        # when fewer than `cand` docs match, kth is -inf and cuts nothing
        # (same as the oracle's "only cut if cand < matches" branch)
        mask = mask & (masked >= kth)
        out = scores * jnp.float32(sec.boost)
        return mask, jnp.where(mask, out, 0.0)

    def _exec_phrase(
        self, q: MatchPhraseQuery, si: int
    ) -> Tuple[jax.Array, jax.Array]:
        """Phrase = device conjunction scoring + host position verify
        against the columnar position index (PositionsEnum analog). The
        candidate set after the conjunction is small, so one device→host
        sync of the mask mirrors ES's doc-at-a-time phrase scoring; BM25
        weights stay on device and _source is never re-analyzed."""
        from .executor import _phrase_match

        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None or mf.type != TEXT:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        qtoks = self.reader.analysis.get(analyzer_name).analyze(q.query)
        terms = [t.text for t in qtoks]
        if not terms:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        conj, scores = self._exec_match(
            MatchQuery(
                field=q.field,
                query=q.query,
                operator="and",
                analyzer=analyzer_name,
                boost=q.boost,
            ),
            si,
        )
        pf = seg.postings.get(q.field)
        if pf is None or not pf.has_positions:
            # legacy segment without positions → oracle fallback
            hm, hs = self._oracle._exec(q, seg)
            return jnp.asarray(hm), jnp.asarray(hs)
        qpos = [t.position for t in qtoks]
        rel = [p - qpos[0] for p in qpos]
        host_conj = np.asarray(conj)
        mask = np.zeros(n, bool)
        tids = [pf.term_id(t) for t in terms]
        for doc in np.nonzero(host_conj)[0]:
            pos_of = {}
            ok = True
            for t, tid in zip(terms, tids):
                if t in pos_of:
                    continue
                ps = pf.doc_positions(tid, int(doc)) if tid >= 0 else None
                if ps is None:
                    ok = False
                    break
                pos_of[t] = ps.tolist()
            mask[doc] = ok and _phrase_match(pos_of, terms, rel, q.slop)
        dmask = jnp.asarray(mask)
        return dmask, jnp.where(dmask, scores, 0.0)

    def _exec_term(self, q: TermQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field == "_id":
            mask = np.zeros(n, bool)
            loc = self._id_map(si).get(str(q.value))
            if loc is not None:
                mask[loc] = True
            dmask = jnp.asarray(mask)
            return dmask, jnp.where(dmask, jnp.float32(q.boost), 0.0)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type in (TEXT, KEYWORD):
            value = q.value
            if isinstance(value, bool):
                value = "true" if value else "false"
            scores, cnt = self._field_terms_scored(si, q.field, [str(value)], q.boost)
            mask = cnt >= 1
            return mask, jnp.where(mask, scores, 0.0)
        dn = self.device_segments[si].numerics.get(q.field)
        if dn is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        values, exists = dn
        target = _coerce_numeric(mf.type, q.value)
        mask = exists & (values == target)
        return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)

    def _exec_terms(self, q: TermsQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field != "_id" and mf is not None and mf.type in (TEXT, KEYWORD):
            # one combined kernel launch for all values (constant-score,
            # so only the match counts matter)
            vals = [
                ("true" if v else "false") if isinstance(v, bool) else str(v)
                for v in q.values
            ]
            _, cnt = self._field_terms_scored(si, q.field, vals, 1.0)
            mask = cnt >= 1
            return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)
        if q.field != "_id" and mf is not None:
            dn = self.device_segments[si].numerics.get(q.field)
            if dn is None:
                return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
            values, exists = dn
            targets = np.array(
                [_coerce_numeric(mf.type, v) for v in q.values], np.float64
            )
            mask = exists & jnp.isin(values, jnp.asarray(targets))
            return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)
        m = jnp.zeros(n, bool)
        for v in q.values:
            tm, _ = self._exec_term(TermQuery(field=q.field, value=v), si)
            m = m | tm
        return m, jnp.where(m, jnp.float32(q.boost), 0.0)

    def _exec_range(self, q: RangeQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        if mf.type in (TEXT, KEYWORD):
            # host bisect on the sorted ord dictionary picks [lo, hi);
            # the multi-value CSR membership test runs on device
            of = seg.ordinals.get(q.field)
            dof = self.device_segments[si].ordinals.get(q.field)
            if of is None or dof is None:
                return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
            import bisect

            terms = of.ord_terms
            lo, hi = 0, len(terms)
            if q.gte is not None:
                lo = bisect.bisect_left(terms, str(q.gte))
            if q.gt is not None:
                lo = max(lo, bisect.bisect_right(terms, str(q.gt)))
            if q.lte is not None:
                hi = min(hi, bisect.bisect_right(terms, str(q.lte)))
            if q.lt is not None:
                hi = min(hi, bisect.bisect_left(terms, str(q.lt)))
            mv_ords, mv_offsets = dof
            in_range = (mv_ords >= lo) & (mv_ords < hi)
            csum = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(in_range.astype(jnp.int32))]
            )
            mask = (csum[mv_offsets[1:]] - csum[mv_offsets[:-1]]) > 0
            return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)
        dn = self.device_segments[si].numerics.get(q.field)
        if dn is None:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        values, exists = dn
        mask = exists
        conv = (lambda v: parse_date_millis(v)) if mf.type == DATE else float
        if q.gte is not None:
            mask = mask & (values >= conv(q.gte))
        if q.gt is not None:
            mask = mask & (values > conv(q.gt))
        if q.lte is not None:
            mask = mask & (values <= conv(q.lte))
        if q.lt is not None:
            mask = mask & (values < conv(q.lt))
        return mask, jnp.where(mask, jnp.float32(q.boost), 0.0)

    def _exec_bool(self, q: BoolQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        seg = self.reader.segments[si]
        n = seg.num_docs
        mask = jnp.ones(n, bool)
        scores = jnp.zeros(n, jnp.float32)
        for c in q.must:
            m, s = self._exec(c, si)
            mask = mask & m
            scores = scores + s
        for c in q.filter:
            mask = mask & self.filter_mask(c, si)
        if q.should:
            sscores = jnp.zeros(n, jnp.float32)
            match_count = jnp.zeros(n, jnp.int32)
            for c in q.should:
                m, s = self._exec(c, si)
                sscores = sscores + jnp.where(m, s, 0.0)
                match_count = match_count + m.astype(jnp.int32)
            default_msm = 0 if (q.must or q.filter) else 1
            msm = (
                dsl.parse_minimum_should_match(q.minimum_should_match, len(q.should))
                if q.minimum_should_match is not None
                else default_msm
            )
            if msm > 0:
                mask = mask & (match_count >= msm)
            scores = scores + jnp.where(match_count > 0, sscores, 0.0)
        for c in q.must_not:
            m, _ = self._exec(c, si)
            mask = mask & ~m
        if q.boost != 1.0:
            scores = scores * jnp.float32(q.boost)
        return mask, jnp.where(mask, scores, 0.0)

    def _exec_multi_match(self, q: MultiMatchQuery, si: int) -> Tuple[jax.Array, jax.Array]:
        from .executor import expand_match_fields

        seg = self.reader.segments[si]
        n = seg.num_docs
        fields = expand_match_fields(self.reader.mappings, q.fields)
        if not fields:
            return jnp.zeros(n, bool), jnp.zeros(n, jnp.float32)
        per_field = [
            self._exec_phrase(
                MatchPhraseQuery(field=fn, query=q.query, boost=q.boost * fb), si
            )
            if q.type == "phrase"
            else self._exec_match(
                MatchQuery(field=fn, query=q.query, operator=q.operator, boost=q.boost * fb),
                si,
            )
            for fn, fb in fields
        ]
        masks = jnp.stack([m for m, _ in per_field])
        score_mat = jnp.stack([s for _, s in per_field])
        mask = masks.any(axis=0)
        if q.type == "best_fields":
            best = score_mat.max(axis=0)
            if q.tie_breaker:
                rest = score_mat.sum(axis=0) - best
                total = best + jnp.float32(q.tie_breaker) * rest
            else:
                total = best
        else:
            total = score_mat.sum(axis=0)
        return mask, jnp.where(mask, total, 0.0)

    # ---- IVF ANN tier (ops/ivf.py): per-segment cluster indexes ----

    def ann_index(self, si: int, field: str, spec):
        """Cached IvfSegmentIndex for one segment's vector column under
        one build shape (spec.nlist / spec.quantized), or None when the
        segment stays exact: below the small-segment floor, no vectors,
        or the HBM ledger can't fit the build (degrade, never trip).
        Built once per executor generation — a refresh/merge that
        touches the shard regenerates the executor, which re-clusters
        exactly like the agg tables re-profile."""
        seg = self.reader.segments[si]
        n = seg.num_docs
        key = (
            si, field, int(spec.nlist), bool(spec.quantized),
            int(spec.min_docs),
        )
        if key in self._ann_indexes:
            return self._ann_indexes[key]
        with self._build_lock:
            if key in self._ann_indexes:
                return self._ann_indexes[key]
            from ..common.memory import hbm_ledger
            from ..ops import ivf
            from . import ann as ann_mod

            idx = None
            vf = seg.vectors.get(field)
            if vf is not None and n >= max(spec.min_docs, 2):
                mat = (
                    vf.unit_vectors
                    if vf.similarity == "cosine"
                    and vf.unit_vectors is not None
                    else vf.vectors
                )
                nlist = spec.nlist or ivf.auto_nlist(n)
                nlist = max(1, min(nlist, n))
                est = ivf.IvfSegmentIndex.estimate_nbytes(
                    n, int(mat.shape[1]), nlist, spec.quantized,
                    itemsize=mat.dtype.itemsize,
                )
                if not hbm_ledger.would_fit(est):
                    hbm_ledger.note_degraded()
                else:
                    # deterministic seed: a pure function of the build
                    # shape, so re-runs (and the k-means determinism
                    # test) reproduce the same centroids bit-for-bit
                    seed = (si * 2654435761 + n * 97 + nlist) & 0x7FFFFFFF
                    idx = ivf.IvfSegmentIndex(
                        mat,
                        vf.similarity,
                        nlist,
                        seed,
                        quantized=spec.quantized,
                    )
                    self._charge("ann", idx.nbytes, False)
                    ann_mod.note_build(idx.build_ms)
            elif vf is not None and n:
                ann_mod.note("small_segment_exact")
            self._ann_indexes[key] = idx
            return idx

    # ---- learned-sparse impact columns (ops/impact.py scorers) ----

    def impact_scorer(self, si: int, field: str, quantized: bool):
        """Cached ops/impact.ImpactScorer over one segment's
        impact-ordered sparse postings column — the int8 qweights plane
        or the fp32 weights plane, chosen per SparseSpec — or None when
        the segment has no such column or the upload would not fit the
        HBM ledger (degrade to the host dense oracle, never trip).
        Charged to the `impacts` category and cached per executor
        generation, exactly like the agg tables and IVF indexes."""
        key = ("sparse", si, field, bool(quantized))
        if key in self._impact_scorers:
            return self._impact_scorers[key]
        with self._build_lock:
            if key in self._impact_scorers:
                return self._impact_scorers[key]
            from ..common.memory import hbm_ledger
            from ..ops import impact as impact_ops

            seg = self.reader.segments[si]
            sf = (getattr(seg, "sparse", None) or {}).get(field)
            sc = None
            if sf is not None and seg.num_docs and sf.n_tiles:
                vals = sf.qweights if quantized else sf.weights
                est = int(sf.doc_ids.nbytes + vals.nbytes)
                if not hbm_ledger.would_fit(est):
                    hbm_ledger.note_degraded()
                else:
                    sc = impact_ops.ImpactScorer(
                        sf.doc_ids,
                        vals,
                        seg.num_docs,
                        self.reader.live_docs[si],
                    )
                    self._charge("impacts", est, False)
                    from ..search import sparse as sparse_mod

                    # compression headline: the value plane actually
                    # uploaded vs the same plane at fp32 (doc-id planes
                    # are identical either way — see ledger_bytes)
                    sparse_mod.note("impact_bytes", int(vals.nbytes))
                    sparse_mod.note(
                        "impact_fp32_equivalent_bytes",
                        int(sf.weights.nbytes),
                    )
            self._impact_scorers[key] = sc
            return sc

    # ---- second-stage rerank column (flat rank_vectors gather arrays) ----

    def rerank_column(self, model):
        """Device-resident shard-level `rank_vectors` column for one
        RerankModel: per-doc CSR bounds over the GLOBAL doc encoding
        (segment-base + local doc — the same bases rescorer.build_plan
        uses) plus the flat token matrix, tail-padded with `tmax` zero
        rows so the maxsim gather never reads out of bounds. int8
        models store quantized rows + per-token scales
        (models/rerank.quantize_tokens). Charged to the `rerank`
        HbmLedger category; a build that would not fit degrades to
        SKIP (returns None — first-stage ranking survives). Cached per
        executor generation, exactly like the agg tables and IVF
        indexes."""
        key = ("rerank", model)
        if key in self._rerank_columns:
            return self._rerank_columns[key]
        with self._build_lock:
            if key in self._rerank_columns:
                return self._rerank_columns[key]
            from ..common.memory import hbm_ledger
            from ..models import rerank as rerank_model

            n_total = sum(s.num_docs for s in self.reader.segments)
            starts = np.zeros(max(n_total, 1), np.int32)
            counts = np.zeros(max(n_total, 1), np.int32)
            chunks: List[np.ndarray] = []
            tmax = 1
            base = 0
            flat = 0
            for seg in self.reader.segments:
                mvf = seg.multi_vectors.get(model.field)
                n = seg.num_docs
                if mvf is not None and len(mvf.tok_vectors):
                    offs = mvf.tok_offsets.astype(np.int64)
                    starts[base : base + n] = flat + offs[:-1]
                    counts[base : base + n] = np.diff(offs)
                    chunks.append(mvf.tok_vectors)
                    flat += int(offs[-1])
                    tmax = max(tmax, mvf.max_tokens)
                base += n
            dims = int(model.dims) or (
                int(chunks[0].shape[1]) if chunks else 1
            )
            toks_host = (
                np.concatenate(chunks, axis=0)
                if chunks
                else np.zeros((0, dims), np.float32)
            )
            pad = np.zeros((tmax, toks_host.shape[1]), toks_host.dtype)
            toks_host = np.concatenate([toks_host, pad], axis=0)
            est = (
                starts.nbytes
                + counts.nbytes
                + toks_host.nbytes
                + (
                    # int8 twin replaces the f32 rows but adds scales
                    toks_host.shape[0] * 4
                    if model.quantized
                    else 0
                )
            )
            if not hbm_ledger.would_fit(est):
                # degrade-to-skip: reranking is an optimization of the
                # ranking, never worth failing (or OOMing) the request
                hbm_ledger.note_degraded()
                rerank_model.note("skipped")
                self._rerank_columns[key] = None
                return None
            scales_dev = None
            if model.quantized:
                qv, scales = rerank_model.quantize_tokens(toks_host)
                toks_dev = jax.device_put(qv, self.device)
                scales_dev = jax.device_put(scales, self.device)
                nbytes = int(qv.nbytes + scales.nbytes)
            else:
                toks_dev = jax.device_put(
                    toks_host.astype(np.float32), self.device
                )
                nbytes = int(toks_host.nbytes)
            col = {
                "starts": jax.device_put(starts, self.device),
                "counts": jax.device_put(counts, self.device),
                "toks": toks_dev,
                "scales": scales_dev,
                "tmax": int(tmax),
                "dims": int(toks_host.shape[1]),
                "nbytes": int(nbytes + starts.nbytes + counts.nbytes),
            }
            self._charge("rerank", col["nbytes"], False)
            self._rerank_columns[key] = col
            return col

    # ---- knn (device matmul + global top-k cut) ----

    def _knn_topk_global(self, sec: KnnSection) -> List[Tuple[jax.Array, jax.Array]]:
        from ..common.faults import faults
        from . import ann as ann_mod

        spec = getattr(sec, "ann", None)
        per_seg = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            if seg.vectors.get(sec.field) is None:
                per_seg.append(
                    (jnp.zeros(n, bool), jnp.zeros(n, jnp.float32), None)
                )
                continue
            vf = seg.vectors[sec.field]
            q = jnp.asarray(np.asarray(sec.query_vector, np.float32))[None, :]
            cand_mask = jnp.asarray(vf.exists)
            if sec.filter is not None:
                cand_mask = cand_mask & self.filter_mask(sec.filter, si)
            live = self.reader.live_docs[si]
            if live is not None:
                cand_mask = cand_mask & jnp.asarray(live)
            k = min(sec.num_candidates, n)
            idx = None
            if spec is not None:
                # probe-path failures (the `ann.probe` fault site, HBM
                # degrade) fall back DETERMINISTICALLY to the exact
                # brute-force oracle below — slow/approximate is
                # acceptable, a failed request is not
                try:
                    faults.check("ann.probe", field=sec.field, segment=si)
                    idx = self.ann_index(si, sec.field, spec)
                except BaseException:
                    ann_mod.note("exact_fallbacks")
                    idx = None
            if idx is not None:
                from ..ops import ivf

                top_s, top_d = ivf.ann_topk_batch(
                    idx,
                    np.asarray(sec.query_vector, np.float32)[None, :],
                    np.ones(1, bool),
                    cand_mask,
                    spec.nprobe,
                    k,
                    quantized=spec.quantized,
                )
                ann_mod.note_search(spec.nprobe, idx.nlist)
                per_seg.append((cand_mask, top_s[0], top_d[0]))
                continue
            vectors, _exists = self.device_segments[si].vectors[sec.field]
            top_s, top_d = scoring.knn_topk(q, vectors, cand_mask, vf.similarity, k)
            per_seg.append((cand_mask, top_s[0], top_d[0]))
        # global k cut across segments
        entries = []
        for si, item in enumerate(per_seg):
            if len(item) == 3 and item[2] is not None:
                _, top_s, top_d = item
                s_host = np.asarray(top_s)
                d_host = np.asarray(top_d)
                for s, d in zip(s_host, d_host):
                    if np.isfinite(s) and (
                        sec.similarity is None or s >= sec.similarity
                    ):
                        entries.append((-float(s), si, int(d)))
        entries.sort()
        keep = entries[: sec.k]
        out = []
        for si, seg in enumerate(self.reader.segments):
            n = seg.num_docs
            mask = np.zeros(n, bool)
            scores = np.zeros(n, np.float32)
            for negs, ksi, d in keep:
                if ksi == si:
                    mask[d] = True
                    scores[d] = -negs * sec.boost
            out.append((jnp.asarray(mask), jnp.asarray(scores)))
        return out
