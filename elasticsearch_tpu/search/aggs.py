"""Aggregations: vectorized bucketing + metrics over columnar doc values.

Reference analog: org.elasticsearch.search.aggregations (SURVEY.md §2.1)
— AggregatorFactories parses the "aggs" tree, per-shard Aggregator
collectors run during the query phase, and InternalAggregation.reduce
merges shard partials at the coordinator. The TPU-native redesign drops
doc-at-a-time Collector callbacks entirely: a query produces a dense
per-segment match mask, every bucketing rule is a vectorized transform
of the doc-value columns (np.bincount / searchsorted — the MXU/VPU-ready
formulation), and sub-aggregations recurse with bucket-refined masks.

Collect/reduce split mirrors the reference: ``collect(shard) → partial``
(InternalAggregation), ``reduce([partials]) → response JSON``; the terms
agg keeps per-shard top ``shard_size`` buckets and reduces like
`InternalTerms.reduce` (sum_other_doc_count accounting included).

Supported (round 1): metrics avg/sum/min/max/value_count/stats/
cardinality/percentiles; buckets terms (keyword/numeric/boolean),
histogram, date_histogram (fixed + calendar), range, date_range,
filter, filters, missing — all with arbitrary sub-agg nesting.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..index.mapping import BOOLEAN, DATE, KEYWORD, TEXT, parse_date_millis
from . import dsl

METRIC_TYPES = {
    "avg",
    "sum",
    "min",
    "max",
    "value_count",
    "stats",
    "extended_stats",
    "cardinality",
    "percentiles",
    "median_absolute_deviation",
    "weighted_avg",
    "top_hits",
}

# pipeline aggs run at REDUCE time over sibling/parent buckets
# (PipelineAggregationBuilder): parent pipelines are declared inside a
# bucket agg and walk its ordered buckets; sibling pipelines sit next to
# a multi-bucket agg and summarize a buckets_path into one value
PARENT_PIPELINE_TYPES = {
    "derivative",
    "cumulative_sum",
    "serial_diff",
    "moving_fn",
    "bucket_script",
    "bucket_selector",
    "bucket_sort",
}
SIBLING_PIPELINE_TYPES = {
    "avg_bucket",
    "max_bucket",
    "min_bucket",
    "sum_bucket",
    "stats_bucket",
}
PIPELINE_TYPES = PARENT_PIPELINE_TYPES | SIBLING_PIPELINE_TYPES
BUCKET_TYPES = {
    "terms",
    "significant_terms",
    "histogram",
    "date_histogram",
    "range",
    "date_range",
    "filter",
    "filters",
    "missing",
    "composite",
    "global",
    "geo_distance",
    "sampler",
}


class AggParseError(ValueError):
    pass


@dataclass
class AggNode:
    name: str
    type: str
    params: dict
    subs: List["AggNode"] = dc_field(default_factory=list)


def parse_aggs(body: Any) -> List[AggNode]:
    """Parses the request's "aggs"/"aggregations" object into a tree."""
    if not isinstance(body, dict):
        raise AggParseError("aggs must be an object")
    nodes = []
    for name, spec in body.items():
        if not isinstance(spec, dict):
            raise AggParseError(f"agg [{name}] must be an object")
        subs: List[AggNode] = []
        agg_type = None
        params: dict = {}
        for key, value in spec.items():
            if key in ("aggs", "aggregations"):
                subs = parse_aggs(value)
            elif key == "meta":
                continue
            else:
                if agg_type is not None:
                    raise AggParseError(
                        f"agg [{name}] defines multiple types "
                        f"[{agg_type}, {key}]"
                    )
                agg_type = key
                params = value if isinstance(value, dict) else {}
        if agg_type is None:
            raise AggParseError(f"agg [{name}] has no type")
        if agg_type not in METRIC_TYPES | BUCKET_TYPES | PIPELINE_TYPES:
            raise AggParseError(f"unknown aggregation type [{agg_type}]")
        if subs and agg_type in METRIC_TYPES | PIPELINE_TYPES:
            raise AggParseError(
                f"metric agg [{name}] cannot have sub-aggregations"
            )
        if agg_type in PIPELINE_TYPES and agg_type != "bucket_sort" and (
            "buckets_path" not in params
        ):
            raise AggParseError(
                f"pipeline agg [{name}] requires [buckets_path]"
            )
        nodes.append(AggNode(name, agg_type, params, subs))
    return nodes


# ----------------------------------------------------------------------
# per-shard collection
# ----------------------------------------------------------------------


class AggCollector:
    """Runs an agg tree over one shard (all its segments) given the
    query's per-segment match masks. Uses the executor for filter
    sub-queries so filter/filters buckets see identical query semantics."""

    def __init__(self, executor):
        self.ex = executor  # NumpyExecutor (oracle semantics)
        self.reader = executor.reader
        self._entry_docs_cache: Dict[tuple, np.ndarray] = {}
        self._ord_index_cache: Dict[tuple, Dict[str, int]] = {}

    # ---- doc-value access helpers ----

    def _numeric_values(self, si: int, field: str):
        seg = self.reader.segments[si]
        nf = seg.numerics.get(field)
        if nf is None:
            n = seg.num_docs
            return np.zeros(n), np.zeros(n, bool)
        return nf.values, nf.exists

    def _keyword_ords(self, si: int, field: str):
        seg = self.reader.segments[si]
        of = seg.ordinals.get(field)
        if of is None:
            return None
        return of

    # ---- entry ----

    def collect(self, nodes: Sequence[AggNode], masks: List[np.ndarray]) -> dict:
        """masks: per-segment boolean match arrays (query+live filtered).
        Pipeline aggs collect nothing — they run at reduce time."""
        return {
            n.name: self._collect_node(n, masks)
            for n in nodes
            if n.type not in PIPELINE_TYPES
        }

    def _collect_node(self, node: AggNode, masks: List[np.ndarray]) -> dict:
        fn = getattr(self, f"_collect_{node.type}", None)
        if fn is None:
            raise AggParseError(f"unknown aggregation type [{node.type}]")
        return fn(node, masks)

    def _entry_docs(self, si: int, of) -> np.ndarray:
        """doc index per multi-value ordinal entry, cached per segment."""
        key = (si, id(of))
        cached = self._entry_docs_cache.get(key)
        if cached is None:
            n = self.reader.segments[si].num_docs
            cached = np.repeat(np.arange(n), np.diff(of.mv_offsets))
            self._entry_docs_cache[key] = cached
        return cached

    # ---- metrics ----

    def _metric_values(
        self, node: AggNode, masks, numeric_only: bool = True
    ) -> np.ndarray:
        f = node.params.get("field")
        if f is None:
            if "script" in node.params:
                raise AggParseError("scripts not supported in this build")
            raise AggParseError(f"agg [{node.name}] requires a field")
        mf = self.reader.mappings.get(f)
        vals = []
        for si, mask in enumerate(masks):
            if mf is not None and mf.type in (KEYWORD, TEXT):
                if numeric_only:
                    raise AggParseError(
                        f"Field [{f}] of type [{mf.type}] is not supported "
                        f"for aggregation [{node.type}]"
                    )
                of = self._keyword_ords(si, f)
                if of is None:
                    continue
                sel = mask[self._entry_docs(si, of)]
                vals.append(of.mv_ords[sel].astype(np.float64))  # count only
            else:
                v, e = self._numeric_values(si, f)
                m = mask & e
                vals.append(v[m])
        return np.concatenate(vals) if vals else np.zeros(0)

    def _collect_avg(self, node, masks):
        v = self._metric_values(node, masks)
        return {"t": "avg", "sum": float(v.sum()), "count": int(len(v))}

    def _collect_sum(self, node, masks):
        v = self._metric_values(node, masks)
        return {"t": "sum", "sum": float(v.sum())}

    def _collect_min(self, node, masks):
        v = self._metric_values(node, masks)
        return {"t": "min", "min": float(v.min()) if len(v) else None}

    def _collect_max(self, node, masks):
        v = self._metric_values(node, masks)
        return {"t": "max", "max": float(v.max()) if len(v) else None}

    def _collect_value_count(self, node, masks):
        v = self._metric_values(node, masks, numeric_only=False)
        return {"t": "value_count", "count": int(len(v))}

    def _collect_stats(self, node, masks):
        v = self._metric_values(node, masks)
        return {
            "t": "stats",
            "count": int(len(v)),
            "sum": float(v.sum()),
            "min": float(v.min()) if len(v) else None,
            "max": float(v.max()) if len(v) else None,
        }

    def _collect_cardinality(self, node, masks):
        """Exact distinct count; partials are numpy arrays so
        cross-segment/shard union needs no boxing. Keyword terms hash
        with a 64-bit murmur3 combination (stable across processes —
        Python hash() is PYTHONHASHSEED-randomized — and wide enough
        that birthday collisions stay negligible, unlike a single
        32-bit hash); term hashes and numeric bit patterns live in
        separate partial keys so they can never collide when reduced
        together. Round 3: HLL++ sketch for sublinear partials."""
        from ..utils.murmur3 import murmurhash3_x86_32

        def _hash64(term: str) -> int:
            b = term.encode("utf-8")
            # mask both halves unsigned BEFORE combining: murmur3_x86_32
            # returns Java-signed ints, and a negative low word would
            # sign-extend over (and erase) the high word
            hi = murmurhash3_x86_32(b, seed=0) & 0xFFFFFFFF
            lo = murmurhash3_x86_32(b, seed=0x9747B28C) & 0xFFFFFFFF
            v = (hi << 32) | lo
            return v - (1 << 64) if v >= (1 << 63) else v  # wrap to int64

        f = node.params.get("field")
        if f is None:
            raise AggParseError(f"agg [{node.name}] requires a field")
        mf = self.reader.mappings.get(f)
        term_parts = []
        num_parts = []
        for si, mask in enumerate(masks):
            if mf is not None and mf.type in (KEYWORD, TEXT):
                of = self._keyword_ords(si, f)
                if of is None:
                    continue
                sel_ords = np.unique(of.mv_ords[mask[self._entry_docs(si, of)]])
                # hash terms so segments with different ord spaces merge
                term_parts.append(
                    np.fromiter(
                        (_hash64(of.ord_terms[o]) for o in sel_ords),
                        np.int64,
                        count=len(sel_ords),
                    )
                )
            else:
                v, e = self._numeric_values(si, f)
                num_parts.append(np.unique(v[mask & e]).view(np.int64))
        return {
            "t": "cardinality",
            # JSON-serializable: partials ride the transport cross-node
            "terms": (
                np.unique(np.concatenate(term_parts)).tolist()
                if term_parts
                else []
            ),
            "nums": (
                np.unique(np.concatenate(num_parts)).tolist()
                if num_parts
                else []
            ),
        }

    def _collect_percentiles(self, node, masks):
        # exact percentiles: the partial keeps matched values as one numpy
        # array (no boxing); t-digest sketching is the round-2 upgrade
        v = self._metric_values(node, masks)
        return {
            "t": "percentiles",
            # JSON-serializable: partials ride the transport cross-node
            "values": v.tolist(),
            "percents": node.params.get(
                "percents", [1, 5, 25, 50, 75, 95, 99]
            ),
        }

    def _collect_extended_stats(self, node, masks):
        v = self._metric_values(node, masks)
        return {
            "t": "extended_stats",
            "count": int(len(v)),
            "sum": float(v.sum()),
            "sum_sq": float((v * v).sum()),
            "min": float(v.min()) if len(v) else None,
            "max": float(v.max()) if len(v) else None,
            "sigma": float(node.params.get("sigma", 2.0)),
        }

    def _collect_median_absolute_deviation(self, node, masks):
        # exact MAD from retained values (the reference approximates
        # with a t-digest; exactness beats sketching at this scale).
        # Partials must be JSON-serializable: they ride the transport
        # to remote coordinators.
        v = self._metric_values(node, masks)
        return {"t": "median_absolute_deviation", "values": v.tolist()}

    def _collect_weighted_avg(self, node, masks):
        vspec = node.params.get("value") or {}
        wspec = node.params.get("weight") or {}
        vf, wf = vspec.get("field"), wspec.get("field")
        if vf is None or wf is None:
            raise AggParseError(
                "[weighted_avg] requires [value.field] and [weight.field]"
            )
        vsum = 0.0
        wsum = 0.0
        for si, mask in enumerate(masks):
            v, ve = self._numeric_values(si, vf)
            w, we = self._numeric_values(si, wf)
            m = mask & ve & we
            vsum += float((v[m] * w[m]).sum())
            wsum += float(w[m].sum())
        return {"t": "weighted_avg", "vsum": vsum, "wsum": wsum}

    def _collect_top_hits(self, node, masks):
        """Per-bucket hit materialization (TopHitsAggregator). Sort:
        numeric/date doc-value fields and `_doc`; the default is `_doc`
        (query scores are not available in the agg phase — documented
        deviation from the reference's score default)."""
        size = _int_param(node, "size", 3)
        sort_spec = node.params.get("sort") or ["_doc"]
        if isinstance(sort_spec, (str, dict)):
            sort_spec = [sort_spec]
        specs = []
        for s in sort_spec:
            if isinstance(s, str):
                specs.append((s, "asc"))
            elif isinstance(s, dict) and len(s) == 1:
                fld, spec = next(iter(s.items()))
                order = (
                    spec.get("order", "asc")
                    if isinstance(spec, dict)
                    else str(spec)
                )
                specs.append((fld, order))
            else:
                raise AggParseError("[top_hits] malformed sort")
        source_spec = node.params.get("_source", True)
        entries = []
        total = 0
        for si, mask in enumerate(masks):
            seg = self.reader.segments[si]
            idx = np.nonzero(mask)[0]
            total += len(idx)
            for d in idx:
                keys = []
                raws = []
                for fld, order in specs:
                    if fld == "_doc":
                        v = float(si * 10**9 + int(d))
                        have = True
                    else:
                        col, e = self._numeric_values(si, fld)
                        have = bool(e[d])
                        v = float(col[d]) if have else None
                    raws.append(v)
                    if not have:
                        # missing sorts LAST in either direction
                        keys.append(float("inf"))
                    else:
                        keys.append(-v if order == "desc" else v)
                entries.append((tuple(keys), raws, si, int(d)))
        entries.sort(key=lambda e: e[0])
        from .executor import filter_source

        hits = []
        for keys, raws, si, d in entries[:size]:
            seg = self.reader.segments[si]
            src = seg.sources[d]
            # _k: internal order keys for the cross-shard merge (stripped
            # at reduce); sort: the raw public values
            h = {"_id": seg.doc_ids[d], "_score": None, "sort": raws,
                 "_k": list(keys)}
            filtered = filter_source(src, source_spec)
            if filtered is not None and source_spec is not False:
                h["_source"] = filtered
            hits.append(h)
        return {"t": "top_hits", "hits": hits, "total": total, "size": size}

    # ---- bucket helpers ----

    def _bucket_result(self, doc_count: int, sub_partial: dict) -> dict:
        return {"doc_count": doc_count, "subs": sub_partial}

    def _sub_collect(self, node: AggNode, bucket_masks: List[np.ndarray]) -> dict:
        if not node.subs:
            return {}
        return self.collect(node.subs, bucket_masks)

    # ---- terms ----

    def _collect_terms(self, node, masks):
        f = node.params.get("field")
        if f is None:
            raise AggParseError("terms agg requires a field")
        size = _int_param(node, "size", 10)
        shard_size = _int_param(node, "shard_size", max(int(size * 1.5) + 10, size))
        mf = self.reader.mappings.get(f)
        if mf is not None and mf.type == TEXT:
            raise AggParseError(
                f"Text fields are not optimised for aggregations [{f}]; "
                "use a keyword sub-field"
            )
        counts: Dict[Any, int] = {}
        is_keyword = mf is not None and mf.type == KEYWORD
        for si, mask in enumerate(masks):
            if is_keyword:
                of = self._keyword_ords(si, f)
                if of is None:
                    continue
                sel = of.mv_ords[mask[self._entry_docs(si, of)]]
                bc = np.bincount(sel, minlength=len(of.ord_terms))
                for o in np.nonzero(bc)[0]:
                    key = of.ord_terms[o]
                    counts[key] = counts.get(key, 0) + int(bc[o])
            else:
                v, e = self._numeric_values(si, f)
                m = mask & e
                u, c = np.unique(v[m], return_counts=True)
                for key, cnt in zip(u.tolist(), c.tolist()):
                    if mf is not None and mf.type == BOOLEAN:
                        key = bool(key)
                    elif mf is not None and mf.type in ("integer", "long", "short", "byte", DATE):
                        key = int(key)
                    counts[key] = counts.get(key, 0) + cnt
        total = sum(counts.values())
        order = _norm_order(node.params.get("order", {"_count": "desc"}))
        top = _order_buckets(counts, order)[:shard_size]
        # this shard's contribution to doc_count_error_upper_bound: the
        # last kept bucket's count if we truncated, else 0 (InternalTerms)
        shard_error = top[-1][1] if len(counts) > shard_size and top else 0
        buckets = {}
        for key, cnt in top:
            subs = {}
            if node.subs:  # bucket masks only needed for sub-aggs
                bucket_masks = [
                    self._term_bucket_mask(si, f, key, mask, is_keyword)
                    for si, mask in enumerate(masks)
                ]
                subs = self._sub_collect(node, bucket_masks)
            buckets[_bkey(key)] = {"key": key, "doc_count": cnt, "subs": subs}
        return {
            "t": "terms",
            "buckets": buckets,
            "sum_docs": total,
            "size": size,
            "order": order,
            "shard_error": shard_error,
        }

    def _term_bucket_mask(self, si, f, key, mask, is_keyword) -> np.ndarray:
        seg = self.reader.segments[si]
        n = seg.num_docs
        if is_keyword:
            of = self._keyword_ords(si, f)
            if of is None:
                return np.zeros(n, bool)
            ord_index = self._ord_index_cache.get((si, f))
            if ord_index is None:
                ord_index = {t: i for i, t in enumerate(of.ord_terms)}
                self._ord_index_cache[(si, f)] = ord_index
            o = ord_index.get(key)
            if o is None:
                return np.zeros(n, bool)
            entry_docs = self._entry_docs(si, of)
            has = np.zeros(n, bool)
            has[entry_docs[of.mv_ords == o]] = True
            return mask & has
        v, e = self._numeric_values(si, f)
        return mask & e & (v == float(key))

    def _collect_global(self, node, masks):
        """global bucket: the whole shard's LIVE docs regardless of the
        query (GlobalAggregator)."""
        full = []
        for si, seg in enumerate(self.reader.segments):
            live = self.reader.live_docs[si]
            full.append(
                np.ones(seg.num_docs, bool) if live is None else live.copy()
            )
        return {
            "t": "global",
            "doc_count": int(sum(m.sum() for m in full)),
            "subs": self._sub_collect(node, full),
        }

    def _collect_significant_terms(self, node, masks):
        """Foreground (query) vs background (whole shard) term counts;
        scoring happens at reduce with the summed stats
        (SignificantTermsAggregatorFactory, JLH heuristic). Background
        counts are mask-independent and cached per (segment, field) so
        nesting under a 1000-bucket terms agg doesn't rescan the shard
        1000 times."""
        f = _req(node, "field")
        mf = self.reader.mappings.get(f)
        if mf is None or mf.type != KEYWORD:
            raise AggParseError(
                f"[significant_terms] requires a keyword field, got [{f}]"
            )
        if not hasattr(self, "_sig_bg_cache"):
            self._sig_bg_cache: Dict[tuple, tuple] = {}
        fg: Dict[str, int] = {}
        bg: Dict[str, int] = {}
        fg_total = 0
        bg_total = 0
        for si, mask in enumerate(masks):
            of = self._keyword_ords(si, f)
            seg = self.reader.segments[si]
            live = self.reader.live_docs[si]
            full = np.ones(seg.num_docs, bool) if live is None else live
            fg_total += int(mask.sum())
            if of is None:
                bg_total += int(full.sum())
                continue
            entry_docs = self._entry_docs(si, of)
            cached = self._sig_bg_cache.get((si, f))
            if cached is None:
                sel = of.mv_ords[full[entry_docs]]
                bc = np.bincount(sel, minlength=len(of.ord_terms))
                bg_counts = {
                    of.ord_terms[o]: int(bc[o]) for o in np.nonzero(bc)[0]
                }
                cached = (bg_counts, int(full.sum()))
                self._sig_bg_cache[(si, f)] = cached
            for key, cnt in cached[0].items():
                bg[key] = bg.get(key, 0) + cnt
            bg_total += cached[1]
            sel = of.mv_ords[mask[entry_docs]]
            bc = np.bincount(sel, minlength=len(of.ord_terms))
            for o in np.nonzero(bc)[0]:
                key = of.ord_terms[o]
                fg[key] = fg.get(key, 0) + int(bc[o])
        return {
            "t": "significant_terms",
            "fg": fg,
            "bg": bg,
            "fg_total": fg_total,
            "bg_total": bg_total,
            "size": _int_param(node, "size", 10),
        }

    def _collect_composite(self, node, masks):
        """Composite: multi-source bucket tuples, paginated at reduce
        via after_key (CompositeAggregator). Sources: terms, histogram,
        date_histogram (fixed_interval). Multi-valued keywords use the
        first value."""
        sources = node.params.get("sources")
        if not isinstance(sources, list) or not sources:
            raise AggParseError("[composite] requires [sources]")
        specs = []
        for s in sources:
            if not isinstance(s, dict) or len(s) != 1:
                raise AggParseError("[composite] malformed source")
            sname, body = next(iter(s.items()))
            if not isinstance(body, dict) or len(body) != 1:
                raise AggParseError("[composite] malformed source")
            stype, params = next(iter(body.items()))
            if stype not in ("terms", "histogram", "date_histogram"):
                raise AggParseError(
                    f"[composite] unsupported source type [{stype}]"
                )
            specs.append((sname, stype, params))
        buckets: Dict[tuple, dict] = {}
        for si, mask in enumerate(masks):
            n = self.reader.segments[si].num_docs
            cols = []
            ok = mask.copy()
            for sname, stype, params in specs:
                f = params.get("field")
                if f is None:
                    raise AggParseError("[composite] source requires [field]")
                mf = self.reader.mappings.get(f)
                if stype == "terms" and mf is not None and mf.type == KEYWORD:
                    of = self._keyword_ords(si, f)
                    if of is None:
                        col = np.full(n, None, object)
                        have = np.zeros(n, bool)
                    else:
                        col = np.full(n, None, object)
                        have = of.ords >= 0
                        idx = np.nonzero(have)[0]
                        col[idx] = [of.ord_terms[o] for o in of.ords[idx]]
                else:
                    v, e = self._numeric_values(si, f)
                    have = e
                    if stype == "histogram":
                        interval = _float_param(
                            _req_param(params, "interval", node), node,
                            "interval",
                        )
                        col = np.floor(v / interval) * interval
                    elif stype == "date_histogram":
                        iv = params.get("fixed_interval") or params.get(
                            "calendar_interval"
                        )
                        ms = _parse_dh_interval({"fixed_interval": iv})[0] if iv else None
                        if ms is None:
                            raise AggParseError(
                                "[composite] date_histogram needs "
                                "fixed_interval"
                            )
                        col = np.floor(v / ms) * ms
                    else:
                        if mf is not None and mf.type in (
                            "integer", "long", "short", "byte",
                        ):
                            col = v.astype(np.int64)
                        else:
                            col = v
                ok &= have
                cols.append(col)
            idx = np.nonzero(ok)[0]
            track_docs = bool(node.subs)  # per-bucket docs only feed subs
            for d in idx:
                key = tuple(
                    c[d] if isinstance(c[d], str) else
                    (int(c[d]) if float(c[d]).is_integer() else float(c[d]))
                    for c in cols
                )
                cur = buckets.get(key)
                if cur is None:
                    buckets[key] = {
                        "count": 1,
                        "docs": [(si, int(d))] if track_docs else [],
                    }
                else:
                    cur["count"] += 1
                    if track_docs:
                        cur["docs"].append((si, int(d)))
        # sub-agg collection per composite bucket
        out_buckets = {}
        for key, info in buckets.items():
            subs = {}
            if node.subs:
                bucket_masks = [
                    np.zeros(self.reader.segments[si].num_docs, bool)
                    for si in range(len(masks))
                ]
                for si, d in info["docs"]:
                    bucket_masks[si][d] = True
                subs = self._sub_collect(node, bucket_masks)
            out_buckets[json.dumps(list(key))] = {
                "key_values": list(key),
                "doc_count": info["count"],
                "subs": subs,
            }
        return {
            "t": "composite",
            "buckets": out_buckets,
            "source_names": [s[0] for s in specs],
            "size": _int_param(node, "size", 10),
            "after": node.params.get("after"),
        }

    def _collect_geo_distance(self, node, masks):
        """geo_distance rings around an origin (GeoDistanceAggregator):
        haversine over the geo_point's lat/lon doc-value columns. The
        bucket/key/keyed machinery is the range agg's (same partial
        shape, same reduce branch, same '50.0-100.0' key format)."""
        from ..search.dsl import _geo_point, parse_distance_meters
        from ..search.executor import _haversine_m

        f = _req(node, "field")
        origin_lat, origin_lon = _geo_point(_req(node, "origin"))
        unit = str(node.params.get("unit", "m"))
        unit_m = parse_distance_meters(f"1{unit}")
        ranges = node.params.get("ranges")
        if not isinstance(ranges, list) or not ranges:
            raise AggParseError("[geo_distance] requires [ranges]")
        # per-segment distances (and field presence), computed once
        seg_dist = []
        seg_base = []
        for si, mask in enumerate(masks):
            lat, le = self._numeric_values(si, f"{f}.lat")
            lon, loe = self._numeric_values(si, f"{f}.lon")
            seg_dist.append(
                _haversine_m(origin_lat, origin_lon, lat, lon) / unit_m
            )
            seg_base.append(mask & le & loe)
        out = []
        for r in ranges:
            frm = float(r["from"]) if r.get("from") is not None else None
            to = float(r["to"]) if r.get("to") is not None else None
            bucket_masks = []
            cnt = 0
            for si in range(len(masks)):
                m = seg_base[si]
                if frm is not None:
                    m = m & (seg_dist[si] >= frm)
                if to is not None:
                    m = m & (seg_dist[si] < to)
                bucket_masks.append(m)
                cnt += int(m.sum())
            key = r.get("key")
            if key is None:
                fs = _range_key_part(r.get("from"), False, frm)
                ts = _range_key_part(r.get("to"), False, to)
                key = f"{fs}-{ts}"
            entry = {
                "key": key,
                "doc_count": cnt,
                "subs": self._sub_collect(node, bucket_masks),
            }
            if frm is not None:
                entry["from"] = frm
            if to is not None:
                entry["to"] = to
            out.append(entry)
        return {
            "t": "geo_distance",
            "buckets": out,
            "keyed": node.params.get("keyed", False),
        }

    def _collect_sampler(self, node, masks):
        """sampler: sub-aggs see only the first shard_size matching docs
        per shard (SamplerAggregator's best-docs simplification: our
        masks carry no scores, so document order stands in for rank)."""
        shard_size = _int_param(node, "shard_size", 100)
        remaining = shard_size
        sampled = []
        for mask in masks:
            m = np.zeros_like(mask)
            if remaining > 0:
                idx = np.nonzero(mask)[0][:remaining]
                m[idx] = True
                remaining -= len(idx)
            sampled.append(m)
        return {
            "t": "sampler",
            "doc_count": int(sum(int(m.sum()) for m in sampled)),
            "subs": self._sub_collect(node, sampled),
        }

    # ---- histogram family ----

    def _collect_histogram(self, node, masks):
        f = _req(node, "field")
        interval = _float_param(_req(node, "interval"), node, "interval")
        if interval <= 0:
            raise AggParseError("interval must be > 0")
        offset = _float_param(node.params.get("offset", 0), node, "offset")
        counts: Dict[float, int] = {}
        per_seg_keys = []
        for si, mask in enumerate(masks):
            v, e = self._numeric_values(si, f)
            keys = np.floor((v - offset) / interval) * interval + offset
            per_seg_keys.append(keys)
            m = mask & e
            u, c = np.unique(keys[m], return_counts=True)
            for k, cnt in zip(u.tolist(), c.tolist()):
                counts[k] = counts.get(k, 0) + cnt
        buckets = {}
        for k in sorted(counts):
            subs = {}
            if node.subs:
                bucket_masks = []
                for si, mask in enumerate(masks):
                    _, e = self._numeric_values(si, f)
                    bucket_masks.append(mask & e & (per_seg_keys[si] == k))
                subs = self._sub_collect(node, bucket_masks)
            buckets[k] = {"key": k, "doc_count": counts[k], "subs": subs}
        return {"t": "histogram", "buckets": buckets}

    def _collect_date_histogram(self, node, masks):
        f = _req(node, "field")
        interval_ms, calendar_unit = _parse_dh_interval(node.params)
        counts: Dict[int, int] = {}
        per_seg_keys = []
        for si, mask in enumerate(masks):
            v, e = self._numeric_values(si, f)
            keys = _date_bucket_keys(v, calendar_unit, interval_ms)
            per_seg_keys.append(keys)
            m = mask & e
            u, c = np.unique(keys[m], return_counts=True)
            for k, cnt in zip(u.tolist(), c.tolist()):
                counts[int(k)] = counts.get(int(k), 0) + cnt
        buckets = {}
        for k in sorted(counts):
            subs = {}
            if node.subs:
                bucket_masks = []
                for si, mask in enumerate(masks):
                    _, e = self._numeric_values(si, f)
                    bucket_masks.append(mask & e & (per_seg_keys[si] == k))
                subs = self._sub_collect(node, bucket_masks)
            buckets[k] = {"key": k, "doc_count": counts[k], "subs": subs}
        return {"t": "date_histogram", "buckets": buckets}

    # ---- range family ----

    def _collect_range(self, node, masks, is_date=False):
        f = _req(node, "field")
        ranges = node.params.get("ranges", [])
        out = []
        for r in ranges:
            frm = r.get("from")
            to = r.get("to")
            if is_date:
                frm = parse_date_millis(frm) if frm is not None else None
                to = parse_date_millis(to) if to is not None else None
            else:
                frm = float(frm) if frm is not None else None
                to = float(to) if to is not None else None
            bucket_masks = []
            cnt = 0
            for si, mask in enumerate(masks):
                v, e = self._numeric_values(si, f)
                m = mask & e
                if frm is not None:
                    m = m & (v >= frm)
                if to is not None:
                    m = m & (v < to)
                bucket_masks.append(m)
                cnt += int(m.sum())
            key = r.get("key")
            if key is None:
                fs = _range_key_part(r.get("from"), is_date, frm)
                ts = _range_key_part(r.get("to"), is_date, to)
                key = f"{fs}-{ts}"
            entry = {
                "key": key,
                "doc_count": cnt,
                "subs": self._sub_collect(node, bucket_masks),
            }
            if frm is not None:
                entry["from"] = frm
            if to is not None:
                entry["to"] = to
            out.append(entry)
        return {"t": "range", "buckets": out, "keyed": node.params.get("keyed", False)}

    def _collect_date_range(self, node, masks):
        r = self._collect_range(node, masks, is_date=True)
        r["t"] = "date_range"
        return r

    # ---- filter / filters / missing ----

    def _query_masks(self, query_json: dict, masks) -> List[np.ndarray]:
        # agg filter contexts ride the node-level bitset cache (the
        # reference caches agg `filter`/`filters` bitsets the same way)
        q = dsl.parse_query(query_json)
        out = []
        for si, mask in enumerate(masks):
            seg = self.reader.segments[si]
            if hasattr(self.ex, "filter_mask"):
                m = self.ex.filter_mask(q, seg)
            else:
                m, _ = self.ex._exec(q, seg)
            out.append(mask & m)
        return out

    def _collect_filter(self, node, masks):
        # the filter *is* the params object itself ({"term": ...})
        fmasks = self._query_masks(node.params, masks)
        return {
            "t": "filter",
            "doc_count": int(sum(m.sum() for m in fmasks)),
            "subs": self._sub_collect(node, fmasks),
        }

    def _collect_filters(self, node, masks):
        specs = node.params.get("filters", {})
        buckets = {}
        if isinstance(specs, dict):
            items = specs.items()
            keyed = True
        else:
            items = ((str(i), s) for i, s in enumerate(specs))
            keyed = False
        for key, qjson in items:
            fmasks = self._query_masks(qjson, masks)
            buckets[key] = {
                "key": key,
                "doc_count": int(sum(m.sum() for m in fmasks)),
                "subs": self._sub_collect(node, fmasks),
            }
        return {"t": "filters", "buckets": buckets, "keyed": keyed}

    def _collect_missing(self, node, masks):
        f = node.params.get("field")
        mf = self.reader.mappings.get(f) if f else None
        mmasks = []
        for si, mask in enumerate(masks):
            seg = self.reader.segments[si]
            n = seg.num_docs
            if mf is not None and mf.type in (KEYWORD, TEXT):
                of = self._keyword_ords(si, f)
                if of is None:
                    have = np.zeros(n, bool)
                else:
                    have = of.ords >= 0
            else:
                _, have = self._numeric_values(si, f)
            mmasks.append(mask & ~have)
        return {
            "t": "missing",
            "doc_count": int(sum(m.sum() for m in mmasks)),
            "subs": self._sub_collect(node, mmasks),
        }


# ----------------------------------------------------------------------
# coordinator reduce (InternalAggregation.reduce analog)
# ----------------------------------------------------------------------


def reduce_aggs(
    nodes: Sequence[AggNode],
    shard_partials: List[dict],
    in_bucket: bool = False,
) -> dict:
    """Coordinator reduce. Pipeline aggs run here: sibling pipelines
    after their targets; parent pipelines are applied by the PARENT
    bucket agg over its reduced bucket list (in_bucket marks sub-level
    reduces, where parent-pipeline nodes are handled by the caller via
    _apply_parent_pipelines)."""
    out = {}
    for node in nodes:
        if node.type in PIPELINE_TYPES:
            continue
        parts = [p[node.name] for p in shard_partials if node.name in p]
        reduced = _reduce_node(node, parts)
        out[node.name] = _apply_parent_pipelines(node, reduced)
    for node in nodes:
        if node.type in SIBLING_PIPELINE_TYPES:
            out[node.name] = _sibling_pipeline(node, out)
        elif node.type in PARENT_PIPELINE_TYPES and not in_bucket:
            raise AggParseError(
                f"pipeline agg [{node.name}] of type [{node.type}] must be "
                "declared inside a multi-bucket aggregation"
            )
    return out


def _reduce_node(node: AggNode, parts: List[dict]) -> dict:
    t = node.type
    if t == "avg":
        s = sum(p["sum"] for p in parts)
        c = sum(p["count"] for p in parts)
        return {"value": (s / c) if c else None}
    if t == "sum":
        return {"value": sum(p["sum"] for p in parts)}
    if t == "min":
        vals = [p["min"] for p in parts if p["min"] is not None]
        return {"value": min(vals) if vals else None}
    if t == "max":
        vals = [p["max"] for p in parts if p["max"] is not None]
        return {"value": max(vals) if vals else None}
    if t == "value_count":
        return {"value": sum(p["count"] for p in parts)}
    if t == "stats":
        c = sum(p["count"] for p in parts)
        s = sum(p["sum"] for p in parts)
        mins = [p["min"] for p in parts if p["min"] is not None]
        maxs = [p["max"] for p in parts if p["max"] is not None]
        return {
            "count": c,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "avg": (s / c) if c else None,
            "sum": s,
        }
    if t == "extended_stats":
        c = sum(p["count"] for p in parts)
        s = sum(p["sum"] for p in parts)
        sq = sum(p["sum_sq"] for p in parts)
        mins = [p["min"] for p in parts if p["min"] is not None]
        maxs = [p["max"] for p in parts if p["max"] is not None]
        sigma = parts[0]["sigma"] if parts else 2.0
        avg = (s / c) if c else None
        variance = max(sq / c - avg * avg, 0.0) if c else None
        std = float(np.sqrt(variance)) if variance is not None else None
        out = {
            "count": c,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "avg": avg,
            "sum": s,
            "sum_of_squares": sq if c else None,
            "variance": variance,
            "std_deviation": std,
        }
        if std is not None:
            out["std_deviation_bounds"] = {
                "upper": avg + sigma * std,
                "lower": avg - sigma * std,
            }
        return out
    if t == "median_absolute_deviation":
        vals = (
            np.concatenate([np.asarray(p["values"]) for p in parts])
            if parts
            else np.zeros(0)
        )
        if not len(vals):
            return {"value": None}
        med = np.median(vals)
        return {"value": float(np.median(np.abs(vals - med)))}
    if t == "weighted_avg":
        vsum = sum(p["vsum"] for p in parts)
        wsum = sum(p["wsum"] for p in parts)
        return {"value": (vsum / wsum) if wsum else None}
    if t == "top_hits":
        size = parts[0]["size"] if parts else 3
        merged_hits = [h for p in parts for h in p["hits"]]
        merged_hits.sort(key=lambda h: tuple(h.get("_k", [])))
        total = sum(p["total"] for p in parts)
        return {
            "hits": {
                "total": {"value": total, "relation": "eq"},
                "max_score": None,
                "hits": [
                    {k: v for k, v in h.items() if k != "_k"}
                    for h in merged_hits[:size]
                ],
            }
        }
    if t == "global":
        return {
            "doc_count": sum(p["doc_count"] for p in parts),
            **_reduce_subs(node, [p["subs"] for p in parts]),
        }
    if t == "significant_terms":
        fg: Dict[str, int] = {}
        bg: Dict[str, int] = {}
        fg_total = sum(p["fg_total"] for p in parts)
        bg_total = sum(p["bg_total"] for p in parts)
        for p in parts:
            for k, v in p["fg"].items():
                fg[k] = fg.get(k, 0) + v
            for k, v in p["bg"].items():
                bg[k] = bg.get(k, 0) + v
        size = parts[0]["size"] if parts else 10
        scored = []
        for k, f_cnt in fg.items():
            b_cnt = bg.get(k, f_cnt)
            if fg_total == 0 or bg_total == 0:
                continue
            fg_rate = f_cnt / fg_total
            bg_rate = b_cnt / bg_total
            if fg_rate <= bg_rate or bg_rate == 0:
                continue  # only terms MORE common in the foreground
            # JLH: (fg% - bg%) * (fg% / bg%) — SignificantTermsHeuristic
            score = (fg_rate - bg_rate) * (fg_rate / bg_rate)
            scored.append((score, k, f_cnt, b_cnt))
        scored.sort(key=lambda x: (-x[0], x[1]))
        return {
            "doc_count": fg_total,
            "bg_count": bg_total,
            "buckets": [
                {
                    "key": k,
                    "doc_count": f_cnt,
                    "score": score,
                    "bg_count": b_cnt,
                }
                for score, k, f_cnt, b_cnt in scored[:size]
            ],
        }
    if t == "composite":
        merged: Dict[str, dict] = {}
        for p in parts:
            for bk, b in p["buckets"].items():
                cur = merged.get(bk)
                if cur is None:
                    merged[bk] = {
                        "key_values": b["key_values"],
                        "doc_count": b["doc_count"],
                        "subs": [b["subs"]],
                    }
                else:
                    cur["doc_count"] += b["doc_count"]
                    cur["subs"].append(b["subs"])
        source_names = parts[0]["source_names"] if parts else []
        size = parts[0]["size"] if parts else 10
        after = parts[0].get("after") if parts else None

        def kkey(b):
            return tuple(_sort_key(v) for v in b["key_values"])

        ordered = sorted(merged.values(), key=kkey)
        if after:
            after_tuple = tuple(
                _sort_key(after.get(nm)) for nm in source_names
            )
            ordered = [b for b in ordered if kkey(b) > after_tuple]
        page = ordered[:size]
        buckets = []
        for b in page:
            buckets.append(
                {
                    "key": dict(zip(source_names, b["key_values"])),
                    "doc_count": b["doc_count"],
                    **_reduce_subs(node, b["subs"]),
                }
            )
        out = {"buckets": buckets}
        if buckets and len(ordered) > size:
            out["after_key"] = buckets[-1]["key"]
        return out
    if t == "cardinality":
        n = 0
        for key in ("terms", "nums"):
            arrays = [np.asarray(p[key]) for p in parts if len(p[key])]
            n += len(np.unique(np.concatenate(arrays))) if arrays else 0
        return {"value": n}
    if t == "percentiles":
        vals = np.concatenate([np.asarray(p["values"]) for p in parts]) if parts else np.zeros(0)
        percents = parts[0]["percents"] if parts else [1, 5, 25, 50, 75, 95, 99]
        values = {}
        for pc in percents:
            values[f"{float(pc)}"] = (
                float(np.percentile(vals, pc)) if len(vals) else None
            )
        return {"values": values}
    if t == "terms":
        merged: Dict[Any, dict] = {}
        total = 0
        size = _int_param(node, "size", 10)
        error_bound = 0
        for p in parts:
            total += p["sum_docs"]
            error_bound += p.get("shard_error", 0)
            for bk, b in p["buckets"].items():
                cur = merged.get(bk)
                if cur is None:
                    merged[bk] = {
                        "key": b["key"],
                        "doc_count": b["doc_count"],
                        "subs": [b["subs"]],
                    }
                else:
                    cur["doc_count"] += b["doc_count"]
                    cur["subs"].append(b["subs"])
        order = _norm_order(node.params.get("order", {"_count": "desc"}))
        counts = {b["key"]: b["doc_count"] for b in merged.values()}
        ordered = _order_buckets(counts, order)[:size]
        buckets = []
        top_total = 0
        for key, cnt in ordered:
            b = merged[_bkey(key)]
            top_total += cnt
            entry = {"key": key, "doc_count": cnt}
            if isinstance(key, bool):
                entry["key"] = int(key)
                entry["key_as_string"] = "true" if key else "false"
            entry.update(_reduce_subs(node, b["subs"]))
            buckets.append(entry)
        return {
            "doc_count_error_upper_bound": error_bound,
            "sum_other_doc_count": max(total - top_total, 0),
            "buckets": buckets,
        }
    if t in ("histogram", "date_histogram"):
        merged = {}
        for p in parts:
            for bk, b in p["buckets"].items():
                cur = merged.get(bk)
                if cur is None:
                    merged[bk] = {
                        "key": b["key"],
                        "doc_count": b["doc_count"],
                        "subs": [b["subs"]],
                    }
                else:
                    cur["doc_count"] += b["doc_count"]
                    cur["subs"].append(b["subs"])
        # min_doc_count applies post-merge (a bucket may clear the bar
        # only once all shards' counts are summed)
        min_count = int(node.params.get("min_doc_count", 0))
        buckets = []
        for bk in sorted(merged):
            b = merged[bk]
            if b["doc_count"] < min_count:
                continue
            entry = {"key": b["key"], "doc_count": b["doc_count"]}
            if t == "date_histogram":
                entry["key_as_string"] = _millis_iso(b["key"])
            entry.update(_reduce_subs(node, b["subs"]))
            buckets.append(entry)
        return {"buckets": buckets}
    if t in ("range", "date_range", "geo_distance"):
        keyed = parts[0]["keyed"] if parts else False
        by_key: Dict[str, dict] = {}
        order: List[str] = []
        for p in parts:
            for b in p["buckets"]:
                cur = by_key.get(b["key"])
                if cur is None:
                    by_key[b["key"]] = {
                        **{k: v for k, v in b.items() if k != "subs"},
                        "subs": [b["subs"]],
                    }
                    order.append(b["key"])
                else:
                    cur["doc_count"] += b["doc_count"]
                    cur["subs"].append(b["subs"])
        buckets = []
        for key in order:
            b = by_key[key]
            entry = {k: v for k, v in b.items() if k != "subs"}
            if t == "date_range":
                if "from" in entry:
                    entry["from_as_string"] = _millis_iso(entry["from"])
                if "to" in entry:
                    entry["to_as_string"] = _millis_iso(entry["to"])
            entry.update(_reduce_subs(node, b["subs"]))
            buckets.append(entry)
        if keyed:
            return {
                "buckets": {
                    b["key"]: {k: v for k, v in b.items() if k != "key"}
                    for b in buckets
                }
            }
        return {"buckets": buckets}
    if t in ("filter", "missing", "sampler"):
        return {
            "doc_count": sum(p["doc_count"] for p in parts),
            **_reduce_subs(node, [p["subs"] for p in parts]),
        }
    if t == "filters":
        keyed = parts[0]["keyed"] if parts else True
        merged = {}
        for p in parts:
            for key, b in p["buckets"].items():
                cur = merged.get(key)
                if cur is None:
                    merged[key] = {
                        "key": b["key"],
                        "doc_count": b["doc_count"],
                        "subs": [b["subs"]],
                    }
                else:
                    cur["doc_count"] += b["doc_count"]
                    cur["subs"].append(b["subs"])
        if keyed:
            return {
                "buckets": {
                    key: {
                        "doc_count": m["doc_count"],
                        **_reduce_subs(node, m["subs"]),
                    }
                    for key, m in merged.items()
                }
            }
        return {
            "buckets": [
                {"doc_count": m["doc_count"], **_reduce_subs(node, m["subs"])}
                for _, m in sorted(merged.items(), key=lambda kv: int(kv[0]))
            ]
        }
    raise AggParseError(f"unknown aggregation type [{t}]")


def _reduce_subs(node: AggNode, sub_partials: List[dict]) -> dict:
    if not node.subs:
        return {}
    return reduce_aggs(
        node.subs, [p for p in sub_partials if p], in_bucket=True
    )


# ----------------------------------------------------------------------
# pipeline aggregations (reduce-time)
# ----------------------------------------------------------------------


def _bucket_path_value(bucket: dict, path: str):
    """Resolves a buckets_path tail inside ONE bucket: `_count`, a
    metric agg name, or `name.prop` (e.g. stats.avg, percentiles.50)."""
    if path == "_count":
        return bucket.get("doc_count")
    name, _, prop = path.partition(".")
    node = bucket.get(name)
    if node is None:
        return None
    if prop:
        if "values" in node and prop in node["values"]:
            return node["values"][prop]
        return node.get(prop)
    if isinstance(node, dict):
        return node.get("value")
    return node


def _sibling_pipeline(node: AggNode, reduced: dict) -> dict:
    """avg/max/min/sum/stats_bucket over a sibling multi-bucket agg
    (BucketMetricsPipelineAggregator)."""
    path = str(_req(node, "buckets_path"))
    head, _, tail = path.partition(">")
    target = reduced.get(head)
    while target is not None and ">" in tail:
        nxt, _, tail = tail.partition(">")
        target = (target or {}).get(nxt)
    if target is None or "buckets" not in target:
        raise AggParseError(
            f"buckets_path [{path}] of [{node.name}] does not point at a "
            "multi-bucket aggregation"
        )
    buckets = target["buckets"]
    if isinstance(buckets, dict):
        buckets = list(buckets.values())
    gap = node.params.get("gap_policy", "skip")
    vals = []
    for b in buckets:
        v = _bucket_path_value(b, tail or "_count")
        if v is None:
            if gap == "insert_zeros":
                vals.append(0.0)
            continue
        vals.append(float(v))
    t = node.type
    if t == "avg_bucket":
        return {"value": (sum(vals) / len(vals)) if vals else None}
    if t == "max_bucket":
        m = max(vals) if vals else None
        keys = [
            b.get("key")
            for b in buckets
            if _bucket_path_value(b, tail or "_count") == m
        ] if m is not None else []
        return {"value": m, "keys": keys}
    if t == "min_bucket":
        m = min(vals) if vals else None
        keys = [
            b.get("key")
            for b in buckets
            if _bucket_path_value(b, tail or "_count") == m
        ] if m is not None else []
        return {"value": m, "keys": keys}
    if t == "sum_bucket":
        return {"value": float(sum(vals))}
    if t == "stats_bucket":
        return {
            "count": len(vals),
            "min": min(vals) if vals else None,
            "max": max(vals) if vals else None,
            "avg": (sum(vals) / len(vals)) if vals else None,
            "sum": float(sum(vals)),
        }
    raise AggParseError(f"unknown sibling pipeline [{t}]")


def _run_pipeline_script(script, bindings: dict):
    from ..script import ScriptError, script_service

    try:
        compiled = script_service.compile(script, "field")
        return compiled.run(bindings)
    except ScriptError as e:
        raise AggParseError(str(e))


def _apply_parent_pipelines(node: AggNode, reduced: dict) -> dict:
    """Runs the node's parent-pipeline subs over its ordered reduced
    buckets (derivative, cumulative_sum, serial_diff, moving_fn,
    bucket_script, bucket_selector, bucket_sort)."""
    pipes = [s for s in node.subs if s.type in PARENT_PIPELINE_TYPES]
    if not pipes or not isinstance(reduced.get("buckets"), list):
        return reduced
    buckets: List[dict] = reduced["buckets"]
    for pipe in pipes:
        t = pipe.type
        gap = pipe.params.get("gap_policy", "skip")

        def series(path):
            out = []
            for b in buckets:
                v = _bucket_path_value(b, path)
                if v is None and gap == "insert_zeros":
                    v = 0.0
                out.append(None if v is None else float(v))
            return out

        if t in ("derivative", "cumulative_sum", "serial_diff", "moving_fn"):
            vals = series(str(_req(pipe, "buckets_path")))
            if t == "derivative":
                prev = None
                for b, v in zip(buckets, vals):
                    if prev is not None and v is not None:
                        b[pipe.name] = {"value": v - prev}
                    prev = v if v is not None else prev
            elif t == "cumulative_sum":
                run = 0.0
                for b, v in zip(buckets, vals):
                    run += v or 0.0
                    b[pipe.name] = {"value": run}
            elif t == "serial_diff":
                lag = int(pipe.params.get("lag", 1))
                for i, b in enumerate(buckets):
                    if i >= lag and vals[i] is not None and vals[i - lag] is not None:
                        b[pipe.name] = {"value": vals[i] - vals[i - lag]}
            else:  # moving_fn
                window = int(_req(pipe, "window"))
                script = _req(pipe, "script")
                shift = int(pipe.params.get("shift", 0))
                for i, b in enumerate(buckets):
                    lo = i - window + shift
                    hi = i + shift
                    win = [
                        v for v in vals[max(0, lo):max(0, hi)]
                        if v is not None
                    ]
                    out = _run_pipeline_script(
                        script,
                        {"values": win, "MovingFunctions": _MovingFunctions},
                    )
                    if out is not None:
                        b[pipe.name] = {"value": float(out)}
        elif t in ("bucket_script", "bucket_selector"):
            paths = _req(pipe, "buckets_path")
            if not isinstance(paths, dict):
                raise AggParseError(
                    f"[{t}] buckets_path must be an object of name → path"
                )
            script = _req(pipe, "script")
            kept = []
            for b in buckets:
                bindings = {}
                missing = False
                for var, path in paths.items():
                    v = _bucket_path_value(b, str(path))
                    if v is None:
                        if gap == "insert_zeros":
                            v = 0.0
                        else:
                            missing = True
                            break
                    bindings[var] = float(v)
                if missing:
                    if t == "bucket_selector":
                        continue  # gap skip drops the bucket from selection
                    kept.append(b)
                    continue
                out = _run_pipeline_script(script, bindings)
                if t == "bucket_script":
                    if out is not None:
                        b[pipe.name] = {"value": float(out)}
                    kept.append(b)
                else:  # bucket_selector
                    if bool(out):
                        kept.append(b)
            if t == "bucket_selector":
                buckets[:] = kept
        elif t == "bucket_sort":
            sort = pipe.params.get("sort") or []
            frm = int(pipe.params.get("from", 0))
            size = pipe.params.get("size")

            def sort_key(b):
                keys = []
                for s in sort:
                    if isinstance(s, str):
                        path, order = s, "asc"
                    else:
                        path, spec = next(iter(s.items()))
                        order = (
                            spec.get("order", "asc")
                            if isinstance(spec, dict)
                            else str(spec)
                        )
                    v = _bucket_path_value(b, path)
                    v = float("-inf") if v is None else float(v)
                    keys.append(-v if order == "desc" else v)
                return tuple(keys)

            if sort:
                buckets.sort(key=sort_key)
            end = None if size is None else frm + int(size)
            buckets[:] = buckets[frm:end]
    return reduced


class _MovingFunctions:
    """MovingFunctions surface for moving_fn scripts."""

    @staticmethod
    def max(values):
        return max(values) if values else None

    @staticmethod
    def min(values):
        return min(values) if values else None

    @staticmethod
    def sum(values):
        return float(sum(values)) if values else 0.0

    @staticmethod
    def unweightedAvg(values):
        return (float(sum(values)) / len(values)) if values else None

    @staticmethod
    def stdDev(values, avg=None):
        if not values:
            return None
        m = avg if avg is not None else sum(values) / len(values)
        return float(np.sqrt(sum((v - m) ** 2 for v in values) / len(values)))


# ----------------------------------------------------------------------
# shared helpers
# ----------------------------------------------------------------------


def _bkey(key: Any) -> str:
    return f"{type(key).__name__}:{key}"


def _order_buckets(counts: Dict[Any, int], order: dict) -> List[tuple]:
    (okey, direction), *_ = list(order.items()) or [("_count", "desc")]
    if okey not in ("_count", "_key"):
        raise AggParseError(
            f"ordering by [{okey}] is not supported "
            "(only _count and _key in this build)"
        )
    reverse = direction == "desc"
    items = list(counts.items())
    if okey == "_key":
        items.sort(key=lambda kv: _sort_key(kv[0]), reverse=reverse)
    else:  # _count, tie-break key asc (Lucene order)
        items.sort(key=lambda kv: _sort_key(kv[0]))
        items.sort(key=lambda kv: kv[1], reverse=reverse)
    return items


def _req_param(params: dict, name: str, node: AggNode):
    v = params.get(name)
    if v is None:
        raise AggParseError(
            f"[{node.type}] agg [{node.name}] source requires [{name}]"
        )
    return v


def _req(node: AggNode, name: str):
    v = node.params.get(name)
    if v is None:
        raise AggParseError(f"[{node.type}] agg [{node.name}] requires [{name}]")
    return v


def _int_param(node: AggNode, name: str, default: int) -> int:
    try:
        return int(node.params.get(name, default))
    except (TypeError, ValueError):
        raise AggParseError(
            f"[{node.type}] agg [{node.name}]: [{name}] must be an integer"
        )


def _float_param(value, node: AggNode, name: str) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        raise AggParseError(
            f"[{node.type}] agg [{node.name}]: [{name}] must be numeric"
        )


def _norm_order(order) -> dict:
    """ES accepts both {"_count": "desc"} and [{"_count": "desc"}, ...];
    multi-criteria lists use the first criterion (tie-breaks beyond it
    are fixed: key asc)."""
    if isinstance(order, list):
        if not order or not isinstance(order[0], dict):
            raise AggParseError("order list must contain objects")
        return order[0]
    if not isinstance(order, dict):
        raise AggParseError("order must be an object or list of objects")
    return order


def _sort_key(k: Any):
    # normalize mixed bool/int/float keys; strings sort as strings
    if isinstance(k, bool):
        return (0, int(k))
    if isinstance(k, (int, float)):
        return (0, float(k))
    return (1, str(k))


_CAL_UNITS = {
    "minute": 60_000,
    "1m": 60_000,
    "hour": 3_600_000,
    "1h": 3_600_000,
    "day": 86_400_000,
    "1d": 86_400_000,
    "week": 7 * 86_400_000,
    "1w": 7 * 86_400_000,
}
_FIXED_SUFFIX = {
    "ms": 1,
    "s": 1000,
    "m": 60_000,
    "h": 3_600_000,
    "d": 86_400_000,
}


def _parse_dh_interval(params: dict):
    """Returns (interval_ms or None, calendar_unit or None)."""
    cal = params.get("calendar_interval")
    if cal is not None:
        if cal in ("month", "1M"):
            return None, "month"
        if cal in ("quarter", "1q"):
            return None, "quarter"
        if cal in ("year", "1y"):
            return None, "year"
        if cal in _CAL_UNITS:
            return _CAL_UNITS[cal], None
        raise AggParseError(f"unknown calendar interval [{cal}]")
    fixed = params.get("fixed_interval") or params.get("interval")
    if fixed is None:
        raise AggParseError("date_histogram requires an interval")
    s = str(fixed)
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            try:
                return int(s[: -len(suffix)]) * _FIXED_SUFFIX[suffix], None
            except ValueError:
                break
    raise AggParseError(f"unparsable interval [{fixed}]")


def _date_bucket_keys(
    millis: np.ndarray, calendar_unit: Optional[str], interval_ms: Optional[int]
) -> np.ndarray:
    if calendar_unit is None:
        assert interval_ms is not None
        return (np.floor(millis / interval_ms) * interval_ms).astype(np.int64)
    # calendar month/quarter/year: bucket start at UTC boundary
    out = np.zeros(len(millis), np.int64)
    for i, ms in enumerate(millis):
        dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
        if calendar_unit == "month":
            b = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        elif calendar_unit == "quarter":
            b = dt.replace(
                month=((dt.month - 1) // 3) * 3 + 1,
                day=1,
                hour=0,
                minute=0,
                second=0,
                microsecond=0,
            )
        else:  # year
            b = dt.replace(
                month=1, day=1, hour=0, minute=0, second=0, microsecond=0
            )
        out[i] = int(b.timestamp() * 1000)
    return out


def _millis_iso(ms: float) -> str:
    dt = datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def _range_key_part(raw, is_date: bool, parsed) -> str:
    if raw is None:
        return "*"
    if is_date:
        return str(raw)
    return f"{float(parsed)}"
