"""Per-node admission control: weighted fair queueing, adaptive
concurrency, deadline shedding, brownout serving, retry budgets.

Reference analogs: upstream Elasticsearch treats overload as a
first-class capability — bounded thread-pool queues rejecting with
EsRejectedExecutionException (429), HierarchyCircuitBreakerService,
and the 8.x SearchBackpressure / adaptive replica selection machinery.
This module is the TPU-serving shape of that substrate, sitting in
FRONT of the QueryBatcher:

* **Weighted fair queueing** (stride scheduling): each index/tenant
  owns a FIFO of waiting requests; when a slot frees, the tenant with
  the lowest virtual pass dequeues and its pass advances by
  ``STRIDE_BASE / weight`` — an index carrying weight 2 drains twice
  as often as a weight-1 peer under contention, and an idle tenant's
  pass snaps forward on arrival so it cannot hoard credit.
* **Adaptive concurrency (AIMD)**: the limit tracks actual device
  capacity instead of a static queue bound. The congestion signal is
  the measured wait between batcher enqueue and device dispatch
  (QueryBatcher reports every batch's worst wait here): sustained
  waits above ``target_delay_ms`` multiplicatively decrease the limit
  (×0.7, at most once per limit-many observations); sustained waits
  under half the target additively recover (+1 per limit-many calm
  observations).
* **Deadline-aware shedding**: a queued request whose ``timeout``
  budget expired is dropped AT DEQUEUE — never dispatched dead — and
  the batcher applies the same rule to its own queue (a job past its
  shard deadline fails its waiter instead of launching kernels).
* **Brownout degraded modes**: pressure (queue-delay ratio × queue
  occupancy) maps to tiers; each tier sheds progressively more work
  (see ``apply_brownout``) and every degraded response carries the
  tier in its ``_overload`` metadata. Tier 4 rejects outright.
* **Retry budget**: a token bucket fed by live admitted traffic
  (``retry_budget_ratio`` tokens per admitted request, SRE-style)
  caps replica-retry amplification — during an incident, retry
  traffic cannot exceed ~ratio of live traffic.

Every rejection raises :class:`EsOverloadedError` → HTTP 429 with a
computed ``Retry-After`` and an ``es.overloaded`` body block, and the
whole layer is deterministic-testable: the ``admission.acquire`` fault
site accepts the ``load`` kind, whose ``delay_ms`` is injected as a
synthetic queue-delay observation (seeded pure-hash draws, no sleep),
so a replayed overload schedule yields the same shed/brownout
decisions.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..common.faults import faults

# env knobs (process start); the cluster-settings consumers in
# ClusterService re-configure() dynamically (search.admission.*)
ADMISSION_ENV = "ES_TPU_ADMISSION"  # "on" (default) | "off"
TARGET_DELAY_ENV = "ES_TPU_ADMISSION_TARGET_MS"
MAX_QUEUE_ENV = "ES_TPU_ADMISSION_MAX_QUEUE"

TARGET_DELAY_MS_DEFAULT = 75.0
MIN_LIMIT_DEFAULT = 4
MAX_LIMIT_DEFAULT = 256
INITIAL_LIMIT_DEFAULT = 64
MAX_QUEUE_DEFAULT = 1024
RETRY_BUDGET_RATIO_DEFAULT = 0.1
RETRY_BUDGET_CAP_DEFAULT = 32.0

STRIDE_BASE = 1 << 16

# brownout tier names, indexed by tier number
TIER_NAMES = ("normal", "shed_optional", "shrink_window", "cache_only",
              "reject")


class EsOverloadedError(Exception):
    """Admission/overload rejection → HTTP 429 with Retry-After.

    Deliberately NOT a RuntimeError (the shard path treats RuntimeError
    as 'batcher closed'), and deliberately its own class: the REST
    layer renders it with the es.overloaded body block; the fan-out
    treats it as request-scoped (a 429 keeps its contract — never
    retried on a replica)."""

    status = 429
    err_type = "es_rejected_execution_exception"

    def __init__(
        self,
        reason: str,
        retry_after_s: float = 1.0,
        tier: int = 4,
        shed: str = "rejected",
    ):
        super().__init__(reason)
        self.reason = reason
        self.retry_after = max(1, int(math.ceil(retry_after_s)))
        self.tier = tier
        self.shed = shed

    def overload_info(self) -> dict:
        """The ``es.overloaded`` block carried in the 429 body."""
        return {
            "reason": self.shed,
            "pressure_tier": self.tier,
            "pressure_mode": TIER_NAMES[min(self.tier, len(TIER_NAMES) - 1)],
            "retry_after_s": self.retry_after,
        }


def overload_body(exc: BaseException, retry_after: int) -> dict:
    """Structured 429 body for ANY rejection path (admission, batcher
    queue-full, HBM breaker): the standard ES error envelope plus an
    ``es.overloaded`` block with the computed backoff hint — callers
    that only read the envelope see es_rejected_execution_exception /
    circuit_breaking_exception exactly as before."""
    err_type = getattr(exc, "err_type", "es_rejected_execution_exception")
    reason = str(exc)
    info = (
        exc.overload_info()
        if isinstance(exc, EsOverloadedError)
        else {"reason": err_type, "retry_after_s": retry_after}
    )
    return {
        "error": {
            "root_cause": [{"type": err_type, "reason": reason}],
            "type": err_type,
            "reason": reason,
        },
        "status": 429,
        "es.overloaded": info,
    }


class _Waiter:
    __slots__ = ("tenant", "event", "granted", "shed", "deadline", "t_enq")

    def __init__(self, tenant: str, deadline: Optional[float]):
        self.tenant = tenant
        self.event = threading.Event()
        self.granted = False
        self.shed: Optional[str] = None  # set when dropped at dequeue
        self.deadline = deadline
        self.t_enq = time.monotonic()


class Ticket:
    """One admitted request: carries the brownout tier decided at
    acquire time and the release bookkeeping."""

    __slots__ = ("tenant", "tier", "t_grant", "released", "counted")

    def __init__(self, tenant: str, tier: int, counted: bool = True):
        self.tenant = tenant
        self.tier = tier
        self.t_grant = time.monotonic()
        self.released = False
        # False for tickets minted while admission was disabled: they
        # hold no inflight slot, so release() must not return one
        self.counted = counted

    @property
    def mode(self) -> str:
        return TIER_NAMES[min(self.tier, len(TIER_NAMES) - 1)]


class _TenantState:
    __slots__ = ("queue", "vpass", "weight", "active", "admitted")

    def __init__(self):
        self.queue: Deque[_Waiter] = deque()
        self.vpass = 0.0
        self.weight = 1.0
        self.active = 0
        self.admitted = 0


class AdmissionController:
    """The per-node admission layer. One instance fronts every index's
    search entry on this node (process-global ``admission`` below)."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        target_delay_ms: Optional[float] = None,
        min_limit: int = MIN_LIMIT_DEFAULT,
        max_limit: int = MAX_LIMIT_DEFAULT,
        initial_limit: int = INITIAL_LIMIT_DEFAULT,
        max_queue: Optional[int] = None,
        retry_budget_ratio: float = RETRY_BUDGET_RATIO_DEFAULT,
        retry_budget_cap: float = RETRY_BUDGET_CAP_DEFAULT,
    ):
        if enabled is None:
            enabled = os.environ.get(ADMISSION_ENV, "on").lower() not in (
                "off", "false", "0",
            )
        if target_delay_ms is None:
            raw = os.environ.get(TARGET_DELAY_ENV, "")
            try:
                target_delay_ms = float(raw) if raw else TARGET_DELAY_MS_DEFAULT
            except ValueError:
                target_delay_ms = TARGET_DELAY_MS_DEFAULT
        if max_queue is None:
            raw = os.environ.get(MAX_QUEUE_ENV, "")
            try:
                max_queue = int(raw) if raw else MAX_QUEUE_DEFAULT
            except ValueError:
                max_queue = MAX_QUEUE_DEFAULT
        self._lock = threading.Lock()
        self.enabled = bool(enabled)
        self.target_delay_s = max(target_delay_ms, 1.0) / 1000.0
        self.min_limit = max(1, int(min_limit))
        self.max_limit = max(self.min_limit, int(max_limit))
        self.limit = float(
            min(max(initial_limit, self.min_limit), self.max_limit)
        )
        self.max_queue = max(1, int(max_queue))
        self._tenants: Dict[str, _TenantState] = {}
        self._inflight = 0
        self._queued = 0
        # AIMD bookkeeping: observations since the last decrease /
        # increase — one window = `limit` observations, so the limit
        # moves at most once per round trip's worth of signal
        self._delay_ewma = 0.0
        self._obs_since_decrease = 0
        self._calm_obs = 0
        # service-time EWMA feeds the Retry-After computation
        self._service_ewma = 0.05
        # retry budget (token bucket fed by admitted live traffic)
        self.retry_budget_ratio = float(retry_budget_ratio)
        self.retry_budget_cap = float(retry_budget_cap)
        self._retry_tokens = float(retry_budget_cap)
        self.stats_counters = {
            "admitted": 0,
            "queued_total": 0,
            "shed_deadline": 0,
            "shed_queue_full": 0,
            "shed_rejected": 0,
            "brownouts": 0,
            "limit_decreases": 0,
            "limit_increases": 0,
            "retries_granted": 0,
            "retries_denied": 0,
            # brownout tier-1 stripped a "profile": true request — the
            # shed profiling is attributable, not silent
            "profiles_shed": 0,
        }
        # per-tier grant counts (index = tier)
        self._tier_grants = [0] * len(TIER_NAMES)

    # ---- configuration ----------------------------------------------

    def configure(self, **kw) -> None:
        """Dynamic re-configuration (cluster settings consumers)."""
        with self._lock:
            if "enabled" in kw and kw["enabled"] is not None:
                self.enabled = bool(kw["enabled"])
            if "target_delay_ms" in kw and kw["target_delay_ms"] is not None:
                self.target_delay_s = max(float(kw["target_delay_ms"]), 1.0) / 1000.0
            if "max_queue" in kw and kw["max_queue"] is not None:
                self.max_queue = max(1, int(kw["max_queue"]))
            if "retry_budget_ratio" in kw and kw["retry_budget_ratio"] is not None:
                self.retry_budget_ratio = float(kw["retry_budget_ratio"])
            if "min_limit" in kw and kw["min_limit"] is not None:
                self.min_limit = max(1, int(kw["min_limit"]))
            if "max_limit" in kw and kw["max_limit"] is not None:
                self.max_limit = max(self.min_limit, int(kw["max_limit"]))
            self.limit = float(
                min(max(self.limit, self.min_limit), self.max_limit)
            )

    def reset(self) -> None:
        """Back to process-start state (tests; mirrors faults.clear)."""
        self.__init__()

    # ---- pressure / tiers -------------------------------------------

    def _pressure_ratio_locked(self) -> float:
        r = self._delay_ewma / self.target_delay_s
        # queue occupancy escalates brownout pressure even while the
        # delay EWMA is still catching up — but saturates at tier 3:
        # actual overflow sheds via the dedicated queue_full bound, and
        # tier-4 reject stays reserved for the congestion signal itself
        occ = self._queued / self.max_queue
        if occ >= 0.5:
            r = max(r, 2.0 + 3.8 * (min(occ, 1.0) - 0.5))
        return r

    @staticmethod
    def _tier_of(ratio: float) -> int:
        if ratio < 0.5:
            return 0
        if ratio < 1.0:
            return 1
        if ratio < 2.0:
            return 2
        if ratio < 4.0:
            return 3
        return 4

    def pressure_tier(self) -> int:
        with self._lock:
            return self._tier_of(self._pressure_ratio_locked())

    def retry_after_s(self) -> int:
        """Computed backoff hint: the time for the current backlog to
        drain at the observed service rate (bounded 1..30s)."""
        with self._lock:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> int:
        backlog = self._queued + self._inflight + 1
        drain = backlog * self._service_ewma / max(self.limit, 1.0)
        return int(min(max(math.ceil(drain), 1), 30))

    # ---- AIMD signal (fed by the batcher's enqueue→dispatch waits) ---

    def observe_queue_delay(self, seconds: float) -> None:
        """One congestion-signal sample: the measured wait between a
        job entering the batcher queue and its device dispatch (or a
        synthetic sample injected by the `load` fault kind)."""
        s = max(float(seconds), 0.0)
        with self._lock:
            self._delay_ewma += 0.3 * (s - self._delay_ewma)
            self._obs_since_decrease += 1
            window = max(int(self.limit), 1)
            if s > self.target_delay_s:
                self._calm_obs = 0
                if self._obs_since_decrease >= window:
                    self.limit = max(self.limit * 0.7, float(self.min_limit))
                    self._obs_since_decrease = 0
                    self.stats_counters["limit_decreases"] += 1
            elif self._delay_ewma < 0.5 * self.target_delay_s:
                self._calm_obs += 1
                if self._calm_obs >= window:
                    if self.limit < self.max_limit:
                        self.limit = min(
                            self.limit + 1.0, float(self.max_limit)
                        )
                        self.stats_counters["limit_increases"] += 1
                    self._calm_obs = 0

    # ---- retry budget ------------------------------------------------

    def retry_allowed(self) -> bool:
        """Spend one retry token (replica retry of a failed shard call).
        Tokens accrue at retry_budget_ratio per admitted request, so
        retry traffic is capped at ~ratio of live traffic."""
        with self._lock:
            if not self.enabled:
                self.stats_counters["retries_granted"] += 1
                return True
            # epsilon absorbs float accrual drift (10 × 0.1 ≠ 1.0)
            if self._retry_tokens >= 1.0 - 1e-9:
                self._retry_tokens = max(self._retry_tokens - 1.0, 0.0)
                self.stats_counters["retries_granted"] += 1
                return True
            self.stats_counters["retries_denied"] += 1
            return False

    # ---- acquire / release ------------------------------------------

    def acquire(
        self,
        tenant: str,
        weight: float = 1.0,
        deadline: Optional[float] = None,
        block: bool = True,
    ) -> Ticket:
        """Admit one request for `tenant` (index name). Returns a
        Ticket carrying the brownout tier, or raises EsOverloadedError
        (429 + Retry-After). Blocks in the tenant's fair queue while
        the node is at its concurrency limit."""
        # fault site: `error` rules raise as usual; `load` rules inject
        # their delay_ms as a synthetic congestion sample (deterministic
        # seeded draws — the replay-test substrate)
        eff = faults.check("admission.acquire", tenant=tenant)
        if eff and eff.get("load_ms"):
            self.observe_queue_delay(eff["load_ms"] / 1000.0)
        if not self.enabled:
            return Ticket(tenant, 0, counted=False)
        with self._lock:
            ts = self._tenants.get(tenant)
            if ts is None:
                ts = self._tenants[tenant] = _TenantState()
            ts.weight = max(float(weight), 1e-3)
            ratio = self._pressure_ratio_locked()
            tier = self._tier_of(ratio)
            if tier >= 4:
                self.stats_counters["shed_rejected"] += 1
                raise EsOverloadedError(
                    f"node overloaded (pressure {ratio:.2f}): rejecting "
                    f"[{tenant}] search",
                    retry_after_s=self._retry_after_locked(),
                    tier=4,
                    shed="pressure_reject",
                )
            free = self._inflight < int(self.limit)
            if free and not self._queued:
                return self._grant_locked(tenant, ts, tier)
            # at the limit (or fairness: earlier waiters exist) — queue
            if not block or self._queued >= self.max_queue:
                self.stats_counters["shed_queue_full"] += 1
                raise EsOverloadedError(
                    f"admission queue full [{self._queued}/"
                    f"{self.max_queue}]: rejecting [{tenant}] search",
                    retry_after_s=self._retry_after_locked(),
                    tier=max(tier, 3),
                    shed="queue_full",
                )
            w = _Waiter(tenant, deadline)
            if not ts.queue:
                # an idle tenant's pass snaps forward to the current
                # minimum so it cannot bank credit while away (stride
                # scheduling's lag bound)
                floor = min(
                    (t.vpass for t in self._tenants.values() if t.queue),
                    default=ts.vpass,
                )
                ts.vpass = max(ts.vpass, floor)
            ts.queue.append(w)
            self._queued += 1
            self.stats_counters["queued_total"] += 1
        # wait outside the lock; release() hands the slot over
        wait_s = None
        if deadline is not None:
            wait_s = max(deadline - time.monotonic(), 0.0) + 0.05
        if not w.event.wait(wait_s):
            # deadline expired while queued: withdraw (shed, not
            # served). release() pops AND grants under one lock hold,
            # so under our lock the waiter is either still queued
            # (withdraw wins) or already granted (grant wins) — no
            # in-between state.
            with self._lock:
                if not w.granted:
                    try:
                        ts.queue.remove(w)
                    except ValueError:  # pragma: no cover - shed race
                        pass
                    else:
                        self._queued -= 1
                        w.shed = "deadline"
                        self.stats_counters["shed_deadline"] += 1
        if w.granted:
            with self._lock:
                tier = self._tier_of(self._pressure_ratio_locked())
                return self._grant_locked(
                    tenant, self._tenants[tenant], tier, counted=True
                )
        raise EsOverloadedError(
            f"search request to [{tenant}] shed "
            f"({w.shed or 'deadline'}) after "
            f"{(time.monotonic() - w.t_enq) * 1000:.0f}ms in the "
            "admission queue",
            retry_after_s=self.retry_after_s(),
            tier=self.pressure_tier(),
            shed=w.shed or "deadline",
        )

    def _grant_locked(
        self, tenant: str, ts: _TenantState, tier: int,
        counted: bool = False,
    ) -> Ticket:
        # `counted`: release() already took the inflight slot when it
        # granted the waiter; immediate grants take it here
        if not counted:
            self._inflight += 1
        ts.active += 1
        ts.admitted += 1
        self.stats_counters["admitted"] += 1
        self._retry_tokens = min(
            self._retry_tokens + self.retry_budget_ratio,
            self.retry_budget_cap,
        )
        self._tier_grants[min(tier, len(TIER_NAMES) - 1)] += 1
        if tier > 0:
            self.stats_counters["brownouts"] += 1
        return Ticket(tenant, tier)

    def release(self, ticket: Ticket) -> None:
        """Completes one admitted request and hands freed slots to the
        fair queue — dropping dead (deadline-expired) waiters at
        dequeue instead of dispatching them."""
        if ticket is None or ticket.released or not ticket.counted:
            return
        ticket.released = True
        grants: List[_Waiter] = []
        now = time.monotonic()
        with self._lock:
            self._service_ewma += 0.1 * (
                max(now - ticket.t_grant, 0.0) - self._service_ewma
            )
            ts = self._tenants.get(ticket.tenant)
            if ts is not None and ts.active > 0:
                ts.active -= 1
            if self._inflight > 0:
                self._inflight -= 1
            # hand freed capacity to waiting tenants: lowest virtual
            # pass first; a dequeued waiter whose deadline already
            # passed is shed right here — never dispatched dead
            while self._inflight < int(self.limit):
                cand = None
                for t in self._tenants.values():
                    if t.queue and (cand is None or t.vpass < cand.vpass):
                        cand = t
                if cand is None:
                    break
                w = cand.queue.popleft()
                self._queued -= 1
                cand.vpass += STRIDE_BASE / cand.weight
                if w.deadline is not None and now > w.deadline:
                    w.shed = "deadline"
                    self.stats_counters["shed_deadline"] += 1
                    w.event.set()
                    continue
                w.granted = True
                self._inflight += 1
                grants.append(w)
        for w in grants:
            w.event.set()

    # ---- observability ----------------------------------------------

    def stats(self) -> dict:
        """The `admission` block in `_nodes/stats`."""
        with self._lock:
            ratio = self._pressure_ratio_locked()
            tier = self._tier_of(ratio)
            return {
                "enabled": self.enabled,
                "limit": int(self.limit),
                "min_limit": self.min_limit,
                "max_limit": self.max_limit,
                "inflight": self._inflight,
                "queued": self._queued,
                "max_queue": self.max_queue,
                "queue_delay_ewma_ms": round(self._delay_ewma * 1000.0, 3),
                "target_delay_ms": round(self.target_delay_s * 1000.0, 3),
                "pressure": round(ratio, 4),
                "pressure_tier": tier,
                "pressure_mode": TIER_NAMES[tier],
                "retry_after_s": self._retry_after_locked(),
                "retry_tokens": round(self._retry_tokens, 3),
                "tier_grants": {
                    TIER_NAMES[i]: n
                    for i, n in enumerate(self._tier_grants)
                },
                "tenants": {
                    name: {
                        "queued": len(t.queue),
                        "active": t.active,
                        "admitted": t.admitted,
                        "weight": t.weight,
                    }
                    for name, t in sorted(self._tenants.items())
                },
                **self.stats_counters,
            }


# ---------------------------------------------------------------------
# brownout degraded modes: progressively shed work as pressure rises
# ---------------------------------------------------------------------


def degradable(body: dict) -> bool:
    """Per-request brownout opt-out: `"allow_degraded": false` pins the
    request to full-fidelity execution (it still pays admission and can
    still be shed outright)."""
    return bool(body.get("allow_degraded", True))


def apply_brownout(body: dict, tier: int) -> tuple:
    """Returns (possibly-rewritten body, [action strings]) for one
    admitted request at `tier`. Tier semantics:

      1 shed_optional — skip work a degraded answer doesn't need: the
        DFS global-stats round and exact total tracking (capped at the
        ES default 10_000), profile output.
      2 shrink_window — halve retriever rank_window_size, halve kNN
        num_candidates (floor k), halve the rescore window_size (floor
        the requested page), cap terms-agg cardinality at 16.
      3 cache_only — agg-only (size:0) bodies must answer from the
        shard request cache; a miss is shed instead of computed.
        Non-agg requests keep their tier-2 degradation.

    Tier 4 never reaches here (acquire rejects)."""
    if tier <= 0 or not degradable(body):
        return body, []
    actions: List[str] = []
    out = dict(body)
    # tier >= 1: shed can_match-skippable / optional work
    if out.get("search_type") == "dfs_query_then_fetch":
        out.pop("search_type")
        actions.append("dfs_skipped")
    if out.get("track_total_hits") is True:
        out["track_total_hits"] = 10_000
        actions.append("total_hits_capped")
    if out.get("profile"):
        out.pop("profile")
        actions.append("profile_dropped")
        with admission._lock:
            admission.stats_counters["profiles_shed"] += 1
    if tier >= 2:
        def shrink_knn(sec):
            k = int(sec.get("k", 10))
            nc = int(sec.get("num_candidates", max(k, 10)))
            if nc > k:
                actions.append("num_candidates_halved")
                return {**sec, "num_candidates": max(nc // 2, k)}
            return sec

        if "knn" in out:
            knn = out["knn"]
            out["knn"] = (
                [shrink_knn(s) for s in knn]
                if isinstance(knn, list)
                else shrink_knn(knn)
            )
        resc = out.get("rescore")
        if isinstance(resc, dict):
            # shrink the second-stage rerank window (never below the
            # requested page, which would 400 at parse)
            floor = int(out.get("size", 10)) + int(out.get("from", 0))
            win = int(resc.get("window_size", 10))
            if win > max(floor, 1) and win > 10:
                resc = {**resc, "window_size": max(win // 2, floor, 10)}
                out["rescore"] = resc
                actions.append("rescore_window_halved")
        ret = out.get("retriever")
        if isinstance(ret, dict) and "rrf" in ret:
            rrf = dict(ret["rrf"])
            win = int(rrf.get("rank_window_size", 100))
            if win > 20:
                rrf["rank_window_size"] = max(win // 2, 20)
                ret = {**ret, "rrf": rrf}
                out["retriever"] = ret
                actions.append("rank_window_halved")
        aggs = out.get("aggs") or out.get("aggregations")
        if isinstance(aggs, dict):
            shrunk, hit = _shrink_agg_sizes(aggs, cap=16)
            if hit:
                out["aggs" if "aggs" in out else "aggregations"] = shrunk
                actions.append("agg_cardinality_capped")
    if tier >= 3:
        aggs = out.get("aggs") or out.get("aggregations")
        if aggs is not None and int(out.get("size", 10)) == 0:
            out["_cache_only"] = True
            actions.append("request_cache_only")
    return out, actions


def _shrink_agg_sizes(node: Any, cap: int) -> tuple:
    """Caps every terms-agg `size` in an agg tree at `cap`."""
    hit = False
    if not isinstance(node, dict):
        return node, False
    out = {}
    for k, v in node.items():
        if k == "terms" and isinstance(v, dict) and int(v.get("size", 10)) > cap:
            v = {**v, "size": cap}
            hit = True
        elif isinstance(v, dict):
            v, h = _shrink_agg_sizes(v, cap)
            hit = hit or h
        out[k] = v
    return out, hit


class RequestCacheOnlyMiss(EsOverloadedError):
    """Tier-3 brownout: an agg body that missed the request cache is
    shed instead of computed (the cache-only degraded mode)."""

    def __init__(self, index: str, shard: int, retry_after_s: float = 2.0):
        super().__init__(
            f"shard [{index}][{shard}] is serving cached-only responses "
            "under overload and this request missed the cache",
            retry_after_s=retry_after_s,
            tier=3,
            shed="cache_only_miss",
        )


# process-wide controller (one node per process in this deployment
# shape — the analog of the process-wide hbm_ledger / faults registry)
admission = AdmissionController()
