"""Shard-level query execution — NumPy oracle executor.

Reference analog: the QueryPhase hot path — SearchService.executeQueryPhase
→ QueryPhase.execute → ContextIndexSearcher.search with Lucene
Weight/Scorer iterators (server/.../search/query/QueryPhase.java).

Execution model (TPU-native, shared by this oracle and the JAX executor in
ops/): every query node evaluates to a dense pair over a segment's docs —
(match_mask: bool[N], scores: float32[N]) — composed with elementwise
AND/OR/sum instead of Lucene's doc-at-a-time iterator trees. The NumPy
version is the *semantics oracle*: the JAX/Pallas path must match it
exactly (tests enforce parity), and it doubles as the measured CPU
baseline for BASELINE.md.

Lucene semantics honored here:
  - shard-level term statistics (df, ttf summed across segments, deletes
    ignored) feed idf/avgdl — as IndexSearcher collectionStatistics does;
  - fields with omitted norms (keyword) score with encodedNorm == 1;
  - bool minimum_should_match defaults: 1 when no must/filter, else 0;
  - top-k ordering is (score desc, global doc asc), global doc order =
    segment order × local doc id (Lucene docBase);
  - match_phrase is evaluated as a conjunction then position-verified
    against re-analyzed stored source (positions are not yet columnar;
    see ROADMAP).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis import AnalysisRegistry
from ..index.mapping import (
    DENSE_VECTOR,
    KEYWORD,
    TEXT,
    DATE,
    BOOLEAN,
    Mappings,
    parse_date_millis,
)
from ..index.segment import Segment
from ..models import bm25
from ..models.similarity import score_vectors
from . import dsl
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    ExistsQuery,
    KnnQueryWrapper,
    KnnSection,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhraseQuery,
    MatchQuery,
    MultiMatchQuery,
    Query,
    QueryParseError,
    RangeQuery,
    TermQuery,
    TermsQuery,
)


@dataclass
class Hit:
    score: float
    segment: int
    local_doc: int
    doc_id: str


@dataclass
class TopDocs:
    total: int
    hits: List[Hit]
    max_score: Optional[float] = None


class ShardReader:
    """A point-in-time view over a shard's segments (ReaderContext analog)."""

    def __init__(
        self,
        segments: List[Segment],
        mappings: Mappings,
        analysis: AnalysisRegistry,
        live_docs: Optional[List[Optional[np.ndarray]]] = None,
    ):
        self.segments = segments
        self.mappings = mappings
        self.analysis = analysis
        self.live_docs = live_docs or [None] * len(segments)

    # ---- shard-level statistics (IndexSearcher.collectionStatistics) ----

    def field_stats(self, field: str) -> Tuple[int, int]:
        """(doc_count, sum_total_term_freq) across segments."""
        dc = 0
        ttf = 0
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is not None:
                dc += pf.stats.doc_count
                ttf += pf.stats.sum_total_term_freq
        return dc, ttf

    def term_stats(self, field: str, term: str) -> Tuple[int, int]:
        """(doc_freq, total_term_freq) across segments (deletes ignored,
        as Lucene does)."""
        df = 0
        ttf = 0
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is None:
                continue
            tid = pf.term_id(term)
            if tid >= 0:
                df += int(pf.term_df[tid])
                ttf += int(pf.term_total_tf[tid])
        return df, ttf

    def num_docs(self) -> int:
        return sum(s.num_docs for s in self.segments)


class NumpyExecutor:
    """The oracle: executes a query tree densely per segment."""

    def __init__(self, reader: ShardReader, k1: float = bm25.DEFAULT_K1, b: float = bm25.DEFAULT_B):
        self.reader = reader
        self.k1 = k1
        self.b = b
        self._weight_cache: Dict[Tuple[str, str], float] = {}
        self._norm_cache: Dict[str, np.ndarray] = {}

    # ---- term weight / norm cache (BM25Similarity.scorer) ----

    def _field_cache(self, field: str) -> np.ndarray:
        cache = self._norm_cache.get(field)
        if cache is None:
            dc, ttf = self.reader.field_stats(field)
            avgdl = bm25.avg_field_length(ttf, dc)
            cache = bm25.norm_inverse_cache(avgdl, self.k1, self.b)
            self._norm_cache[field] = cache
        return cache

    def _term_weight(self, field: str, term: str) -> float:
        key = (field, term)
        w = self._weight_cache.get(key)
        if w is None:
            df, _ = self.reader.term_stats(field, term)
            dc, _ = self.reader.field_stats(field)
            w = float(bm25.idf(dc, df)) if df > 0 else 0.0
            self._weight_cache[key] = w
        return w

    # ---- entry point ----

    def search(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> TopDocs:
        return self.execute(query, size, from_, knn, min_score)[0]

    def execute(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> Tuple[TopDocs, List[np.ndarray]]:
        """(TopDocs, per-segment match masks) — masks feed the agg phase
        so query execution isn't paid twice."""
        # knn sections: per-segment candidates, then a *global* top-k cut
        # across segments (SearchPhaseController.mergeKnnResults semantics)
        knn_sets = [self._knn_topk_global(sec) for sec in (knn or [])]
        per_segment: List[Tuple[np.ndarray, np.ndarray]] = []
        for si, seg in enumerate(self.reader.segments):
            mask, scores = self._execute_root(query, knn_sets, si, seg)
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & live
            if min_score is not None:
                mask = mask & (scores >= min_score)
            per_segment.append((mask, scores))

        total = int(sum(m.sum() for m, _ in per_segment))
        # global collection: (score desc, global doc asc)
        all_scores = []
        all_keys = []
        for si, (mask, scores) in enumerate(per_segment):
            idx = np.nonzero(mask)[0]
            all_scores.append(scores[idx])
            all_keys.append([(si, int(i)) for i in idx])
        if all_scores:
            flat_scores = np.concatenate(all_scores)
        else:
            flat_scores = np.zeros(0, np.float32)
        flat_keys = [k for ks in all_keys for k in ks]
        order = sorted(
            range(len(flat_keys)), key=lambda i: (-float(flat_scores[i]), flat_keys[i])
        )
        top = order[from_ : from_ + size]
        hits = [
            Hit(
                score=float(flat_scores[i]),
                segment=flat_keys[i][0],
                local_doc=flat_keys[i][1],
                doc_id=self.reader.segments[flat_keys[i][0]].doc_ids[flat_keys[i][1]],
            )
            for i in top
        ]
        max_score = float(flat_scores.max()) if len(flat_scores) else None
        return (
            TopDocs(total=total, hits=hits, max_score=max_score),
            [m for m, _ in per_segment],
        )

    def _execute_root(
        self,
        query: Optional[Query],
        knn_sets: List[List[Tuple[np.ndarray, np.ndarray]]],
        si: int,
        seg: Segment,
    ) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        if query is None and not knn_sets:
            query = MatchAllQuery()
        if query is not None:
            mask, scores = self._exec(query, seg)
        else:
            mask = np.zeros(n, dtype=bool)
            scores = np.zeros(n, dtype=np.float32)
        # knn winners become additional SHOULD-like exact doc/score sets
        # (KnnScoreDocQuery semantics: scores add where both match)
        for ks in knn_sets:
            kmask, kscores = ks[si]
            scores = np.where(kmask, scores + kscores, scores).astype(np.float32)
            mask = mask | kmask
        return mask, scores

    def _knn_topk_global(self, sec: KnnSection) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Per-segment knn candidates cut to the global top-k of the shard:
        per segment keep num_candidates, then keep only the k best
        (score desc, global doc asc) across all segments."""
        per_seg = [
            self._exec_knn(sec, si, seg)
            for si, seg in enumerate(self.reader.segments)
        ]
        entries = []  # (score, si, doc)
        for si, (mask, scores) in enumerate(per_seg):
            for doc in np.nonzero(mask)[0]:
                entries.append((float(scores[doc]), si, int(doc)))
        entries.sort(key=lambda t: (-t[0], t[1], t[2]))
        keep = entries[: sec.k]
        out = []
        for si, (mask, scores) in enumerate(per_seg):
            new_mask = np.zeros_like(mask)
            for s, ksi, doc in keep:
                if ksi == si:
                    new_mask[doc] = True
            out.append((new_mask, scores))
        return out

    # ---- node dispatch ----

    def _exec(self, q: Query, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        if isinstance(q, MatchAllQuery):
            return np.ones(n, bool), np.full(n, np.float32(q.boost), np.float32)
        if isinstance(q, MatchNoneQuery):
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if isinstance(q, MatchQuery):
            return self._exec_match(q, seg)
        if isinstance(q, MatchPhraseQuery):
            return self._exec_phrase(q, seg)
        if isinstance(q, TermQuery):
            return self._exec_term(q, seg)
        if isinstance(q, TermsQuery):
            return self._exec_terms(q, seg)
        if isinstance(q, RangeQuery):
            return self._exec_range(q, seg)
        if isinstance(q, ExistsQuery):
            return self._exec_exists(q, seg)
        if isinstance(q, BoolQuery):
            return self._exec_bool(q, seg)
        if isinstance(q, ConstantScoreQuery):
            m, _ = self._exec(q.filter_query, seg)
            return m, np.where(m, np.float32(q.boost), np.float32(0)).astype(np.float32)
        if isinstance(q, MultiMatchQuery):
            return self._exec_multi_match(q, seg)
        if isinstance(q, KnnQueryWrapper):
            si = self.reader.segments.index(seg)
            return self._exec_knn(q.knn, si, seg)
        raise QueryParseError(f"unsupported query node [{type(q).__name__}]")

    # ---- leaves ----

    def _score_term_dense(
        self, seg: Segment, field: str, term: str, boost: float
    ) -> Tuple[np.ndarray, np.ndarray]:
        """TermQuery scoring: dense (mask, scores) for one term."""
        n = seg.num_docs
        mask = np.zeros(n, bool)
        scores = np.zeros(n, np.float32)
        pf = seg.postings.get(field)
        if pf is None:
            return mask, scores
        tid = pf.term_id(term)
        if tid < 0:
            return mask, scores
        start = int(pf.term_tile_start[tid])
        count = int(pf.term_tile_count[tid])
        doc_rows = pf.doc_ids[start : start + count].ravel()
        tf_rows = pf.tfs[start : start + count].ravel()
        valid = doc_rows >= 0
        docs = doc_rows[valid]
        tfs = tf_rows[valid]
        mf = self.reader.mappings.get(field)
        omit_norms = mf is not None and mf.type != TEXT
        if omit_norms:
            norm_bytes = np.ones(len(docs), np.int64)
        else:
            norm_bytes = pf.norms[docs].astype(np.int64)
        weight = np.float32(boost) * np.float32(self._term_weight(field, term))
        cache = self._field_cache(field)
        s = bm25.score_freqs(tfs, norm_bytes, weight, cache)
        mask[docs] = True
        scores[docs] = s
        return mask, scores

    def _exec_match(self, q: MatchQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        mf = self.reader.mappings.get(q.field)
        n = seg.num_docs
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type != TEXT:
            # match on keyword/numeric degrades to a term query (ES behavior)
            return self._exec_term(TermQuery(field=q.field, value=q.query, boost=q.boost), seg)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        terms = [t.text for t in self.reader.analysis.get(analyzer_name).analyze(q.query)]
        if not terms:
            # analyzes to no tokens → matches nothing (MatchNoDocsQuery)
            return np.zeros(n, bool), np.zeros(n, np.float32)
        masks = []
        scores = np.zeros(n, np.float32)
        for t in terms:
            m, s = self._score_term_dense(seg, q.field, t, q.boost)
            masks.append(m)
            scores = (scores + s).astype(np.float32)
        stacked = np.stack(masks)
        if q.operator == "and":
            mask = stacked.all(axis=0)
        else:
            msm = dsl.parse_minimum_should_match(q.minimum_should_match, len(terms))
            msm = max(1, msm)
            mask = stacked.sum(axis=0) >= msm
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_phrase(self, q: MatchPhraseQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        mf = self.reader.mappings.get(q.field)
        n = seg.num_docs
        if mf is None or mf.type != TEXT:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        analyzer = self.reader.analysis.get(analyzer_name)
        qtoks = analyzer.analyze(q.query)
        terms = [t.text for t in qtoks]
        if not terms:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        # conjunction prefilter
        conj, scores = self._exec_match(
            MatchQuery(field=q.field, query=q.query, operator="and",
                       analyzer=analyzer_name, boost=q.boost),
            seg,
        )
        # position verification against re-analyzed stored source
        qpos = [t.position for t in qtoks]
        rel = [p - qpos[0] for p in qpos]
        mask = np.zeros(n, bool)
        for doc in np.nonzero(conj)[0]:
            src = seg.sources[doc] or {}
            value = _extract_field(src, q.field)
            ok = False
            for v in value:
                toks = analyzer.analyze(str(v))
                pos_of: Dict[str, List[int]] = {}
                for t in toks:
                    pos_of.setdefault(t.text, []).append(t.position)
                if _phrase_match(pos_of, terms, rel, q.slop):
                    ok = True
                    break
            mask[doc] = ok
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_term(self, q: TermQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field == "_id":
            mask = np.zeros(n, bool)
            for i, d in enumerate(seg.doc_ids):
                if d == str(q.value):
                    mask[i] = True
            return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type in (TEXT, KEYWORD):
            value = q.value
            if isinstance(value, bool):
                value = "true" if value else "false"
            return self._score_term_dense(seg, q.field, str(value), q.boost)
        # numeric/date/boolean: doc-values equality, constant score
        nf = seg.numerics.get(q.field)
        if nf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        target = _coerce_numeric(mf.type, q.value)
        mask = nf.exists & (nf.values == target)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_terms(self, q: TermsQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.zeros(n, bool)
        for v in q.values:
            m, _ = self._exec_term(TermQuery(field=q.field, value=v), seg)
            mask |= m
        # terms query is constant-scoring (boost)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_range(self, q: RangeQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type in (TEXT, KEYWORD):
            of = seg.ordinals.get(q.field)
            if of is None:
                return np.zeros(n, bool), np.zeros(n, np.float32)
            terms = of.ord_terms
            lo, hi = 0, len(terms)
            if q.gte is not None:
                lo = _bisect_left(terms, str(q.gte))
            if q.gt is not None:
                lo = max(lo, _bisect_right(terms, str(q.gt)))
            if q.lte is not None:
                hi = min(hi, _bisect_right(terms, str(q.lte)))
            if q.lt is not None:
                hi = min(hi, _bisect_left(terms, str(q.lt)))
            # multi-value: any of the doc's ordinals in [lo, hi)
            in_range = (of.mv_ords >= lo) & (of.mv_ords < hi)
            hit_counts = np.diff(np.concatenate([[0], np.cumsum(in_range)])[of.mv_offsets])
            mask = hit_counts > 0
            return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)
        nf = seg.numerics.get(q.field)
        if nf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        mask = nf.exists.copy()
        conv = (lambda v: parse_date_millis(v)) if mf.type == DATE else float
        if q.gte is not None:
            mask &= nf.values >= conv(q.gte)
        if q.gt is not None:
            mask &= nf.values > conv(q.gt)
        if q.lte is not None:
            mask &= nf.values <= conv(q.lte)
        if q.lt is not None:
            mask &= nf.values < conv(q.lt)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_exists(self, q: ExistsQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.zeros(n, bool)
        pf = seg.postings.get(q.field)
        if pf is not None:
            mask |= pf.norms > 0
        nf = seg.numerics.get(q.field)
        if nf is not None:
            mask |= nf.exists
        vf = seg.vectors.get(q.field)
        if vf is not None:
            mask |= vf.exists
        of = seg.ordinals.get(q.field)
        if of is not None:
            mask |= of.ords >= 0
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    # ---- compounds ----

    def _exec_bool(self, q: BoolQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.ones(n, bool)
        scores = np.zeros(n, np.float32)
        any_positive = bool(q.must or q.filter or q.should)
        for c in q.must:
            m, s = self._exec(c, seg)
            mask &= m
            scores = (scores + s).astype(np.float32)
        for c in q.filter:
            m, _ = self._exec(c, seg)
            mask &= m
        if q.should:
            smasks = []
            sscores = np.zeros(n, np.float32)
            for c in q.should:
                m, s = self._exec(c, seg)
                smasks.append(m)
                sscores = (sscores + np.where(m, s, 0)).astype(np.float32)
            stacked = np.stack(smasks)
            match_count = stacked.sum(axis=0)
            default_msm = 0 if (q.must or q.filter) else 1
            msm = (
                dsl.parse_minimum_should_match(q.minimum_should_match, len(q.should))
                if q.minimum_should_match is not None
                else default_msm
            )
            if msm > 0:
                mask &= match_count >= msm
            scores = (scores + np.where(match_count > 0, sscores, 0)).astype(np.float32)
        elif not any_positive:
            # only must_not: everything matches with score 0
            pass
        for c in q.must_not:
            m, _ = self._exec(c, seg)
            mask &= ~m
        if q.boost != 1.0:
            scores = (scores * np.float32(q.boost)).astype(np.float32)
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_multi_match(self, q: MultiMatchQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        fields = expand_match_fields(self.reader.mappings, q.fields)
        if not fields:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        per_field: List[Tuple[np.ndarray, np.ndarray]] = []
        for fname, fboost in fields:
            m, s = self._exec_match(
                MatchQuery(field=fname, query=q.query, operator=q.operator,
                           boost=q.boost * fboost),
                seg,
            )
            per_field.append((m, s))
        masks = np.stack([m for m, _ in per_field])
        score_mat = np.stack([s for _, s in per_field])
        mask = masks.any(axis=0)
        if q.type == "best_fields":
            best = score_mat.max(axis=0)
            if q.tie_breaker:
                rest = score_mat.sum(axis=0) - best
                total = (best + np.float32(q.tie_breaker) * rest).astype(np.float32)
            else:
                total = best
        else:  # most_fields / cross_fields (round 1: summed per-field scores)
            total = score_mat.sum(axis=0, dtype=np.float32)
        return mask, np.where(mask, total, 0).astype(np.float32)

    # ---- knn ----

    def _exec_knn(self, sec: KnnSection, si: int, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        vf = seg.vectors.get(sec.field)
        if vf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        scores = score_vectors(
            np.asarray(sec.query_vector, np.float32),
            vf.vectors,
            vf.similarity,
            vf.unit_vectors,
        )
        mask = vf.exists.copy()
        if sec.filter is not None:
            fm, _ = self._exec(sec.filter, seg)
            mask &= fm
        live = self.reader.live_docs[si]
        if live is not None:
            mask = mask & live
        if sec.similarity is not None:
            mask &= scores >= np.float32(sec.similarity)
        # per-shard: keep only top num_candidates, then top k overall
        cand = min(sec.num_candidates, int(mask.sum()))
        if cand < int(mask.sum()):
            masked = np.where(mask, scores, -np.inf)
            kth = np.partition(masked, -cand)[-cand]
            mask &= masked >= kth
        # top-level k cut happens at merge; apply boost
        out = (scores * np.float32(sec.boost)).astype(np.float32)
        return mask, np.where(mask, out, 0).astype(np.float32)


# ---- helpers ----

def expand_match_fields(mappings, patterns) -> List[Tuple[str, float]]:
    """Expands multi_match field patterns (``title^2``, ``body``, ``*``,
    ``name.*``) against the mapping's text/keyword fields — the
    QueryParserHelper.resolveMappingFields analog."""
    import fnmatch

    from ..index.mapping import KEYWORD as _KW, TEXT as _TX

    out: List[Tuple[str, float]] = []
    for f in patterns:
        boost = 1.0
        name = f
        if "^" in f:
            name, _, b = f.partition("^")
            boost = float(b)
        if "*" in name or "?" in name:
            # snapshot: concurrent dynamic mapping may grow the dict
            for fname, mf in sorted(list(mappings.fields.items())):
                if mf.type in (_TX, _KW) and fnmatch.fnmatch(fname, name):
                    out.append((fname, boost))
        else:
            out.append((name, boost))
    return out


def _extract_field(src: dict, path: str):
    node = src
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return []
    return node if isinstance(node, list) else [node]


def _phrase_match(pos_of: Dict[str, List[int]], terms: List[str], rel: List[int], slop: int) -> bool:
    """Exact phrase when slop=0: all terms at consecutive relative positions.
    Sloppy phrases use a simple window check (admits standard slop cases)."""
    first = pos_of.get(terms[0], [])
    for p0 in first:
        if slop == 0:
            if all(p0 + r in pos_of.get(t, []) for t, r in zip(terms[1:], rel[1:])):
                return True
        else:
            ok = True
            for t, r in zip(terms[1:], rel[1:]):
                cands = pos_of.get(t, [])
                if not any(abs(p - (p0 + r)) <= slop for p in cands):
                    ok = False
                    break
            if ok:
                return True
    return False


def _coerce_numeric(ftype: str, value) -> float:
    if ftype == BOOLEAN:
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        return 1.0 if value == "true" else 0.0
    if ftype == DATE:
        return parse_date_millis(value)
    return float(value)


def _bisect_left(arr: List[str], x: str) -> int:
    import bisect

    return bisect.bisect_left(arr, x)


def _bisect_right(arr: List[str], x: str) -> int:
    import bisect

    return bisect.bisect_right(arr, x)
