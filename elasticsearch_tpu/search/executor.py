"""Shard-level query execution — NumPy oracle executor.

Reference analog: the QueryPhase hot path — SearchService.executeQueryPhase
→ QueryPhase.execute → ContextIndexSearcher.search with Lucene
Weight/Scorer iterators (server/.../search/query/QueryPhase.java).

Execution model (TPU-native, shared by this oracle and the JAX executor in
ops/): every query node evaluates to a dense pair over a segment's docs —
(match_mask: bool[N], scores: float32[N]) — composed with elementwise
AND/OR/sum instead of Lucene's doc-at-a-time iterator trees. The NumPy
version is the *semantics oracle*: the JAX/Pallas path must match it
exactly (tests enforce parity), and it doubles as the measured CPU
baseline for BASELINE.md.

Lucene semantics honored here:
  - shard-level term statistics (df, ttf summed across segments, deletes
    ignored) feed idf/avgdl — as IndexSearcher collectionStatistics does;
  - fields with omitted norms (keyword) score with encodedNorm == 1;
  - bool minimum_should_match defaults: 1 when no must/filter, else 0;
  - top-k ordering is (score desc, global doc asc), global doc order =
    segment order × local doc id (Lucene docBase);
  - match_phrase is evaluated as a conjunction then position-verified
    against re-analyzed stored source (positions are not yet columnar;
    see ROADMAP).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis import AnalysisRegistry
from ..index.mapping import (
    DENSE_VECTOR,
    KEYWORD,
    TEXT,
    DATE,
    BOOLEAN,
    Mappings,
    parse_date_millis,
)
from ..index.segment import Segment
from ..models import bm25
from ..models.similarity import score_vectors
from . import dsl
from .dsl import (
    BoolQuery,
    ConstantScoreQuery,
    ExistsQuery,
    KnnQueryWrapper,
    KnnSection,
    MatchAllQuery,
    MatchNoneQuery,
    MatchPhraseQuery,
    MatchQuery,
    MultiMatchQuery,
    Query,
    QueryParseError,
    RangeQuery,
    TermQuery,
    TermsQuery,
)


@dataclass
class Hit:
    score: float
    segment: int
    local_doc: int
    doc_id: str


@dataclass
class TopDocs:
    total: int
    hits: List[Hit]
    max_score: Optional[float] = None
    # Lucene TotalHits.Relation: "eq" when total is exact, "gte" when a
    # pruned collection proved at least `total` matches (WANDScorer under
    # totalHitsThreshold)
    relation: str = "eq"


class ShardReader:
    """A point-in-time view over a shard's segments (ReaderContext analog)."""

    def __init__(
        self,
        segments: List[Segment],
        mappings: Mappings,
        analysis: AnalysisRegistry,
        live_docs: Optional[List[Optional[np.ndarray]]] = None,
    ):
        self.segments = segments
        self.mappings = mappings
        self.analysis = analysis
        self.live_docs = live_docs or [None] * len(segments)

    # ---- shard-level statistics (IndexSearcher.collectionStatistics) ----

    def field_stats(self, field: str) -> Tuple[int, int]:
        """(doc_count, sum_total_term_freq) across segments."""
        dc = 0
        ttf = 0
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is not None:
                dc += pf.stats.doc_count
                ttf += pf.stats.sum_total_term_freq
        return dc, ttf

    def term_stats(self, field: str, term: str) -> Tuple[int, int]:
        """(doc_freq, total_term_freq) across segments (deletes ignored,
        as Lucene does)."""
        df = 0
        ttf = 0
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is None:
                continue
            tid = pf.term_id(term)
            if tid >= 0:
                df += int(pf.term_df[tid])
                ttf += int(pf.term_total_tf[tid])
        return df, ttf

    def num_docs(self) -> int:
        return sum(s.num_docs for s in self.segments)


import contextvars

# global term statistics for the CURRENT request in DFS mode:
# {"fields": {field: [doc_count, sum_ttf]},
#  "terms": {field: {term: doc_freq}}}
DFS_STATS: contextvars.ContextVar = contextvars.ContextVar(
    "dfs_stats", default=None
)

# per-request device-array cache for DFS norm uploads (kept OUT of the
# DFS stats dict, which rides the wire as JSON)
DFS_NORM_CACHE: contextvars.ContextVar = contextvars.ContextVar(
    "dfs_norm_cache", default=None
)

# "profile": true phase accounting for the CURRENT request: executors
# add device_scoring_ns / device_transfer_ns / host_merge_ns entries
# (the per-kernel breakdown SURVEY §5 asks profile=true to return)
PROFILE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "profile_ctx", default=None
)


class NumpyExecutor:
    """The oracle: executes a query tree densely per segment."""

    def __init__(self, reader: ShardReader, k1: float = bm25.DEFAULT_K1, b: float = bm25.DEFAULT_B):
        self.reader = reader
        self.k1 = k1
        self.b = b
        self._weight_cache: Dict[Tuple[str, str], float] = {}
        self._norm_cache: Dict[str, np.ndarray] = {}
        # filter-bitset cache identity; None (executors constructed
        # outside IndexService) disables the node-level cache
        self.cache_ctx = None
        self._seg_index = {id(s): i for i, s in enumerate(reader.segments)}

    # ---- filter-context evaluation via the node-level bitset cache ----

    def filter_mask(self, q: Query, seg: Segment) -> np.ndarray:
        """Match mask of one filter-context clause on one segment,
        reusing the node-level bitset cache (LRUQueryCache analog; host
        entries are np.packbits bitmaps). Falls back to direct
        evaluation when uncached/uncacheable — bit-identical either way
        (filter context ignores scores)."""
        ctx = self.cache_ctx
        if ctx is None or not dsl.is_cacheable_filter(q):
            return self._exec(q, seg)[0]
        from .query_cache import filter_cache

        si = self._seg_index.get(id(seg))
        if si is None:
            return self._exec(q, seg)[0]
        fkey = dsl.canonical_key(q)
        packed = filter_cache.get(ctx, si, fkey)
        if packed is not None:
            return np.unpackbits(packed, count=seg.num_docs).astype(bool)
        mask = self._exec(q, seg)[0]
        bits = np.packbits(mask.astype(np.uint8))
        filter_cache.put(ctx, si, fkey, bits, int(bits.nbytes))
        return mask

    # ---- term weight / norm cache (BM25Similarity.scorer) ----
    #
    # DFS mode (search_type=dfs_query_then_fetch): the coordinator's
    # aggregated cross-shard statistics ride a request-scoped context
    # variable (DFS_STATS) and override the shard-local stats WITHOUT
    # touching the per-executor caches (SearchPhaseController
    # .aggregateDfs feeding Weight creation, SURVEY §2.1 DFS row).

    def _field_cache(self, field: str) -> np.ndarray:
        dfs = DFS_STATS.get()
        if dfs is not None and field in dfs.get("fields", {}):
            dc, ttf = dfs["fields"][field]
            avgdl = bm25.avg_field_length(ttf, dc)
            return bm25.norm_inverse_cache(avgdl, self.k1, self.b)
        cache = self._norm_cache.get(field)
        if cache is None:
            dc, ttf = self.reader.field_stats(field)
            avgdl = bm25.avg_field_length(ttf, dc)
            cache = bm25.norm_inverse_cache(avgdl, self.k1, self.b)
            self._norm_cache[field] = cache
        return cache

    def _term_weight(self, field: str, term: str) -> float:
        dfs = DFS_STATS.get()
        if dfs is not None and field in dfs.get("fields", {}):
            df = dfs.get("terms", {}).get(field, {}).get(term)
            if df is not None:
                dc, _ = dfs["fields"][field]
                return float(bm25.idf(dc, df)) if df > 0 else 0.0
            # a term the DFS walker missed (analyzer edge) falls back to
            # shard-local stats rather than silently scoring 0
        key = (field, term)
        w = self._weight_cache.get(key)
        if w is None:
            df, _ = self.reader.term_stats(field, term)
            dc, _ = self.reader.field_stats(field)
            w = float(bm25.idf(dc, df)) if df > 0 else 0.0
            self._weight_cache[key] = w
        return w

    # ---- entry point ----

    def search(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> TopDocs:
        return self.execute(query, size, from_, knn, min_score)[0]

    def execute(
        self,
        query: Optional[Query],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
    ) -> Tuple[TopDocs, List[np.ndarray]]:
        """(TopDocs, per-segment match masks) — masks feed the agg phase
        so query execution isn't paid twice."""
        # knn sections: per-segment candidates, then a *global* top-k cut
        # across segments (SearchPhaseController.mergeKnnResults semantics)
        knn_sets = [self._knn_topk_global(sec) for sec in (knn or [])]
        per_segment: List[Tuple[np.ndarray, np.ndarray]] = []
        for si, seg in enumerate(self.reader.segments):
            mask, scores = self._execute_root(query, knn_sets, si, seg)
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & live
            if min_score is not None:
                mask = mask & (scores >= min_score)
            per_segment.append((mask, scores))

        total = int(sum(m.sum() for m, _ in per_segment))
        # global collection: (score desc, global doc asc)
        all_scores = []
        all_keys = []
        for si, (mask, scores) in enumerate(per_segment):
            idx = np.nonzero(mask)[0]
            all_scores.append(scores[idx])
            all_keys.append([(si, int(i)) for i in idx])
        if all_scores:
            flat_scores = np.concatenate(all_scores)
        else:
            flat_scores = np.zeros(0, np.float32)
        flat_keys = [k for ks in all_keys for k in ks]
        order = sorted(
            range(len(flat_keys)), key=lambda i: (-float(flat_scores[i]), flat_keys[i])
        )
        top = order[from_ : from_ + size]
        hits = [
            Hit(
                score=float(flat_scores[i]),
                segment=flat_keys[i][0],
                local_doc=flat_keys[i][1],
                doc_id=self.reader.segments[flat_keys[i][0]].doc_ids[flat_keys[i][1]],
            )
            for i in top
        ]
        max_score = float(flat_scores.max()) if len(flat_scores) else None
        return (
            TopDocs(total=total, hits=hits, max_score=max_score),
            [m for m, _ in per_segment],
        )

    def execute_sorted(
        self,
        query: Optional[Query],
        sort_specs: List[dict],
        size: int = 10,
        from_: int = 0,
        knn: Optional[List[KnnSection]] = None,
        min_score: Optional[float] = None,
        search_after: Optional[List] = None,
    ) -> Tuple[TopDocs, List[np.ndarray], List[List]]:
        """Field-sorted collection (FieldSortBuilder / SortField analog).

        Returns (TopDocs, masks, sort_values per hit). Sort keys: field
        doc values (numeric/date/boolean/keyword), _score, _doc; missing
        values follow the `missing` policy (_last default)."""
        knn_sets = [self._knn_topk_global(sec) for sec in (knn or [])]
        per_segment = []
        for si, seg in enumerate(self.reader.segments):
            mask, scores = self._execute_root(query, knn_sets, si, seg)
            live = self.reader.live_docs[si]
            if live is not None:
                mask = mask & live
            if min_score is not None:
                mask = mask & (scores >= min_score)
            per_segment.append((mask, scores))
        total = int(sum(m.sum() for m, _ in per_segment))

        cand_rows: List[np.ndarray] = []  # per key: concatenated arrays
        seg_idx: List[np.ndarray] = []
        doc_idx: List[np.ndarray] = []
        score_arr: List[np.ndarray] = []
        key_cols: List[List[np.ndarray]] = [[] for _ in sort_specs]
        raw_cols: List[List[np.ndarray]] = [[] for _ in sort_specs]
        doc_base = 0
        for si, (mask, scores) in enumerate(per_segment):
            seg = self.reader.segments[si]
            seg_base, doc_base = doc_base, doc_base + seg.num_docs
            idx = np.nonzero(mask)[0]
            if not len(idx):
                continue
            seg_idx.append(np.full(len(idx), si))
            doc_idx.append(idx)
            score_arr.append(scores[idx])
            for ki, spec in enumerate(sort_specs):
                sort_key, raw = _sort_key_values(
                    spec, seg, idx, scores[idx], self.reader.mappings, seg_base
                )
                if sort_key is None:  # string column: rank globally below
                    sort_key = np.zeros(0)
                key_cols[ki].append(sort_key)
                raw_cols[ki].append(raw)
        if not seg_idx:
            return TopDocs(total=total, hits=[], max_score=None), [
                m for m, _ in per_segment
            ], []
        segs = np.concatenate(seg_idx)
        docs = np.concatenate(doc_idx)
        scrs = np.concatenate(score_arr)
        raws = [np.concatenate(c) for c in raw_cols]
        keys = []
        after_keys = []
        for ki, spec in enumerate(sort_specs):
            cols = key_cols[ki]
            after_v = search_after[ki] if search_after is not None else None
            if any(len(c) == 0 for c in cols):
                key, ak = _rank_strings(raws[ki], spec, after_v)
            else:
                key = np.concatenate(cols)
                ak = _numeric_after_key(after_v, spec)
            keys.append(key)
            after_keys.append(ak)
        if search_after is not None:
            # keep only docs strictly after the cursor in key space
            # (SearchAfterBuilder: the cursor is the last hit's sort values)
            gt = np.zeros(len(segs), bool)
            eq = np.ones(len(segs), bool)
            for ki, ak in enumerate(after_keys):
                col = keys[ki]
                gt |= eq & (col > ak)
                eq &= col == ak
            mask_after = gt  # strictly greater (ties skipped, as ES does
            # when the tiebreak column is included in the sort)
            segs, docs, scrs = segs[mask_after], docs[mask_after], scrs[mask_after]
            keys = [k[mask_after] for k in keys]
            raws = [r[mask_after] for r in raws]
            if not len(segs):
                return (
                    TopDocs(total=total, hits=[], max_score=None),
                    [m for m, _ in per_segment],
                    [],
                )
        # lexsort: last key is primary → reverse; tiebreak (seg, doc)
        order = np.lexsort(tuple([docs, segs] + keys[::-1]))
        top = order[from_ : from_ + size]
        hits = [
            Hit(
                score=float(scrs[i]),
                segment=int(segs[i]),
                local_doc=int(docs[i]),
                doc_id=self.reader.segments[int(segs[i])].doc_ids[int(docs[i])],
            )
            for i in top
        ]
        sort_values = [[_to_jsonable(raws[ki][i]) for ki in range(len(sort_specs))] for i in top]
        return (
            TopDocs(total=total, hits=hits, max_score=None),
            [m for m, _ in per_segment],
            sort_values,
        )

    def _execute_root(
        self,
        query: Optional[Query],
        knn_sets: List[List[Tuple[np.ndarray, np.ndarray]]],
        si: int,
        seg: Segment,
    ) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        if query is None and not knn_sets:
            query = MatchAllQuery()
        if query is not None:
            mask, scores = self._exec(query, seg)
        else:
            mask = np.zeros(n, dtype=bool)
            scores = np.zeros(n, dtype=np.float32)
        # knn winners become additional SHOULD-like exact doc/score sets
        # (KnnScoreDocQuery semantics: scores add where both match)
        for ks in knn_sets:
            kmask, kscores = ks[si]
            scores = np.where(kmask, scores + kscores, scores).astype(np.float32)
            mask = mask | kmask
        return mask, scores

    def _knn_topk_global(self, sec: KnnSection) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Per-segment knn candidates cut to the global top-k of the shard:
        per segment keep num_candidates, then keep only the k best
        (score desc, global doc asc) across all segments."""
        per_seg = [
            self._exec_knn(sec, si, seg)
            for si, seg in enumerate(self.reader.segments)
        ]
        entries = []  # (score, si, doc)
        for si, (mask, scores) in enumerate(per_seg):
            for doc in np.nonzero(mask)[0]:
                entries.append((float(scores[doc]), si, int(doc)))
        entries.sort(key=lambda t: (-t[0], t[1], t[2]))
        keep = entries[: sec.k]
        out = []
        for si, (mask, scores) in enumerate(per_seg):
            new_mask = np.zeros_like(mask)
            for s, ksi, doc in keep:
                if ksi == si:
                    new_mask[doc] = True
            out.append((new_mask, scores))
        return out

    # ---- node dispatch ----

    def _exec(self, q: Query, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        if isinstance(q, MatchAllQuery):
            return np.ones(n, bool), np.full(n, np.float32(q.boost), np.float32)
        if isinstance(q, MatchNoneQuery):
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if isinstance(q, MatchQuery):
            return self._exec_match(q, seg)
        if isinstance(q, MatchPhraseQuery):
            return self._exec_phrase(q, seg)
        if isinstance(q, TermQuery):
            return self._exec_term(q, seg)
        if isinstance(q, TermsQuery):
            return self._exec_terms(q, seg)
        if isinstance(q, RangeQuery):
            return self._exec_range(q, seg)
        if isinstance(q, ExistsQuery):
            return self._exec_exists(q, seg)
        if isinstance(q, BoolQuery):
            return self._exec_bool(q, seg)
        if isinstance(q, ConstantScoreQuery):
            m = self.filter_mask(q.filter_query, seg)
            return m, np.where(m, np.float32(q.boost), np.float32(0)).astype(np.float32)
        if isinstance(q, MultiMatchQuery):
            return self._exec_multi_match(q, seg)
        if isinstance(q, KnnQueryWrapper):
            si = self.reader.segments.index(seg)
            return self._exec_knn(q.knn, si, seg)
        if isinstance(q, dsl.SparseVectorQuery):
            return self._exec_sparse(q, seg)
        if isinstance(q, dsl.IdsQuery):
            return self._exec_ids(q, seg)
        if isinstance(q, (dsl.PrefixQuery, dsl.WildcardQuery, dsl.RegexpQuery)):
            return self._exec_pattern(q, seg)
        if isinstance(q, dsl.FuzzyQuery):
            return self._exec_fuzzy(q, seg)
        if isinstance(q, dsl.DisMaxQuery):
            return self._exec_dis_max(q, seg)
        if isinstance(q, dsl.BoostingQuery):
            return self._exec_boosting(q, seg)
        if isinstance(q, dsl.FunctionScoreQuery):
            return self._exec_function_score(q, seg)
        if isinstance(q, dsl.MatchPhrasePrefixQuery):
            return self._exec_match_phrase_prefix(q, seg)
        if isinstance(q, dsl.SpanTermQuery):
            return self._score_term_dense(seg, q.field, q.value, q.boost)
        if isinstance(q, dsl.SpanNearQuery):
            return self._exec_span_near(q, seg)
        if isinstance(q, dsl.MoreLikeThisQuery):
            return self._exec(self._rewrite_mlt(q), seg)
        if isinstance(q, dsl.GeoDistanceQuery):
            return self._exec_geo_distance(q, seg)
        if isinstance(q, dsl.GeoBoundingBoxQuery):
            return self._exec_geo_bbox(q, seg)
        if isinstance(q, dsl.NestedQuery):
            return self._exec_nested(q, seg)
        if isinstance(q, dsl.PercolateQuery):
            return self._exec_percolate(q, seg)
        if isinstance(q, dsl.ScriptScoreQuery):
            return self._exec_script_score(q, seg)
        if isinstance(q, dsl.ScriptQuery):
            return self._exec_script_query(q, seg)
        if isinstance(q, dsl.QueryStringQuery):
            return self._exec(rewrite_query_string(q, self.reader.mappings), seg)
        raise QueryParseError(f"unsupported query node [{type(q).__name__}]")

    # ---- expanded / compound leaves ----

    def _exec_ids(self, q: "dsl.IdsQuery", seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        wanted = set(q.values)
        mask = np.fromiter(
            (d in wanted for d in seg.doc_ids), bool, count=n
        ) if n else np.zeros(0, bool)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _expand_terms(self, q, seg: Segment) -> List[str]:
        """MultiTermQuery rewrite: expand the pattern against the sorted
        term dictionary (constant-score rewrite, the ES default)."""
        import bisect
        import fnmatch
        import re as _re

        pf = seg.postings.get(q.field)
        if pf is None:
            return []
        terms = pf.terms
        value = q.value.lower() if q.case_insensitive else q.value
        if isinstance(q, dsl.PrefixQuery):
            if q.case_insensitive:
                return [t for t in terms if t.lower().startswith(value)]
            # scan forward from the insertion point: O(matches), and no
            # sentinel-character upper bound to miss astral-plane terms
            lo = bisect.bisect_left(terms, value)
            out = []
            for i in range(lo, len(terms)):
                if not terms[i].startswith(value):
                    break
                out.append(terms[i])
            return out
        if isinstance(q, dsl.WildcardQuery):
            rx = _re.compile(
                fnmatch.translate(value), _re.IGNORECASE if q.case_insensitive else 0
            )
            return [t for t in terms if rx.match(t)]
        # regexp: Lucene anchors the pattern to the whole term
        flags = _re.IGNORECASE if q.case_insensitive else 0
        try:
            rx = _re.compile(q.value, flags)
        except _re.error as e:
            raise QueryParseError(f"invalid regexp [{q.value}]: {e}")
        return [t for t in terms if rx.fullmatch(t)]

    def _exec_pattern(self, q, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        matched = self._expand_terms(q, seg)
        mask = np.zeros(n, bool)
        for t in matched:
            m, _ = self._score_term_dense(seg, q.field, t, 1.0)
            mask |= m
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _fuzzy_terms(self, q: "dsl.FuzzyQuery", seg: Segment) -> List[str]:
        """FuzzyQuery expansion against the term dictionary (bounded by
        max_expansions, Lucene FuzzyTermsEnum semantics)."""
        pf = seg.postings.get(q.field)
        if pf is None:
            return []
        max_edits = _fuzziness_edits(q.fuzziness, q.value)
        prefix = q.value[: q.prefix_length]
        cands: List[str] = []
        for t in pf.terms:
            if abs(len(t) - len(q.value)) > max_edits:
                continue
            if prefix and not t.startswith(prefix):
                continue
            if _levenshtein_at_most(q.value, t, max_edits):
                cands.append(t)
                if len(cands) >= q.max_expansions:
                    break
        return cands

    def _exec_fuzzy(self, q: "dsl.FuzzyQuery", seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        cands = self._fuzzy_terms(q, seg)
        mask = np.zeros(n, bool)
        for t in cands:
            m, _ = self._score_term_dense(seg, q.field, t, 1.0)
            mask |= m
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_dis_max(self, q: "dsl.DisMaxQuery", seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        masks, scores = [], []
        for sub in q.queries:
            m, s = self._exec(sub, seg)
            masks.append(m)
            scores.append(np.where(m, s, 0))
        mask = np.any(masks, axis=0)
        mat = np.stack(scores)
        best = mat.max(axis=0)
        total = best + np.float32(q.tie_breaker) * (mat.sum(axis=0) - best)
        total = (total * np.float32(q.boost)).astype(np.float32)
        return mask, np.where(mask, total, 0).astype(np.float32)

    def _exec_boosting(self, q: "dsl.BoostingQuery", seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        pm, ps = self._exec(q.positive, seg)
        nm, _ = self._exec(q.negative, seg)
        scores = np.where(nm, ps * np.float32(q.negative_boost), ps)
        scores = (scores * np.float32(q.boost)).astype(np.float32)
        return pm, np.where(pm, scores, 0).astype(np.float32)

    def _exec_match_phrase_prefix(
        self, q: "dsl.MatchPhrasePrefixQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Phrase with the LAST term prefix-expanded (max_expansions);
        each expansion is position-verified like match_phrase; a doc's
        score is the best matching expansion's conjunction score."""
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None or mf.type != TEXT:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        toks = self.reader.analysis.get(analyzer_name).analyze(q.query)
        terms = [t.text for t in toks]
        if not terms:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        pf = seg.postings.get(q.field)
        if pf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        expansions = self._expand_terms(
            dsl.PrefixQuery(field=q.field, value=terms[-1]), seg
        )[: q.max_expansions]
        if not expansions:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        qpos = [t.position for t in toks]
        rel = [p - qpos[0] for p in qpos]
        fixed = terms[:-1]
        total_mask = np.zeros(n, bool)
        total_scores = np.zeros(n, np.float32)
        for exp in expansions:
            full = fixed + [exp]
            conj = np.ones(n, bool)
            sc = np.zeros(n, np.float32)
            for t in full:
                m, s = self._score_term_dense(seg, q.field, t, q.boost)
                conj &= m
                sc = (sc + np.where(m, s, 0)).astype(np.float32)
            cand = np.nonzero(conj)[0]
            if not len(cand):
                continue
            vmask = np.zeros(n, bool)
            if len(full) == 1:
                vmask[cand] = True
            elif pf.has_positions:
                tids = [pf.term_id(t) for t in full]
                for doc in cand:
                    pos_of: Dict[str, List[int]] = {}
                    ok = True
                    for t, tid in zip(full, tids):
                        if t in pos_of:
                            continue
                        ps = (
                            pf.doc_positions(tid, int(doc))
                            if tid >= 0
                            else None
                        )
                        if ps is None:
                            ok = False
                            break
                        pos_of[t] = ps.tolist()
                    vmask[doc] = ok and _phrase_match(
                        pos_of, full, rel, q.slop
                    )
            else:
                # positionless segment: conjunction approximation
                vmask[cand] = True
            total_mask |= vmask
            total_scores = np.maximum(
                total_scores, np.where(vmask, sc, 0)
            ).astype(np.float32)
        return total_mask, np.where(total_mask, total_scores, 0).astype(
            np.float32
        )

    def _exec_span_near(
        self, q: "dsl.SpanNearQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """span_near over span_terms: a doc matches when one position
        per clause can be chosen whose total span fits within slop
        (in_order optionally enforces clause order). Scores sum the
        clause term scores (SpanWeight's simpler sloppy-freq scoring is
        approximated; documented)."""
        n = seg.num_docs
        field = q.clauses[0].field if q.clauses else ""
        pf = seg.postings.get(field)
        if pf is None or not pf.has_positions:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        terms = [c.value for c in q.clauses]
        conj = np.ones(n, bool)
        sc = np.zeros(n, np.float32)
        for t in terms:
            m, s = self._score_term_dense(seg, field, t, q.boost)
            conj &= m
            sc = (sc + np.where(m, s, 0)).astype(np.float32)
        tids = [pf.term_id(t) for t in terms]
        if any(tid < 0 for tid in tids):
            return np.zeros(n, bool), np.zeros(n, np.float32)
        mask = np.zeros(n, bool)
        k = len(terms)
        for doc in np.nonzero(conj)[0]:
            plists = [pf.doc_positions(tid, int(doc)) for tid in tids]
            if any(p is None for p in plists):
                continue
            mask[doc] = _span_near_match(
                [p.tolist() for p in plists], q.slop, q.in_order, k
            )
        return mask, np.where(mask, sc, 0).astype(np.float32)

    def _rewrite_mlt(self, q: "dsl.MoreLikeThisQuery") -> "dsl.BoolQuery":
        """MLT → should-bool of the top tf-idf 'interesting' terms from
        the liked texts/docs (MoreLikeThisQuery.createQuery)."""
        fields = list(q.fields)
        if not fields:
            fields = [
                f.name
                for f in self.reader.mappings.fields.values()
                if f.type == TEXT and "." not in f.name
            ]
        tf: Dict[Tuple[str, str], int] = {}
        exclude_ids: List[str] = []
        for like in q.like:
            if isinstance(like, dict):
                doc_id = like.get("_id")
                if doc_id is None:
                    continue
                exclude_ids.append(str(doc_id))
                src = None
                for seg in self.reader.segments:
                    try:
                        loc = seg.doc_ids.index(str(doc_id))
                        src = seg.sources[loc]
                        break
                    except ValueError:
                        continue
                if src is None:
                    continue
                for f in fields:
                    for v in _extract_field(src, f):
                        self._mlt_count(f, str(v), tf)
            else:
                for f in fields:
                    self._mlt_count(f, str(like), tf)
        scored = []
        for (f, term), freq in tf.items():
            if freq < q.min_term_freq:
                continue
            df, _ = self.reader.term_stats(f, term)
            if df < q.min_doc_freq:
                continue
            dc, _ = self.reader.field_stats(f)
            idf = float(bm25.idf(dc, df)) if df > 0 else 0.0
            scored.append((freq * idf, f, term))
        scored.sort(key=lambda x: (-x[0], x[1], x[2]))
        should: List[dsl.Query] = [
            dsl.TermQuery(field=f, value=t)
            for _, f, t in scored[: q.max_query_terms]
        ]
        must_not: List[dsl.Query] = (
            [dsl.IdsQuery(values=exclude_ids)] if exclude_ids else []
        )
        return dsl.BoolQuery(
            should=should or [dsl.MatchNoneQuery()],
            must_not=must_not,
            minimum_should_match=q.minimum_should_match,
            boost=q.boost,
        )

    def _mlt_count(self, field: str, text: str, tf: Dict[Tuple[str, str], int]):
        for t in search_field_terms(
            self.reader.mappings, self.reader.analysis, field, text
        ):
            tf[(field, t)] = tf.get((field, t), 0) + 1

    def _geo_columns(self, seg: Segment, field: str):
        lat = seg.numerics.get(f"{field}.lat")
        lon = seg.numerics.get(f"{field}.lon")
        if lat is None or lon is None:
            n = seg.num_docs
            z = np.zeros(n)
            return z, z, np.zeros(n, bool)
        return lat.values, lon.values, lat.exists & lon.exists

    def _exec_geo_distance(
        self, q: "dsl.GeoDistanceQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        lat, lon, have = self._geo_columns(seg, q.field)
        dist = _haversine_m(q.lat, q.lon, lat, lon)
        mask = have & (dist <= q.distance_m)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_geo_bbox(
        self, q: "dsl.GeoBoundingBoxQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        lat, lon, have = self._geo_columns(seg, q.field)
        lat_ok = (lat <= q.top) & (lat >= q.bottom)
        if q.left <= q.right:
            lon_ok = (lon >= q.left) & (lon <= q.right)
        else:  # dateline-crossing box
            lon_ok = (lon >= q.left) | (lon <= q.right)
        mask = have & lat_ok & lon_ok
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_nested(
        self, q: "dsl.NestedQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """nested: the inner query must hold within ONE object of the
        nested array (per-doc _source evaluation — the semantics the
        reference realizes with hidden child docs). Constant score."""
        n = seg.num_docs
        mask = np.zeros(n, bool)
        for d in range(n):
            src = seg.sources[d]
            if src is None:
                continue
            objs = _nested_objects(src, q.path)
            for obj in objs:
                if self._nested_obj_match(obj, q.query, q.path):
                    mask[d] = True
                    break
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _nested_obj_match(self, obj: dict, spec: dict, path: str) -> bool:
        if not isinstance(spec, dict) or len(spec) != 1:
            raise QueryParseError("[nested] inner query malformed")
        kind, params = next(iter(spec.items()))

        def rel_value(field: str):
            rel = field[len(path) + 1:] if field.startswith(path + ".") else field
            node: Any = obj
            for part in rel.split("."):
                node = node.get(part) if isinstance(node, dict) else None
                if node is None:
                    return []
            return node if isinstance(node, list) else [node]

        def analyzed_terms(field: str, text: str) -> List[str]:
            return search_field_terms(
                self.reader.mappings, self.reader.analysis, field, text
            )

        if kind == "bool":
            musts = params.get("must", [])
            shoulds = params.get("should", [])
            must_nots = params.get("must_not", [])
            filters = params.get("filter", [])
            if any(
                not self._nested_obj_match(obj, c, path)
                for c in list(musts) + list(filters)
            ):
                return False
            if any(self._nested_obj_match(obj, c, path) for c in must_nots):
                return False
            if shoulds and not (musts or filters):
                return any(
                    self._nested_obj_match(obj, c, path) for c in shoulds
                )
            return True
        if kind in ("term", "match"):
            field, spec2 = next(iter(params.items()))
            want = (
                spec2.get("value" if kind == "term" else "query")
                if isinstance(spec2, dict)
                else spec2
            )
            vals = rel_value(field)
            if kind == "term":
                return any(str(v) == str(want) for v in vals)
            qterms = set(analyzed_terms(field, str(want)))
            for v in vals:
                if qterms & set(analyzed_terms(field, str(v))):
                    return True
            return False
        if kind == "terms":
            field, wants = next(iter(params.items()))
            vals = {str(v) for v in rel_value(field)}
            return any(str(w) in vals for w in wants)
        if kind == "range":
            field, cond = next(iter(params.items()))
            for v in rel_value(field):
                try:
                    x = float(v)
                except (TypeError, ValueError):
                    continue
                ok = True
                if "gte" in cond and not x >= float(cond["gte"]):
                    ok = False
                if "gt" in cond and not x > float(cond["gt"]):
                    ok = False
                if "lte" in cond and not x <= float(cond["lte"]):
                    ok = False
                if "lt" in cond and not x < float(cond["lt"]):
                    ok = False
                if ok:
                    return True
            return False
        if kind == "exists":
            return bool(rel_value(params.get("field", "")))
        raise QueryParseError(
            f"[nested] unsupported inner query [{kind}] (this build "
            "supports bool/term/match/terms/range/exists)"
        )

    def _exec_percolate(
        self, q: "dsl.PercolateQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """percolate: a stored-query doc matches when its query matches
        ANY of the provided documents. The candidate documents are
        indexed once into a scratch single-doc-per-entry reader (the
        percolator's MemoryIndex analog) and every stored query executes
        against it."""
        n = seg.num_docs
        mask = np.zeros(n, bool)
        doc_ex = getattr(q, "_doc_executor", None)
        if doc_ex is None:
            from ..index.engine import ShardEngine
            from ..index.mapping import Mappings

            # a COPY of the mappings: dynamic-mapping the candidate
            # doc's fields must never mutate the live index mapping
            scratch_mappings = Mappings(self.reader.mappings.to_json())
            scratch = ShardEngine(scratch_mappings, self.reader.analysis)
            for i, doc in enumerate(q.documents):
                scratch.index(f"_percolate_{i}", doc)
            scratch.refresh()
            doc_ex = NumpyExecutor(scratch.reader(), self.k1, self.b)
            # memoized on the (per-request) query node: every segment of
            # every shard reuses the one scratch index
            q._doc_executor = doc_ex
        parsed_cache = getattr(q, "_parsed_cache", None)
        if parsed_cache is None:
            parsed_cache = {}
            q._parsed_cache = parsed_cache
        for d in range(n):
            src = seg.sources[d]
            if src is None:
                continue
            stored_vals = [
                v for v in _extract_field(src, q.field) if isinstance(v, dict)
            ]
            if not stored_vals:
                continue
            stored = stored_vals[0]
            key = id(src)
            node = parsed_cache.get(key)
            if node is None:
                try:
                    node = dsl.parse_query(stored)
                except dsl.QueryParseError:
                    continue  # index-time validation makes this rare
                parsed_cache[key] = node
            td = doc_ex.search(node, size=1)
            mask[d] = td.total > 0
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_script_score(
        self, q: "dsl.ScriptScoreQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """ScriptScoreQuery: the script runs per matching doc with
        doc-value + vector-function bindings (host-side, exactly where
        the reference runs painless)."""
        from ..script import ScriptError, script_service

        mask, base = self._exec(q.query, seg)
        scores = np.zeros(seg.num_docs, np.float32)
        try:
            for d in np.nonzero(mask)[0]:
                scores[d] = script_service.run_score(
                    q.script,
                    _source_field_lookup(seg, int(d)),
                    score=float(base[d]),
                )
        except ScriptError as e:
            raise QueryParseError(str(e))
        if q.min_score is not None:
            mask = mask & (scores >= np.float32(q.min_score))
        scores = (scores * np.float32(q.boost)).astype(np.float32)
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_script_query(
        self, q: "dsl.ScriptQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        from ..script import ScriptError, script_service

        n = seg.num_docs
        mask = np.zeros(n, bool)
        try:
            for d in range(n):
                mask[d] = script_service.run_filter(
                    q.script, _source_field_lookup(seg, d)
                )
        except ScriptError as e:
            raise QueryParseError(str(e))
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_function_score(
        self, q: "dsl.FunctionScoreQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask, base = self._exec(q.query, seg)
        fvals: List[np.ndarray] = []
        for fn in q.functions:
            if fn.filter is not None:
                fmask, _ = self._exec(fn.filter, seg)
            else:
                fmask = np.ones(n, bool)
            val = np.ones(n, np.float32)
            if fn.field_value_factor is not None:
                val = _field_value_factor(fn.field_value_factor, seg)
            elif fn.random_score is not None:
                seed = fn.random_score.get("seed", 0)
                val = np.asarray(
                    [_stable_random(seed, d) for d in seg.doc_ids], np.float32
                ) if n else np.zeros(0, np.float32)
            elif fn.script_score is not None:
                from ..script import ScriptError, script_service

                script = fn.script_score.get("script")
                val = np.zeros(n, np.float32)
                try:
                    for d in np.nonzero(fmask & mask)[0]:
                        val[d] = script_service.run_score(
                            script,
                            _source_field_lookup(seg, int(d)),
                            score=float(base[d]),
                        )
                except ScriptError as e:
                    raise QueryParseError(str(e))
            if fn.weight is not None:
                val = val * np.float32(fn.weight)
            # functions only apply where their filter matches; identity
            # elsewhere depends on score_mode (multiply→1, sum→0)
            fvals.append(np.where(fmask, val, np.nan))
        if fvals:
            mat = np.stack(fvals)
            present = ~np.isnan(mat)
            any_fn = present.any(axis=0)
            zed = np.where(present, mat, 0.0)
            if q.score_mode == "multiply":
                combined = np.where(present, mat, 1.0).prod(axis=0)
            elif q.score_mode == "sum":
                combined = zed.sum(axis=0)
            elif q.score_mode == "avg":
                cnt = np.maximum(present.sum(axis=0), 1)
                combined = zed.sum(axis=0) / cnt
            elif q.score_mode == "max":
                combined = np.where(present, mat, -np.inf).max(axis=0)
            elif q.score_mode == "min":
                combined = np.where(present, mat, np.inf).min(axis=0)
            elif q.score_mode == "first":
                first_idx = present.argmax(axis=0)
                combined = mat[first_idx, np.arange(n)]
            else:
                raise QueryParseError(f"unknown score_mode [{q.score_mode}]")
            combined = np.where(any_fn, combined, 1.0).astype(np.float32)
            if q.max_boost is not None:
                combined = np.minimum(combined, np.float32(q.max_boost))
            bm = q.boost_mode
            if bm == "multiply":
                scores = base * combined
            elif bm == "sum":
                scores = base + combined
            elif bm == "replace":
                scores = combined
            elif bm == "avg":
                scores = (base + combined) / 2
            elif bm == "max":
                scores = np.maximum(base, combined)
            elif bm == "min":
                scores = np.minimum(base, combined)
            else:
                raise QueryParseError(f"unknown boost_mode [{bm}]")
        else:
            scores = base
        scores = (scores * np.float32(q.boost)).astype(np.float32)
        if q.min_score is not None:
            mask = mask & (scores >= np.float32(q.min_score))
        return mask, np.where(mask, scores, 0).astype(np.float32)

    # ---- leaves ----

    def _score_term_dense(
        self, seg: Segment, field: str, term: str, boost: float
    ) -> Tuple[np.ndarray, np.ndarray]:
        """TermQuery scoring: dense (mask, scores) for one term."""
        n = seg.num_docs
        mask = np.zeros(n, bool)
        scores = np.zeros(n, np.float32)
        pf = seg.postings.get(field)
        if pf is None:
            return mask, scores
        tid = pf.term_id(term)
        if tid < 0:
            return mask, scores
        start = int(pf.term_tile_start[tid])
        count = int(pf.term_tile_count[tid])
        doc_rows = pf.doc_ids[start : start + count].ravel()
        tf_rows = pf.tfs[start : start + count].ravel()
        valid = doc_rows >= 0
        docs = doc_rows[valid]
        tfs = tf_rows[valid]
        mf = self.reader.mappings.get(field)
        omit_norms = mf is not None and mf.type != TEXT
        if omit_norms:
            norm_bytes = np.ones(len(docs), np.int64)
        else:
            norm_bytes = pf.norms[docs].astype(np.int64)
        weight = np.float32(boost) * np.float32(self._term_weight(field, term))
        cache = self._field_cache(field)
        s = bm25.score_freqs(tfs, norm_bytes, weight, cache)
        mask[docs] = True
        scores[docs] = s
        return mask, scores

    def _exec_match(self, q: MatchQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        mf = self.reader.mappings.get(q.field)
        n = seg.num_docs
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type != TEXT:
            # match on keyword/numeric degrades to a term query (ES behavior)
            return self._exec_term(TermQuery(field=q.field, value=q.query, boost=q.boost), seg)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        terms = [t.text for t in self.reader.analysis.get(analyzer_name).analyze(q.query)]
        if not terms:
            # analyzes to no tokens → matches nothing (MatchNoDocsQuery)
            return np.zeros(n, bool), np.zeros(n, np.float32)
        masks = []
        scores = np.zeros(n, np.float32)
        for t in terms:
            m, s = self._score_term_dense(seg, q.field, t, q.boost)
            masks.append(m)
            scores = (scores + s).astype(np.float32)
        stacked = np.stack(masks)
        if q.operator == "and":
            mask = stacked.all(axis=0)
        else:
            msm = dsl.parse_minimum_should_match(q.minimum_should_match, len(terms))
            msm = max(1, msm)
            mask = stacked.sum(axis=0) >= msm
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_phrase(self, q: MatchPhraseQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        mf = self.reader.mappings.get(q.field)
        n = seg.num_docs
        if mf is None or mf.type != TEXT:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        analyzer_name = q.analyzer or mf.search_analyzer or mf.analyzer
        analyzer = self.reader.analysis.get(analyzer_name)
        qtoks = analyzer.analyze(q.query)
        terms = [t.text for t in qtoks]
        if not terms:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        # conjunction prefilter
        conj, scores = self._exec_match(
            MatchQuery(field=q.field, query=q.query, operator="and",
                       analyzer=analyzer_name, boost=q.boost),
            seg,
        )
        # position verification against the columnar position index
        # (Lucene PositionsEnum semantics) — never re-analyzes _source
        qpos = [t.position for t in qtoks]
        rel = [p - qpos[0] for p in qpos]
        mask = np.zeros(n, bool)
        pf = seg.postings.get(q.field)
        if pf is not None and pf.has_positions:
            tids = [pf.term_id(t) for t in terms]
            for doc in np.nonzero(conj)[0]:
                pos_of: Dict[str, List[int]] = {}
                ok = True
                for t, tid in zip(terms, tids):
                    if t in pos_of:
                        continue
                    ps = pf.doc_positions(tid, int(doc)) if tid >= 0 else None
                    if ps is None:
                        ok = False
                        break
                    pos_of[t] = ps.tolist()
                mask[doc] = ok and _phrase_match(pos_of, terms, rel, q.slop)
            return mask, np.where(mask, scores, 0).astype(np.float32)
        # legacy segments without stored positions: re-analyze _source
        for doc in np.nonzero(conj)[0]:
            src = seg.sources[doc] or {}
            value = _extract_field(src, q.field)
            ok = False
            for v in value:
                toks = analyzer.analyze(str(v))
                pos_of = {}
                for t in toks:
                    pos_of.setdefault(t.text, []).append(t.position)
                if _phrase_match(pos_of, terms, rel, q.slop):
                    ok = True
                    break
            mask[doc] = ok
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_term(self, q: TermQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if q.field == "_id":
            mask = np.zeros(n, bool)
            for i, d in enumerate(seg.doc_ids):
                if d == str(q.value):
                    mask[i] = True
            return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type in (TEXT, KEYWORD):
            return self._score_term_dense(
                seg, q.field, dsl.term_token(q.value), q.boost
            )
        # numeric/date/boolean: doc-values equality, constant score
        nf = seg.numerics.get(q.field)
        if nf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        target = _coerce_numeric(mf.type, q.value)
        mask = nf.exists & (nf.values == target)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_terms(self, q: TermsQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.zeros(n, bool)
        for v in q.values:
            m, _ = self._exec_term(TermQuery(field=q.field, value=v), seg)
            mask |= m
        # terms query is constant-scoring (boost)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_range(self, q: RangeQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mf = self.reader.mappings.get(q.field)
        if mf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        if mf.type in (TEXT, KEYWORD):
            of = seg.ordinals.get(q.field)
            if of is None:
                return np.zeros(n, bool), np.zeros(n, np.float32)
            terms = of.ord_terms
            lo, hi = 0, len(terms)
            if q.gte is not None:
                lo = _bisect_left(terms, str(q.gte))
            if q.gt is not None:
                lo = max(lo, _bisect_right(terms, str(q.gt)))
            if q.lte is not None:
                hi = min(hi, _bisect_right(terms, str(q.lte)))
            if q.lt is not None:
                hi = min(hi, _bisect_left(terms, str(q.lt)))
            # multi-value: any of the doc's ordinals in [lo, hi)
            in_range = (of.mv_ords >= lo) & (of.mv_ords < hi)
            hit_counts = np.diff(np.concatenate([[0], np.cumsum(in_range)])[of.mv_offsets])
            mask = hit_counts > 0
            return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)
        nf = seg.numerics.get(q.field)
        if nf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        mask = nf.exists.copy()
        conv = (lambda v: parse_date_millis(v)) if mf.type == DATE else float
        if q.gte is not None:
            mask &= nf.values >= conv(q.gte)
        if q.gt is not None:
            mask &= nf.values > conv(q.gt)
        if q.lte is not None:
            mask &= nf.values <= conv(q.lte)
        if q.lt is not None:
            mask &= nf.values < conv(q.lt)
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    def _exec_exists(self, q: ExistsQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.zeros(n, bool)
        pf = seg.postings.get(q.field)
        if pf is not None:
            mask |= pf.norms > 0
        nf = seg.numerics.get(q.field)
        if nf is not None:
            mask |= nf.exists
        vf = seg.vectors.get(q.field)
        if vf is not None:
            mask |= vf.exists
        of = seg.ordinals.get(q.field)
        if of is not None:
            mask |= of.ords >= 0
        return mask, np.where(mask, np.float32(q.boost), 0).astype(np.float32)

    # ---- compounds ----

    def _exec_bool(self, q: BoolQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        mask = np.ones(n, bool)
        scores = np.zeros(n, np.float32)
        any_positive = bool(q.must or q.filter or q.should)
        for c in q.must:
            m, s = self._exec(c, seg)
            mask &= m
            scores = (scores + s).astype(np.float32)
        for c in q.filter:
            mask &= self.filter_mask(c, seg)
        if q.should:
            smasks = []
            sscores = np.zeros(n, np.float32)
            for c in q.should:
                m, s = self._exec(c, seg)
                smasks.append(m)
                sscores = (sscores + np.where(m, s, 0)).astype(np.float32)
            stacked = np.stack(smasks)
            match_count = stacked.sum(axis=0)
            default_msm = 0 if (q.must or q.filter) else 1
            msm = (
                dsl.parse_minimum_should_match(q.minimum_should_match, len(q.should))
                if q.minimum_should_match is not None
                else default_msm
            )
            if msm > 0:
                mask &= match_count >= msm
            scores = (scores + np.where(match_count > 0, sscores, 0)).astype(np.float32)
        elif not any_positive:
            # only must_not: everything matches with score 0
            pass
        for c in q.must_not:
            m, _ = self._exec(c, seg)
            mask &= ~m
        if q.boost != 1.0:
            scores = (scores * np.float32(q.boost)).astype(np.float32)
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_multi_match(self, q: MultiMatchQuery, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        fields = expand_match_fields(self.reader.mappings, q.fields)
        if not fields:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        per_field: List[Tuple[np.ndarray, np.ndarray]] = []
        for fname, fboost in fields:
            if q.type == "phrase":
                m, s = self._exec_phrase(
                    MatchPhraseQuery(
                        field=fname, query=q.query, boost=q.boost * fboost
                    ),
                    seg,
                )
            else:
                m, s = self._exec_match(
                    MatchQuery(field=fname, query=q.query, operator=q.operator,
                               boost=q.boost * fboost),
                    seg,
                )
            per_field.append((m, s))
        masks = np.stack([m for m, _ in per_field])
        score_mat = np.stack([s for _, s in per_field])
        mask = masks.any(axis=0)
        if q.type == "best_fields":
            best = score_mat.max(axis=0)
            if q.tie_breaker:
                rest = score_mat.sum(axis=0) - best
                total = (best + np.float32(q.tie_breaker) * rest).astype(np.float32)
            else:
                total = best
        else:  # most_fields / cross_fields (round 1: summed per-field scores)
            total = score_mat.sum(axis=0, dtype=np.float32)
        return mask, np.where(mask, total, 0).astype(np.float32)

    # ---- knn ----

    def _exec_sparse(
        self, q: "dsl.SparseVectorQuery", seg: Segment
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Dense fp32 learned-sparse scorer — THE float oracle for the
        impact-tile device path. Term-at-a-time np.add.at in sorted
        query-term order: a doc occurs at most once in a term's
        postings, so each score cell accumulates exactly one f32 add
        per term, in term order — the same per-cell order the device
        kernel scatters (ops/impact.py lays tiles out per term in the
        identical sorted order), which is what makes the unquantized
        device path bit-equal to this function."""
        n = seg.num_docs
        sf = (seg.sparse or {}).get(q.field)
        if sf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        scores = np.zeros(n, np.float32)
        mask = np.zeros(n, bool)
        boost = np.float32(q.boost)
        for t, w in sorted(q.query_vector.items()):
            tid = sf.term_id(t)
            if tid < 0:
                continue
            docs, ws = sf.term_postings(tid)
            tw = np.float32(boost * np.float32(w))
            np.add.at(scores, docs, tw * ws)
            mask[docs] = True
        return mask, np.where(mask, scores, 0).astype(np.float32)

    def _exec_knn(self, sec: KnnSection, si: int, seg: Segment) -> Tuple[np.ndarray, np.ndarray]:
        n = seg.num_docs
        vf = seg.vectors.get(sec.field)
        if vf is None:
            return np.zeros(n, bool), np.zeros(n, np.float32)
        scores = score_vectors(
            np.asarray(sec.query_vector, np.float32),
            vf.vectors,
            vf.similarity,
            vf.unit_vectors,
        )
        mask = vf.exists.copy()
        if sec.filter is not None:
            mask &= self.filter_mask(sec.filter, seg)
        live = self.reader.live_docs[si]
        if live is not None:
            mask = mask & live
        if sec.similarity is not None:
            mask &= scores >= np.float32(sec.similarity)
        # per-shard: keep only top num_candidates, then top k overall
        cand = min(sec.num_candidates, int(mask.sum()))
        if cand < int(mask.sum()):
            masked = np.where(mask, scores, -np.inf)
            kth = np.partition(masked, -cand)[-cand]
            mask &= masked >= kth
        # top-level k cut happens at merge; apply boost
        out = (scores * np.float32(sec.boost)).astype(np.float32)
        return mask, np.where(mask, out, 0).astype(np.float32)


# ---- helpers ----

def parse_sort(sort_body) -> List[dict]:
    """Normalizes the request's "sort" into [{field, order, missing}]."""
    specs = []
    for entry in sort_body if isinstance(sort_body, list) else [sort_body]:
        if isinstance(entry, str):
            specs.append(
                {
                    "field": entry,
                    "order": "desc" if entry == "_score" else "asc",
                    "missing": "_last",
                }
            )
        elif isinstance(entry, dict) and len(entry) == 1:
            field, cfg = next(iter(entry.items()))
            if isinstance(cfg, str):
                specs.append({"field": field, "order": cfg, "missing": "_last"})
            elif isinstance(cfg, dict):
                specs.append(
                    {
                        "field": field,
                        "order": cfg.get(
                            "order", "desc" if field == "_score" else "asc"
                        ),
                        "missing": cfg.get("missing", "_last"),
                    }
                )
            else:
                raise QueryParseError(f"malformed sort entry [{entry}]")
        else:
            raise QueryParseError(f"malformed sort entry [{entry}]")
    return specs


def _sort_key_values(spec, seg, idx, scores, mappings, doc_base=0):
    """(lexsort-ready key array, raw response values) for matching docs.

    Keys live in "ascending key space": desc orders negate the value, and
    the `missing` policy fills ±inf in key space so _last/_first hold for
    either direction (SortField.setMissingValue semantics). Keyword keys
    are float ord ranks within the segment — NOTE: cross-segment keyword
    sort uses per-segment ranks, which is correct only because the merge
    re-sorts on the raw string values at the coordinator.
    """
    field = spec["field"]
    desc = spec["order"] == "desc"
    missing = spec["missing"]
    n = len(idx)
    if field == "_score":
        raw = scores.astype(np.float64)
        return (-raw if desc else raw), raw
    if field == "_doc":
        # global doc id = cumulative segment docBase + local id, so
        # cross-segment ordering is segment-major (Lucene docBase
        # semantics) and search_after cursors are unambiguous
        raw = (idx + doc_base).astype(np.float64)
        return (-raw if desc else raw), raw
    mf = mappings.get(field)
    if mf is not None and mf.type in (KEYWORD, TEXT):
        # string keys are only comparable globally: return key=None and
        # let execute_sorted rank the concatenated raw values
        of = seg.ordinals.get(field)
        if of is None:
            return None, np.full(n, None, object)
        ords = of.ords[idx]
        raw = np.asarray(
            [of.ord_terms[o] if o >= 0 else None for o in ords], object
        )
        return None, raw
    nf = seg.numerics.get(field)
    if nf is None:
        vals = np.zeros(n)
        have = np.zeros(n, bool)
    else:
        vals = nf.values[idx]
        have = nf.exists[idx]
    key_vals = -vals if desc else vals
    if missing == "_first":
        fill_key = -np.inf
        raw = np.where(have, vals, np.nan)
    elif missing == "_last":
        fill_key = np.inf
        raw = np.where(have, vals, np.nan)
    else:
        # concrete missing value: docs sort (and report) AS that value
        mv = float(missing)
        fill_key = -mv if desc else mv
        raw = np.where(have, vals, mv)
    key = np.where(have, key_vals, fill_key)
    return key.astype(np.float64), raw


def _rank_strings(raw: np.ndarray, spec: dict, after_value=None):
    """Global ascending-key-space ranks for a string sort column; the
    search_after cursor (if any) is ranked in the same space."""
    have = np.asarray([v is not None for v in raw])
    vals = {v for v in raw if v is not None}
    if after_value is not None:
        vals.add(str(after_value))
    uniq = {v: i for i, v in enumerate(sorted(vals))}
    key = np.asarray([float(uniq[v]) if v is not None else 0.0 for v in raw])
    desc = spec["order"] == "desc"
    if desc:
        key = -key
    fill = np.inf if spec["missing"] == "_last" else -np.inf
    key = np.where(have, key, fill)
    ak = None
    if after_value is not None:
        ak = float(uniq[str(after_value)])
        if desc:
            ak = -ak
    elif after_value is None:
        ak = fill  # null cursor = the missing fill position
    return key, ak


def _numeric_after_key(after_value, spec: dict):
    if after_value is None:
        # null cursor = the doc before had a missing value
        return np.inf if spec["missing"] == "_last" else -np.inf
    v = float(after_value)
    return -v if spec["order"] == "desc" else v


def _to_jsonable(v):
    if v is None:
        return None
    if isinstance(v, (np.floating, np.integer)):
        f = float(v)
        if np.isnan(f):
            return None
        return int(f) if f.is_integer() and abs(f) < 2**53 else f
    return v


def filter_source(src: Optional[dict], spec):
    """_source request option: false, list of patterns, or
    {includes, excludes} (FetchSourcePhase / XContentMapValues.filter)."""
    import fnmatch

    if src is None or spec is None or spec is True:
        return src
    if spec is False:
        return None
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        includes, excludes = spec, []
    else:
        includes = spec.get("includes", []) or []
        excludes = spec.get("excludes", []) or []
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]

    def walk(node, path):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            p = f"{path}.{k}" if path else k
            if excludes and any(fnmatch.fnmatch(p, e) for e in excludes):
                continue
            if isinstance(v, dict):
                sub = walk(v, p)
                if sub or _included(p, includes, prefix_ok=True):
                    if includes and not _included(p, includes, prefix_ok=True):
                        continue
                    out[k] = sub
            else:
                if not includes or _included(p, includes):
                    out[k] = v
        return out

    return walk(src, "")


def _included(path, includes, prefix_ok=False):
    import fnmatch

    for inc in includes:
        if fnmatch.fnmatch(path, inc):
            return True
        if inc.startswith(path + "."):
            return True  # an ancestor of an included leaf
        if path.startswith(inc + "."):
            return True  # a descendant of an included object
        if prefix_ok and fnmatch.fnmatch(path, inc + "*"):
            return True
    return False


def _fuzziness_edits(fuzziness: str, term: str) -> int:
    """Fuzziness.AUTO: 0 edits for length<3, 1 for 3-5, else 2."""
    f = str(fuzziness).upper()
    if f.startswith("AUTO"):
        n = len(term)
        return 0 if n < 3 else (1 if n <= 5 else 2)
    try:
        return max(0, min(int(float(f)), 2))
    except ValueError:
        raise QueryParseError(f"invalid fuzziness [{fuzziness}]")


def _levenshtein_at_most(a: str, b: str, k: int) -> bool:
    if a == b:
        return True
    if k == 0:
        return False
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        row_min = i
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1, prev[j - 1] + (ca != cb)))
            row_min = min(row_min, cur[-1])
        if row_min > k:
            return False
        prev = cur
    return prev[-1] <= k


def levenshtein_distance(a: str, b: str) -> int:
    """Exact edit distance (unbounded variant of _levenshtein_at_most
    above — keep the two in sync)."""
    if a == b:
        return 0
    if not a or not b:
        return max(len(a), len(b))
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1, prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def search_field_terms(
    mappings, analysis, field: str, text: str, override: Optional[str] = None
) -> List[str]:
    """Search-time analysis of one value: the field's search analyzer
    (or analyzer, or `standard`), falling back to the raw value when the
    analyzer name is unknown. Shared by DFS stats gathering, MLT term
    selection, and nested-object matching."""
    mf = mappings.get(field)
    name = override or (
        (mf.search_analyzer or mf.analyzer) if mf is not None else "standard"
    )
    try:
        return analysis.get(name).terms(str(text))
    except ValueError:
        return [str(text)]


def _span_near_match(
    plists: List[List[int]], slop: int, in_order: bool, k: int
) -> bool:
    """One-position-per-clause arrangement with span width - k <= slop;
    in_order additionally requires strictly increasing positions in
    clause order (SpanNearQuery/NearSpansOrdered semantics, simplified)."""
    if k == 0:
        return False
    if k == 1:
        return len(plists[0]) > 0
    if in_order:
        # for each start position, greedily pick the smallest admissible
        # position in each subsequent clause (minimal-span witness)
        for p0 in plists[0]:
            prev = p0
            ok = True
            for lst in plists[1:]:
                nxt = next((p for p in lst if p > prev), None)
                if nxt is None:
                    ok = False
                    break
                prev = nxt
            if ok and (prev - p0 + 1) - k <= slop:
                return True
        return False
    # unordered: smallest window covering one position from every list
    events = sorted(
        (p, li) for li, lst in enumerate(plists) for p in lst
    )
    from collections import defaultdict

    need = k
    have: Dict[int, int] = defaultdict(int)
    missing = need
    lo = 0
    for hi, (p, li) in enumerate(events):
        if have[li] == 0:
            missing -= 1
        have[li] += 1
        while missing == 0:
            span = p - events[lo][0] + 1
            if span - k <= slop:
                return True
            lp, lli = events[lo]
            have[lli] -= 1
            if have[lli] == 0:
                missing += 1
            lo += 1
    return False


_EARTH_RADIUS_M = 6371008.7714  # GeoUtils.EARTH_MEAN_RADIUS


def _haversine_m(lat1, lon1, lat2, lon2):
    """Vectorized haversine distance in meters (GeoDistance.ARC)."""
    la1, lo1 = np.radians(lat1), np.radians(lon1)
    la2, lo2 = np.radians(lat2), np.radians(lon2)
    dlat = la2 - la1
    dlon = lo2 - lo1
    a = (
        np.sin(dlat / 2.0) ** 2
        + np.cos(la1) * np.cos(la2) * np.sin(dlon / 2.0) ** 2
    )
    return 2.0 * _EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def _nested_objects(src: dict, path: str) -> List[dict]:
    node: Any = src
    for part in path.split("."):
        node = node.get(part) if isinstance(node, dict) else None
        if node is None:
            return []
    if isinstance(node, dict):
        return [node]
    return [o for o in node if isinstance(o, dict)] if isinstance(node, list) else []


def _source_field_lookup(seg: Segment, local: int):
    """doc['field'] resolver for scripts: dotted-path lookup into the
    stored source (ScriptDocValues backed by _source — the reference
    reads typed doc values; sources carry the same values here,
    including dense vectors)."""
    src = seg.sources[local]

    def lookup(field: str) -> list:
        node = src
        for part in field.split("."):
            if isinstance(node, dict):
                node = node.get(part)
            else:
                node = None
                break
        if node is None:
            return []
        return node if isinstance(node, list) else [node]

    return lookup


def _field_value_factor(cfg: dict, seg: Segment) -> np.ndarray:
    """FieldValueFactorFunction: factor * modifier(doc_value)."""
    field = cfg.get("field")
    if field is None:
        raise QueryParseError("[field_value_factor] requires [field]")
    n = seg.num_docs
    nf = seg.numerics.get(field)
    missing = cfg.get("missing")
    if nf is None:
        if missing is None:
            vals = np.zeros(n)
            have = np.zeros(n, bool)
        else:
            vals = np.full(n, float(missing))
            have = np.ones(n, bool)
    else:
        vals, have = nf.values, nf.exists
        if missing is not None:
            vals = np.where(have, vals, float(missing))
            have = np.ones(n, bool)
    v = vals * float(cfg.get("factor", 1.0))
    modifier = cfg.get("modifier", "none")
    mods = {
        "none": lambda x: x,
        "log": lambda x: np.log10(np.maximum(x, 1e-30)),
        "log1p": lambda x: np.log10(x + 1),
        "log2p": lambda x: np.log10(x + 2),
        "ln": lambda x: np.log(np.maximum(x, 1e-30)),
        "ln1p": lambda x: np.log1p(x),
        "ln2p": lambda x: np.log(x + 2),
        "square": lambda x: x * x,
        "sqrt": lambda x: np.sqrt(np.maximum(x, 0)),
        "reciprocal": lambda x: 1.0 / np.where(x == 0, 1e30, x),
    }
    if modifier not in mods:
        raise QueryParseError(f"unknown modifier [{modifier}]")
    out = mods[modifier](v).astype(np.float32)
    return np.where(have, out, 0.0).astype(np.float32)


def _stable_random(seed, doc_id: str) -> float:
    """Deterministic per-doc pseudo-random in [0,1) (RandomScoreFunction)."""
    import hashlib

    h = hashlib.md5(f"{seed}:{doc_id}".encode()).digest()
    return int.from_bytes(h[:8], "big") / 2**64


def rewrite_query_string(q: "dsl.QueryStringQuery", mappings) -> "dsl.Query":
    """query_string lite → bool tree. Supports: bare terms, field:term,
    quoted phrases, AND/OR/NOT connectives (first connective wins as the
    group operator), +term/-term prefixes in simple mode."""
    import re as _re

    default_fields = q.fields or (
        [q.default_field] if q.default_field and q.default_field != "*" else ["*"]
    )
    tokens = _re.findall(r'(?:[\w.*]+:)?"[^"]*"|\S+', q.query)
    must: List[dsl.Query] = []
    should: List[dsl.Query] = []
    must_not: List[dsl.Query] = []
    operator = q.default_operator
    pending: List[Tuple[str, dsl.Query]] = []  # (polarity, query)
    saw_and = False
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        i += 1
        up = tok.upper()
        if up == "AND" and not q.simple:
            saw_and = True
            continue
        if up == "OR" and not q.simple:
            continue
        if up == "NOT" and not q.simple:
            if i < len(tokens):
                sub = _qs_leaf(tokens[i], default_fields)
                if sub is not None:
                    pending.append(("not", sub))
                i += 1
            continue
        polarity = ""
        if q.simple and tok[:1] in "+-" and len(tok) > 1:
            polarity = tok[0]
            tok = tok[1:]
        sub = _qs_leaf(tok, default_fields)
        if sub is None:
            continue
        pending.append(("must" if polarity == "+" else "not" if polarity == "-" else "", sub))
    use_and = saw_and or operator == "and"
    for pol, sub in pending:
        if pol == "not":
            must_not.append(sub)
        elif pol == "must" or use_and:
            must.append(sub)
        else:
            should.append(sub)
    return dsl.BoolQuery(
        must=must, should=should, must_not=must_not, boost=q.boost,
        # should is only mandatory when it stands alone (bool default)
        minimum_should_match="1" if (should and not must) else None,
    )


def _qs_leaf(tok: str, default_fields: List[str]) -> Optional["dsl.Query"]:
    field = None
    if ":" in tok and not tok.startswith('"'):
        field, _, tok = tok.partition(":")
    if not tok:
        return None
    fields = [field] if field else default_fields
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        phrase = tok[1:-1]
        if len(fields) == 1 and fields[0] != "*":
            return dsl.MatchPhraseQuery(field=fields[0], query=phrase)
        return dsl.MultiMatchQuery(query=phrase, fields=fields, type="phrase")
    if "*" in tok or "?" in tok:
        if len(fields) == 1 and fields[0] != "*":
            return dsl.WildcardQuery(field=fields[0], value=tok)
        # wildcard over unspecified fields: unsupported → match nothing
        return dsl.MatchNoneQuery()
    if len(fields) == 1 and fields[0] != "*":
        return dsl.MatchQuery(field=fields[0], query=tok)
    return dsl.MultiMatchQuery(query=tok, fields=fields)


def expand_match_fields(mappings, patterns) -> List[Tuple[str, float]]:
    """Expands multi_match field patterns (``title^2``, ``body``, ``*``,
    ``name.*``) against the mapping's text/keyword fields — the
    QueryParserHelper.resolveMappingFields analog."""
    import fnmatch

    from ..index.mapping import KEYWORD as _KW, TEXT as _TX

    out: List[Tuple[str, float]] = []
    for f in patterns:
        boost = 1.0
        name = f
        if "^" in f:
            name, _, b = f.partition("^")
            boost = float(b)
        if "*" in name or "?" in name:
            # snapshot: concurrent dynamic mapping may grow the dict
            for fname, mf in sorted(list(mappings.fields.items())):
                if mf.type in (_TX, _KW) and fnmatch.fnmatch(fname, name):
                    out.append((fname, boost))
        else:
            out.append((name, boost))
    return out


def _extract_field(src: dict, path: str):
    node = src
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return []
    return node if isinstance(node, list) else [node]


def _phrase_match(pos_of: Dict[str, List[int]], terms: List[str], rel: List[int], slop: int) -> bool:
    """Exact phrase when slop=0: all terms at consecutive relative positions.
    Sloppy phrases use a simple window check (admits standard slop cases)."""
    first = pos_of.get(terms[0], [])
    for p0 in first:
        if slop == 0:
            if all(p0 + r in pos_of.get(t, []) for t, r in zip(terms[1:], rel[1:])):
                return True
        else:
            ok = True
            for t, r in zip(terms[1:], rel[1:]):
                cands = pos_of.get(t, [])
                if not any(abs(p - (p0 + r)) <= slop for p in cands):
                    ok = False
                    break
            if ok:
                return True
    return False


def _coerce_numeric(ftype: str, value) -> float:
    if ftype == BOOLEAN:
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        return 1.0 if value == "true" else 0.0
    if ftype == DATE:
        return parse_date_millis(value)
    return float(value)


def _bisect_left(arr: List[str], x: str) -> int:
    import bisect

    return bisect.bisect_left(arr, x)


def _bisect_right(arr: List[str], x: str) -> int:
    import bisect

    return bisect.bisect_right(arr, x)
