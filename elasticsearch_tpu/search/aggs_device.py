"""Device-resident aggregations engine — the compiler from a parsed
``AggNode`` tree to a plan of segment-sum kernels (ops/agg_kernels.py).

The host ``AggCollector`` (search/aggs.py) walks doc values with numpy
per shard and is the float ORACLE: every partial this module emits is
wire-identical to the host collector's, so the coordinator reduce
(``reduce_aggs``) needs no changes and a device-collected shard can
reduce together with a host-collected one. The request cache, brownout
tiers, and multi-index reduce therefore all work unchanged on top.

Routing contract ("never a silent wrong answer"):

  * ``try_compile`` returns a plan ONLY when every node in the tree is
    device-supported AND the touched columns satisfy the exactness
    profile below; anything else returns None and the whole tree runs
    on the host collector (``ES_TPU_DEVICE_AGGS=force`` raises instead,
    so CI can assert routing).
  * bucket/doc counts are int32 scatters — exact by construction.
  * metric sums ride int32 segment_sum over a host-prepared int32
    copy of the column, only for integer-valued columns whose Σ|v|
    stays inside the int32 window per segment: every partial sum is
    then exact in any association order, so the device result equals
    the oracle's float64 sum bit-for-bit.
  * min/max/percentiles require f32-exact columns (every value survives
    a float64→float32→float64 round trip), making them exact too.
  * histogram / date_histogram / range bucket boundaries are computed
    with EXACT integer arithmetic on a per-(segment, field) int32
    offset column (value − column_min), so floor-division and range
    membership can never disagree with the oracle's float64 math.
    (Float32 doc-value columns would mis-bucket date millis — float32
    resolution at 1.7e12 is ~2 minutes.)

Supported tree: metric leaves sum/avg/min/max/value_count/stats (+
percentiles via device sorted-quantile at the ROOT level), buckets
terms (keyword via the multi-value ordinal CSR, numeric via per-column
value ordinals), histogram, date_histogram (fixed intervals),
range/date_range, filter/filters (riding the PR 2 filter-bitset cache),
with ONE level of nesting: any supported bucket node over metric-leaf
subs (bucket-id × metric segment_sum). Deeper nesting, calendar
intervals, keyword metrics, and every other agg type route to the host.

HBM: the per-(segment, field) integer offset and value-ordinal columns
this engine uploads are charged to a new ``aggs`` ledger category via
the owning executor (released on executor close, i.e. on every engine
change-generation bump); budget pressure degrades compilation to the
host path instead of tripping the breaker.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.faults import faults
from ..index.mapping import BOOLEAN, DATE, KEYWORD, TEXT, parse_date_millis
from ..ops import agg_kernels, scoring
from . import dsl
from .aggs import (
    AggNode,
    AggParseError,
    PIPELINE_TYPES,
    _bkey,
    _int_param,
    _norm_order,
    _order_buckets,
    _parse_dh_interval,
    _range_key_part,
    _req,
)
from .executor import Hit, TopDocs

# hard cap on device bucket cardinality per node per segment (mirrors
# search.max_buckets); larger cardinalities route to the host
MAX_DEVICE_BUCKETS = 65536
# int32 sum window: Σ|v| below this keeps every partial sum exact
# (sums accumulate as int32 scatter-adds over an int32 value column)
I32_SUM_BOUND = float(2**31 - 2**16)
# float32 exact-integer window (the mesh's float32 psum max path)
F32_SUM_BOUND = float(2**24)
# two-word integer column split: value − min = hi·2**24 + lo, both
# words int32 — exact for any span below 2**53 (all date millis)
WIDE_SHIFT = 24

_INT_KEY_TYPES = ("integer", "long", "short", "byte", DATE)


class DeviceAggUnsupported(Exception):
    """This tree (or its columns) cannot run exactly on device; the
    caller routes the WHOLE body to the host collector."""

    def __init__(self, reason: str, budget: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.budget = budget


# ---------------------------------------------------------------------------
# node-level stats (the `_nodes/stats` aggs block)
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
AGG_STATS = {
    "device_routed": 0,  # shard agg collections served by this engine
    "host_routed": 0,  # shard agg collections on the host AggCollector
    "fallbacks": 0,  # device dispatch failed mid-flight → host rerun
    "mesh_routed": 0,  # whole-index SPMD agg launches (mesh step)
    "kernel_ms": 0.0,  # device dispatch+download wall time
}


def note_device_routed() -> None:
    with _STATS_LOCK:
        AGG_STATS["device_routed"] += 1


def note_host_routed() -> None:
    with _STATS_LOCK:
        AGG_STATS["host_routed"] += 1


def note_fallback() -> None:
    with _STATS_LOCK:
        AGG_STATS["fallbacks"] += 1


def note_mesh_routed() -> None:
    with _STATS_LOCK:
        AGG_STATS["mesh_routed"] += 1


def note_kernel_ms(ms: float) -> None:
    with _STATS_LOCK:
        AGG_STATS["kernel_ms"] += ms


def stats_snapshot() -> dict:
    from ..common.memory import hbm_ledger

    with _STATS_LOCK:
        out = dict(AGG_STATS)
    out["kernel_ms"] = round(out["kernel_ms"], 3)
    out["ledger_bytes"] = hbm_ledger.stats()["by_category"].get("aggs", 0)
    return out


def reset_stats() -> None:
    """Test hook: zero the routing counters."""
    with _STATS_LOCK:
        for k in AGG_STATS:
            AGG_STATS[k] = 0.0 if k == "kernel_ms" else 0


# ---------------------------------------------------------------------------
# per-(segment, field) column exactness profiles + device agg columns
# ---------------------------------------------------------------------------


@dataclass
class ColProfile:
    """Host-side facts about one numeric doc-value column that decide
    what may run on device exactly (computed once per executor
    generation — the column is immutable for the executor's life)."""

    present: bool
    n_exist: int = 0
    integer_valued: bool = False
    f32_exact: bool = False
    abs_sum: float = 0.0
    vmin: float = 0.0
    vmax: float = 0.0

    @property
    def sum_exact(self) -> bool:
        return (
            not self.present
            or self.n_exist == 0
            or (self.integer_valued and self.abs_sum < I32_SUM_BOUND)
        )

    @property
    def cmp_exact(self) -> bool:
        return not self.present or self.n_exist == 0 or self.f32_exact


def col_profile(ex, si: int, field: str) -> ColProfile:
    key = (si, field)
    cached = ex._agg_profiles.get(key)
    if cached is not None:
        return cached
    with ex._build_lock:
        cached = ex._agg_profiles.get(key)
        if cached is not None:
            return cached
        nf = ex.reader.segments[si].numerics.get(field)
        if nf is None:
            prof = ColProfile(present=False)
        else:
            v = nf.values[nf.exists]
            if len(v) == 0:
                prof = ColProfile(present=True, n_exist=0)
            else:
                finite = bool(np.isfinite(v).all())
                prof = ColProfile(
                    present=True,
                    n_exist=int(len(v)),
                    integer_valued=finite
                    and bool((v == np.floor(v)).all())
                    and bool((np.abs(v) < 2**62).all()),
                    f32_exact=finite
                    and bool(
                        (v.astype(np.float32).astype(np.float64) == v).all()
                    ),
                    abs_sum=float(np.abs(v).sum()),
                    vmin=float(v.min()),
                    vmax=float(v.max()),
                )
        ex._agg_profiles[key] = prof
        return prof


def _charge_aggs(ex, nbytes: int) -> None:
    """Charges an agg column upload to the `aggs` ledger category; a
    budget breach DEGRADES compilation to the host path (never trips)."""
    from ..common.memory import hbm_ledger

    if not hbm_ledger.would_fit(nbytes):
        hbm_ledger.note_degraded()
        raise DeviceAggUnsupported(
            f"agg column of {nbytes} bytes exceeds the HBM budget",
            budget=True,
        )
    ex._charge("aggs", nbytes, False)


def wide_col(ex, si: int, field: str):
    """Two-word exact integer view of one column: (device int32 hi,
    device int32 lo, device bool exists, base, dmax) where value −
    base = hi·2**24 + lo. Exact for any date-millis span (Δ < 2**53),
    where a single int32 offset — let alone the float32 doc-value
    column — could not represent the column. None when the segment
    lacks the column. Cached per (segment, field)."""
    import jax

    key = ("wide", si, field)
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        nf = ex.reader.segments[si].numerics.get(field)
        if nf is None:
            ex._agg_cols[key] = None
            return None
        prof = col_profile(ex, si, field)
        base = int(prof.vmin) if prof.n_exist else 0
        dmax = int(prof.vmax) - base if prof.n_exist else 0
        hi_host = np.zeros(len(nf.values), np.int32)
        lo_host = np.zeros(len(nf.values), np.int32)
        if prof.n_exist:
            delta = nf.values[nf.exists].astype(np.int64) - base
            hi_host[nf.exists] = (delta >> WIDE_SHIFT).astype(np.int32)
            lo_host[nf.exists] = (
                delta & ((1 << WIDE_SHIFT) - 1)
            ).astype(np.int32)
        _charge_aggs(ex, int(hi_host.nbytes + lo_host.nbytes))
        dn = ex.device_segments[si].numerics.get(field)
        out = (
            jax.device_put(hi_host, ex.device),
            jax.device_put(lo_host, ex.device),
            dn[1],
            base,
            dmax,
        )
        ex._agg_cols[key] = out
        return out




def int_col(ex, si: int, field: str):
    """Cached device int32 copy of an integer-valued column (0 where
    missing) — the exact sum accumulator operand. Callers gate on
    ``ColProfile.sum_exact`` so the cast and the scatter-sums can never
    overflow/round."""
    import jax

    key = ("int", si, field)
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        nf = ex.reader.segments[si].numerics.get(field)
        if nf is None:
            ex._agg_cols[key] = None
            return None
        host = np.zeros(len(nf.values), np.int32)
        host[nf.exists] = nf.values[nf.exists].astype(np.int64).astype(
            np.int32
        )
        _charge_aggs(ex, int(host.nbytes))
        out = jax.device_put(host, ex.device)
        ex._agg_cols[key] = out
        return out


# ---- bucket SPACES (host facts: ids per slot, slot→doc map, static
# gate, cardinality) and their device LAYOUTS (the sorted-permutation
# operands the segment-sum kernels consume) ----


def _space_kw(ex, si: int, field: str):
    """Keyword terms bucket space over the multi-value ordinal CSR:
    ids = mv_ords (entry-level), slot→doc map = the CSR row expansion."""
    key = ("space_kw", si, field)
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        of = ex.reader.segments[si].ordinals.get(field)
        if of is None:
            ex._agg_cols[key] = None
            return None
        n = ex.reader.segments[si].num_docs
        map_host = np.repeat(
            np.arange(n, dtype=np.int64), np.diff(of.mv_offsets)
        )
        space = (
            of.mv_ords.astype(np.int64),
            map_host,
            np.ones(len(of.mv_ords), bool),
            len(of.ord_terms),
        )
        ex._agg_cols[key] = space
        return space


def _space_num(ex, si: int, field: str):
    """Numeric terms bucket space: per-column value ordinals (the
    hashed-ords analog — exact for any float column). Also caches the
    sorted unique values for key mapping at collect."""
    key = ("space_num", si, field)
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        nf = ex.reader.segments[si].numerics.get(field)
        if nf is None:
            ex._agg_cols[key] = None
            return None
        uniq = np.unique(nf.values[nf.exists])
        if len(uniq) > MAX_DEVICE_BUCKETS:
            raise DeviceAggUnsupported(
                f"numeric terms cardinality {len(uniq)} exceeds "
                f"{MAX_DEVICE_BUCKETS}"
            )
        ids = np.full(len(nf.values), len(uniq), np.int64)
        if len(uniq):
            ids[nf.exists] = np.searchsorted(uniq, nf.values[nf.exists])
        space = (ids, None, nf.exists, len(uniq))
        ex._agg_cols[key] = (space, uniq)
        return ex._agg_cols[key]


def _space_hist(ex, si: int, field: str, interval: int, offset: int):
    """Histogram bucket space: ids are floor((v − offset) / interval) −
    qmin computed host-side in EXACT int64 — the dashboard case (one
    interval, many queries) pays the host pass once per executor
    generation. Returns ((ids, map, gate, nb), qmin) or None."""
    key = ("space_hist", si, field, int(interval), int(offset))
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        nf = ex.reader.segments[si].numerics.get(field)
        if nf is None or not nf.exists.any():
            ex._agg_cols[key] = None
            return None
        # numpy int64 floor-division follows Python floor semantics, so
        # pre-1970 dates bucket exactly like the oracle's np.floor
        q = (nf.values[nf.exists].astype(np.int64) - offset) // interval
        qmin = int(q.min())
        nb = int(q.max()) - qmin + 1
        if nb > MAX_DEVICE_BUCKETS:
            raise DeviceAggUnsupported(
                f"histogram would make {nb} buckets"
            )
        ids = np.full(len(nf.values), nb, np.int64)
        ids[nf.exists] = q - qmin
        out = ((ids, None, nf.exists, nb), qmin)
        ex._agg_cols[key] = out
        return out


def counts_layout(ex, si: int, skey: tuple, space):
    """Device operands for sorted_bucket_counts: the bucket-major slot
    permutation (composed with the slot→doc map), the pre-permuted
    static gate, and the int32 bucket boundaries."""
    import jax

    key = ("clay", si) + skey
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        ids, map_host, gate, nb = space
        perm = bounds = None
        from ..common.settings import device_build_mode

        if device_build_mode() != "off":
            # bucket ids are small ints: the stable argsort + boundary
            # table build rides the device build kernels (bit-identical
            # by the stable-sort contract; ops/index_build.py)
            got = None
            try:
                from ..ops.index_build import agg_perm_tables_device

                got = agg_perm_tables_device(ids, nb)
            except Exception:
                got = None  # host fallback below — never a wrong table
            if got is not None:
                perm, bounds = got
        if perm is None:
            perm = np.argsort(ids, kind="stable")
            bounds = np.searchsorted(
                ids[perm], np.arange(nb + 1)
            ).astype(np.int32)
        map_p = (
            perm if map_host is None else map_host[perm]
        ).astype(np.int32)
        gate_p = gate[perm]
        _charge_aggs(
            ex, int(map_p.nbytes + gate_p.nbytes + bounds.nbytes)
        )
        out = {
            "map": jax.device_put(map_p, ex.device),
            "gate": jax.device_put(gate_p, ex.device),
            "bounds": jax.device_put(bounds, ex.device),
        }
        ex._agg_cols[key] = out
        return out


def metric_layout(ex, si: int, skey: tuple, mfield: str,
                  need_int: bool, space):
    """Device operands for sorted_bucket_metrics: slots re-sorted by
    (bucket, metric value asc) so per-bucket extrema are rank lookups,
    with the metric column pre-permuted (float32 for min/max, exact
    int32 copy for sums). None when the segment lacks the column."""
    import jax

    key = ("mlay", si, mfield, bool(need_int)) + skey
    if key in ex._agg_cols:
        return ex._agg_cols[key]
    with ex._build_lock:
        if key in ex._agg_cols:
            return ex._agg_cols[key]
        nf = ex.reader.segments[si].numerics.get(mfield)
        if nf is None:
            ex._agg_cols[key] = None
            return None
        ids, map_host, gate, nb = space
        if map_host is None:
            mvals = nf.values
            mex = nf.exists
        else:
            mvals = nf.values[map_host]
            mex = nf.exists[map_host]
        perm = np.lexsort((mvals, ids))
        bounds = np.searchsorted(
            ids[perm], np.arange(nb + 1)
        ).astype(np.int32)
        gate_p = (gate & mex)[perm]
        v_p = mvals[perm].astype(np.float32)
        iv_p = np.zeros(len(perm), np.int32)
        if need_int:
            sel_vals = mvals[perm][gate_p]
            iv_p[gate_p] = sel_vals.astype(np.int64).astype(np.int32)
        map_p = (
            perm if map_host is None else map_host[perm]
        ).astype(np.int32)
        _charge_aggs(
            ex,
            int(
                map_p.nbytes + gate_p.nbytes + v_p.nbytes
                + iv_p.nbytes + bounds.nbytes
            ),
        )
        out = {
            "map": jax.device_put(map_p, ex.device),
            "gate": jax.device_put(gate_p, ex.device),
            "v": jax.device_put(v_p, ex.device),
            "iv": jax.device_put(iv_p, ex.device),
            "bounds": jax.device_put(bounds, ex.device),
        }
        ex._agg_cols[key] = out
        return out


# ---------------------------------------------------------------------------
# metric leaves
# ---------------------------------------------------------------------------

_METRIC_KINDS = (
    "sum", "avg", "min", "max", "value_count", "stats", "percentiles",
)
_NEEDS_SUM = {"sum", "avg", "stats"}
_NEEDS_CMP = {"min", "max", "stats", "percentiles"}


class _MetricSpec:
    """One supported metric leaf (standalone or a bucket sub-agg)."""

    def __init__(self, ex, node: AggNode, mappings, root: bool):
        self.name = node.name
        self.kind = node.type
        self.field = _req(node, "field")
        self.percents = node.params.get(
            "percents", [1, 5, 25, 50, 75, 95, 99]
        )
        mf = mappings.get(self.field)
        if mf is not None and mf.type in (KEYWORD, TEXT):
            raise DeviceAggUnsupported(
                f"metric [{self.kind}] over keyword/text field "
                f"[{self.field}]"
            )
        if self.kind == "percentiles" and not root:
            raise DeviceAggUnsupported(
                "percentiles under a bucket agg"
            )
        for si in range(len(ex.reader.segments)):
            p = col_profile(ex, si, self.field)
            if self.kind in _NEEDS_SUM and not p.sum_exact:
                raise DeviceAggUnsupported(
                    f"[{self.field}] sum not float32-exact "
                    "(non-integer values or |sum| >= 2^24)"
                )
            if self.kind in _NEEDS_CMP and not p.cmp_exact:
                raise DeviceAggUnsupported(
                    f"[{self.field}] values not float32-exact"
                )

    @property
    def sig(self) -> tuple:
        return ("metric", self.kind, self.field, tuple(self.percents))

    # ---- root-level (single implicit bucket) ----

    def _ivals(self, ex, si: int):
        # the int32 sum operand; kinds that never sum ride a shared
        # zeros column (their sum output is discarded)
        if self.kind in _NEEDS_SUM:
            return int_col(ex, si, self.field)
        return _ZERO_IDS(ex, si)

    def dispatch_root(self, ex, si: int, mask):
        dn = ex.device_segments[si].numerics.get(self.field)
        if dn is None:
            return None
        v, e = dn
        sel = mask & e
        if self.kind == "percentiles":
            return agg_kernels.masked_sorted(sel, v)
        return agg_kernels.masked_metric(sel, v, self._ivals(ex, si))

    def collect_root(self, pends) -> dict:
        if self.kind == "percentiles":
            vals: List[np.ndarray] = []
            for p in pends:
                if p is None:
                    continue
                sorted_v, cnt = p
                c = int(np.asarray(cnt))
                if c:
                    vals.append(
                        np.asarray(sorted_v)[:c].astype(np.float64)
                    )
            flat = np.concatenate(vals) if vals else np.zeros(0)
            return {
                "t": "percentiles",
                "values": flat.tolist(),
                "percents": self.percents,
            }
        count = 0
        total = 0.0
        mn = None
        mx = None
        for p in pends:
            if p is None:
                continue
            c, s, lo, hi = (np.asarray(x) for x in p)
            c = int(c)
            if not c:
                continue
            count += c
            total += float(s)
            lo = float(lo)
            hi = float(hi)
            mn = lo if mn is None else min(mn, lo)
            mx = hi if mx is None else max(mx, hi)
        return _metric_partial(self.kind, count, total, mn, mx)

    # ---- bucketed (bucket-id × metric segment_sum) ----

    def dispatch_sorted(self, ex, si: int, mask, skey: tuple, space):
        """Per-bucket (count, sum, min, max) arrays over a bucket
        space's sorted metric layout (bucket-id × metric segment_sum)."""
        lay = metric_layout(
            ex, si, skey, self.field, self.kind in _NEEDS_SUM, space
        )
        if lay is None:
            return None
        return agg_kernels.sorted_bucket_metrics(
            mask, lay["map"], lay["gate"], lay["v"], lay["iv"],
            lay["bounds"],
        )

    def dispatch_sub_masked(self, ex, si: int, sel):
        """Single-bucket metric over an explicit selection mask (the
        range/filter bucket subs)."""
        dn = ex.device_segments[si].numerics.get(self.field)
        if dn is None:
            return None
        v, e = dn
        return agg_kernels.masked_metric(sel & e, v, self._ivals(ex, si))


def _metric_partial(kind: str, count: int, total: float,
                    mn: Optional[float], mx: Optional[float]) -> dict:
    """The host collector's exact partial wire shape for one metric."""
    if kind == "avg":
        return {"t": "avg", "sum": total, "count": count}
    if kind == "sum":
        return {"t": "sum", "sum": total}
    if kind == "min":
        return {"t": "min", "min": mn}
    if kind == "max":
        return {"t": "max", "max": mx}
    if kind == "value_count":
        return {"t": "value_count", "count": count}
    return {
        "t": "stats",
        "count": count,
        "sum": total,
        "min": mn,
        "max": mx,
    }


class _SubAccum:
    """Accumulates bucket-sub metric components across segments, keyed
    by the parent's bucket key."""

    def __init__(self, specs: List[_MetricSpec]):
        self.specs = specs
        self.acc: List[Dict[Any, list]] = [dict() for _ in specs]

    def add_arrays(self, sub_outs, keys_of_idx) -> None:
        """sub_outs: per spec, (cnt, sum, min, max) device arrays (or
        None); keys_of_idx: [(bucket_index, key)] worth accumulating."""
        for spi, out in enumerate(sub_outs):
            if out is None:
                continue
            cnt, sm, mn, mx = (np.atleast_1d(np.asarray(x)) for x in out)
            store = self.acc[spi]
            for bi, key in keys_of_idx:
                c = int(cnt[bi])
                if not c:
                    continue
                cur = store.get(key)
                if cur is None:
                    store[key] = [c, float(sm[bi]), float(mn[bi]),
                                  float(mx[bi])]
                else:
                    cur[0] += c
                    cur[1] += float(sm[bi])
                    cur[2] = min(cur[2], float(mn[bi]))
                    cur[3] = max(cur[3], float(mx[bi]))

    def subs_for(self, key) -> dict:
        out = {}
        for spec, store in zip(self.specs, self.acc):
            got = store.get(key)
            if got is None:
                out[spec.name] = _metric_partial(
                    spec.kind, 0, 0.0, None, None
                )
            else:
                out[spec.name] = _metric_partial(spec.kind, *got)
        return out


def _compile_subs(ex, node: AggNode, mappings) -> List[_MetricSpec]:
    """A bucket node's collected subs must all be supported metric
    leaves (one nesting level); pipeline subs collect nothing and pass
    through to the reduce."""
    specs = []
    for sub in node.subs:
        if sub.type in PIPELINE_TYPES:
            continue
        if sub.type not in _METRIC_KINDS or sub.type == "percentiles":
            raise DeviceAggUnsupported(
                f"sub-agg [{sub.name}] of type [{sub.type}] under "
                f"[{node.name}]"
            )
        specs.append(_MetricSpec(ex, sub, mappings, root=False))
    return specs


# ---------------------------------------------------------------------------
# bucket nodes
# ---------------------------------------------------------------------------


class _TermsSpec:
    """terms over a keyword (ordinal CSR) or numeric (value ordinal)
    column; metric subs scatter into the same bucket-id space."""

    def __init__(self, ex, node: AggNode, mappings):
        self.name = node.name
        self.field = _req(node, "field")
        self.size = _int_param(node, "size", 10)
        self.shard_size = _int_param(
            node, "shard_size", max(int(self.size * 1.5) + 10, self.size)
        )
        self.order = _norm_order(node.params.get("order", {"_count": "desc"}))
        okey = next(iter(self.order)) if self.order else "_count"
        if okey not in ("_count", "_key"):
            raise DeviceAggUnsupported(f"terms order [{okey}]")
        mf = mappings.get(self.field)
        if mf is not None and mf.type == TEXT:
            raise DeviceAggUnsupported("terms over a text field")
        self.keyword = mf is not None and mf.type == KEYWORD
        self.ftype = None if mf is None else mf.type
        if not self.keyword:
            if mf is None:
                raise DeviceAggUnsupported("terms over an unmapped field")
            for si in range(len(ex.reader.segments)):
                _space_num(ex, si, self.field)  # raises on cardinality
        else:
            for si in range(len(ex.reader.segments)):
                of = ex.reader.segments[si].ordinals.get(self.field)
                if of is not None and len(of.ord_terms) > MAX_DEVICE_BUCKETS:
                    raise DeviceAggUnsupported(
                        "keyword terms cardinality over the device cap"
                    )
        self.subs = _compile_subs(ex, node, mappings)

    @property
    def sig(self) -> tuple:
        return (
            "terms", self.field, self.keyword, self.size, self.shard_size,
            tuple(self.order.items()), tuple(s.sig for s in self.subs),
        )

    def dispatch(self, ex, si: int, mask):
        if self.keyword:
            space = _space_kw(ex, si, self.field)
            skey = ("kw", self.field)
        else:
            got = _space_num(ex, si, self.field)
            if got is None:
                return None
            space, _uniq = got
            skey = ("num", self.field)
        if space is None:
            return None
        lay = counts_layout(ex, si, skey, space)
        counts = agg_kernels.sorted_bucket_counts(
            mask, lay["map"], lay["gate"], lay["bounds"]
        )
        sub_outs = [
            sp.dispatch_sorted(ex, si, mask, skey, space)
            for sp in self.subs
        ]
        return ("kw" if self.keyword else "num", si, counts, sub_outs)

    def _num_key(self, raw: float):
        key = float(raw)
        if self.ftype == BOOLEAN:
            return bool(key)
        if self.ftype in _INT_KEY_TYPES:
            return int(key)
        return key

    def collect(self, ex, pends) -> dict:
        counts: Dict[Any, int] = {}
        accum = _SubAccum(self.subs)
        for item in pends:
            if item is None:
                continue
            kind, si, dev_counts, sub_outs = item
            host_counts = np.asarray(dev_counts)
            nz = np.nonzero(host_counts)[0]
            if kind == "kw":
                terms = ex.reader.segments[si].ordinals[self.field].ord_terms
                keys_of_idx = [(int(o), terms[int(o)]) for o in nz]
            else:
                uniq = _space_num(ex, si, self.field)[1]
                keys_of_idx = [
                    (int(o), self._num_key(uniq[int(o)])) for o in nz
                ]
            for o, key in keys_of_idx:
                counts[key] = counts.get(key, 0) + int(host_counts[o])
            if self.subs:
                accum.add_arrays(sub_outs, keys_of_idx)
        total = sum(counts.values())
        top = _order_buckets(counts, self.order)[: self.shard_size]
        shard_error = (
            top[-1][1] if len(counts) > self.shard_size and top else 0
        )
        buckets = {}
        for key, cnt in top:
            subs = accum.subs_for(key) if self.subs else {}
            buckets[_bkey(key)] = {
                "key": key, "doc_count": cnt, "subs": subs,
            }
        return {
            "t": "terms",
            "buckets": buckets,
            "sum_docs": total,
            "size": self.size,
            "order": self.order,
            "shard_error": shard_error,
        }


class _HistoSpec:
    """histogram / date_histogram via exact integer floor-division on
    the offset column. Per-segment bases; the host merges by key."""

    def __init__(self, ex, node: AggNode, mappings, date: bool):
        self.name = node.name
        self.field = _req(node, "field")
        self.date = date
        if date:
            interval_ms, calendar_unit = _parse_dh_interval(node.params)
            if calendar_unit is not None:
                raise DeviceAggUnsupported(
                    f"calendar interval [{calendar_unit}]"
                )
            self.interval = int(interval_ms)
            self.offset = 0
        else:
            interval = float(node.params.get("interval", 0))
            offset = float(node.params.get("offset", 0))
            if interval <= 0:
                raise AggParseError("interval must be > 0")
            if interval != int(interval) or offset != int(offset):
                raise DeviceAggUnsupported(
                    "non-integer histogram interval/offset"
                )
            self.interval = int(interval)
            self.offset = int(offset)
        # bucket-id columns are exact int64 host floor-divisions cached
        # per (segment, field, interval, offset); building them at
        # compile time surfaces cardinality/HBM breaches as host routing
        for si in range(len(ex.reader.segments)):
            p = col_profile(ex, si, self.field)
            if not p.present or p.n_exist == 0:
                continue
            if not p.integer_valued:
                raise DeviceAggUnsupported(
                    f"[{self.field}] is not an integer-valued column"
                )
            _space_hist(ex, si, self.field, self.interval, self.offset)
        self.subs = _compile_subs(ex, node, mappings)

    @property
    def sig(self) -> tuple:
        return (
            "date_histogram" if self.date else "histogram",
            self.field, self.interval, self.offset,
            tuple(s.sig for s in self.subs),
        )

    def dispatch(self, ex, si: int, mask):
        got = _space_hist(
            ex, si, self.field, self.interval, self.offset
        )
        if got is None:
            return None
        space, qmin = got
        skey = ("hist", self.field, self.interval, self.offset)
        lay = counts_layout(ex, si, skey, space)
        counts = agg_kernels.sorted_bucket_counts(
            mask, lay["map"], lay["gate"], lay["bounds"]
        )
        sub_outs = [
            sp.dispatch_sorted(ex, si, mask, skey, space)
            for sp in self.subs
        ]
        return (si, qmin, counts, sub_outs)

    def collect(self, ex, pends) -> dict:
        counts: Dict[Any, int] = {}
        accum = _SubAccum(self.subs)
        for item in pends:
            if item is None:
                continue
            si, qmin, dev_counts, sub_outs = item
            host_counts = np.asarray(dev_counts)
            nz = np.nonzero(host_counts)[0]
            keys_of_idx = []
            for rel in nz:
                raw = (qmin + int(rel)) * self.interval + self.offset
                key = int(raw) if self.date else float(raw)
                keys_of_idx.append((int(rel), key))
                counts[key] = counts.get(key, 0) + int(host_counts[rel])
            if self.subs:
                accum.add_arrays(sub_outs, keys_of_idx)
        buckets = {}
        for k in sorted(counts):
            subs = accum.subs_for(k) if self.subs else {}
            buckets[k] = {"key": k, "doc_count": counts[k], "subs": subs}
        return {
            "t": "date_histogram" if self.date else "histogram",
            "buckets": buckets,
        }


class _RangeSpec:
    """range / date_range as exact int32 comparisons in offset space."""

    def __init__(self, ex, node: AggNode, mappings, date: bool):
        self.name = node.name
        self.field = _req(node, "field")
        self.date = date
        self.keyed = node.params.get("keyed", False)
        ranges = node.params.get("ranges", [])
        if not isinstance(ranges, list):
            raise DeviceAggUnsupported("malformed ranges")
        self.ranges = []
        for r in ranges:
            frm_raw = r.get("from")
            to_raw = r.get("to")
            if date:
                frm = parse_date_millis(frm_raw) if frm_raw is not None else None
                to = parse_date_millis(to_raw) if to_raw is not None else None
            else:
                frm = float(frm_raw) if frm_raw is not None else None
                to = float(to_raw) if to_raw is not None else None
            key = r.get("key")
            if key is None:
                fs = _range_key_part(frm_raw, date, frm)
                ts = _range_key_part(to_raw, date, to)
                key = f"{fs}-{ts}"
            self.ranges.append((frm, to, key))
        for si in range(len(ex.reader.segments)):
            p = col_profile(ex, si, self.field)
            if p.present and p.n_exist and not p.integer_valued:
                raise DeviceAggUnsupported(
                    f"[{self.field}] is not an integer-valued column"
                )
        self.subs = _compile_subs(ex, node, mappings)

    @property
    def sig(self) -> tuple:
        return (
            "date_range" if self.date else "range", self.field, self.keyed,
            tuple((f, t, k) for f, t, k in self.ranges),
            tuple(s.sig for s in self.subs),
        )

    def dispatch(self, ex, si: int, mask):
        got = wide_col(ex, si, self.field)
        if got is None:
            return None
        hi_w, lo_w, e, base, dmax = got
        out = []
        for frm, to, _key in self.ranges:
            # v >= frm  ⟺  Δ >= ceil(frm) − base  (v integer-valued);
            # v < to    ⟺  Δ < ceil(to) − base — compared as two int32
            # words (divmod by 2**24, floor semantics matching the
            # column split). Bounds clamp into the observed span first
            # so the word decomposition can never overflow int32.
            lo_b = -1 if frm is None else math.ceil(frm) - base
            hi_b = dmax + 2 if to is None else math.ceil(to) - base
            lo_b = max(-1, min(lo_b, dmax + 2))
            hi_b = max(-1, min(hi_b, dmax + 2))
            lhi, llo = divmod(lo_b, 1 << WIDE_SHIFT)
            hhi, hlo = divmod(hi_b, 1 << WIDE_SHIFT)
            rmask = agg_kernels.wide_range_mask(
                hi_w, lo_w, e,
                np.int32(lhi), np.int32(llo),
                np.int32(hhi), np.int32(hlo),
            )
            sel = mask & rmask
            cnt = sel.sum()
            sub_outs = [
                sp.dispatch_sub_masked(ex, si, sel)
                for sp in self.subs
            ]
            out.append((cnt, sub_outs))
        return out

    def collect(self, ex, pends) -> dict:
        n_ranges = len(self.ranges)
        counts = [0] * n_ranges
        accums = [_SubAccum(self.subs) for _ in range(n_ranges)]
        for item in pends:
            if item is None:
                continue
            for ri, (cnt, sub_outs) in enumerate(item):
                counts[ri] += int(np.asarray(cnt))
                if self.subs:
                    accums[ri].add_arrays(sub_outs, [(0, 0)])
        out = []
        for ri, (frm, to, key) in enumerate(self.ranges):
            entry = {
                "key": key,
                "doc_count": counts[ri],
                "subs": accums[ri].subs_for(0) if self.subs else {},
            }
            if frm is not None:
                entry["from"] = frm
            if to is not None:
                entry["to"] = to
            out.append(entry)
        return {
            "t": "date_range" if self.date else "range",
            "buckets": out,
            "keyed": self.keyed,
        }


def _ZERO_IDS(ex, si: int):
    """Cached device int32 zeros([n_docs]) — the single-bucket id
    column for range/filter metric subs."""
    import jax
    import jax.numpy as jnp

    key = ("zero", si)
    cached = ex._agg_cols.get(key)
    if cached is None:
        n = ex.reader.segments[si].num_docs
        cached = jnp.zeros(n, jnp.int32)
        cached = jax.device_put(cached, ex.device)
        ex._agg_cols[key] = cached
    return cached


class _FilterSpec:
    """filter / filters riding the PR 2 filter-bitset cache: the
    bucket's bitset ANDs into the query mask on device."""

    def __init__(self, ex, node: AggNode, mappings, multi: bool):
        self.name = node.name
        self.multi = multi
        self.items: List[Tuple[str, object]] = []
        try:
            if multi:
                specs = node.params.get("filters", {})
                if isinstance(specs, dict):
                    self.keyed = True
                    items = specs.items()
                else:
                    self.keyed = False
                    items = ((str(i), s) for i, s in enumerate(specs))
                for key, qjson in items:
                    self.items.append((key, dsl.parse_query(qjson)))
            else:
                self.keyed = True
                self.items.append((node.name, dsl.parse_query(node.params)))
        except dsl.QueryParseError as e:
            raise DeviceAggUnsupported(f"filter parse: {e}")
        self.subs = _compile_subs(ex, node, mappings)

    @property
    def sig(self) -> tuple:
        return (
            "filters" if self.multi else "filter",
            tuple(dsl.canonical_key(q) for _k, q in self.items),
            self.keyed, tuple(s.sig for s in self.subs),
        )

    def dispatch(self, ex, si: int, mask):
        out = []
        for _key, q in self.items:
            sel = mask & ex.filter_mask(q, si)
            cnt = sel.sum()
            sub_outs = [
                sp.dispatch_sub_masked(ex, si, sel)
                for sp in self.subs
            ]
            out.append((cnt, sub_outs))
        return out

    def collect(self, ex, pends) -> dict:
        n = len(self.items)
        counts = [0] * n
        accums = [_SubAccum(self.subs) for _ in range(n)]
        for item in pends:
            if item is None:
                continue
            for fi, (cnt, sub_outs) in enumerate(item):
                counts[fi] += int(np.asarray(cnt))
                if self.subs:
                    accums[fi].add_arrays(sub_outs, [(0, 0)])
        if not self.multi:
            return {
                "t": "filter",
                "doc_count": counts[0],
                "subs": accums[0].subs_for(0) if self.subs else {},
            }
        buckets = {}
        for fi, (key, _q) in enumerate(self.items):
            buckets[key] = {
                "key": key,
                "doc_count": counts[fi],
                "subs": accums[fi].subs_for(0) if self.subs else {},
            }
        return {"t": "filters", "buckets": buckets, "keyed": self.keyed}


# ---------------------------------------------------------------------------
# tree compilation + the shard-level plan (the batcher's agg job plan)
# ---------------------------------------------------------------------------


def _compile_node(ex, node: AggNode, mappings):
    t = node.type
    if t in _METRIC_KINDS:
        if node.subs:
            raise DeviceAggUnsupported("metric with subs")
        return _MetricSpec(ex, node, mappings, root=True)
    if t == "terms":
        return _TermsSpec(ex, node, mappings)
    if t == "histogram":
        return _HistoSpec(ex, node, mappings, date=False)
    if t == "date_histogram":
        return _HistoSpec(ex, node, mappings, date=True)
    if t == "range":
        return _RangeSpec(ex, node, mappings, date=False)
    if t == "date_range":
        return _RangeSpec(ex, node, mappings, date=True)
    if t == "filter":
        return _FilterSpec(ex, node, mappings, multi=False)
    if t == "filters":
        return _FilterSpec(ex, node, mappings, multi=True)
    raise DeviceAggUnsupported(f"agg type [{t}]")


class DeviceAggPlan:
    """A compiled shard-level device agg request: the QueryBatcher's
    ``agg`` job family dispatches it (device scatter launches) and
    collects it (compact downloads → host partials). The result is
    (TopDocs, partials) with partials wire-identical to AggCollector's."""

    def __init__(self, ex, nodes: Sequence[AggNode], specs, index: str,
                 sid: int, query, k: int):
        self.ex = ex
        self.nodes = nodes
        self.specs = specs  # name → spec for non-pipeline root nodes
        self.index = index
        self.sid = sid
        self.query = query
        self.k = int(k)
        self.sig = tuple(sp.sig for _name, sp in specs)

    def flops_estimate(self) -> int:
        n_docs = sum(s.num_docs for s in self.ex.reader.segments)
        return agg_kernels.agg_flops(n_docs, max(len(self.specs), 1))

    def dispatch(self) -> dict:
        """Launches all device work (query masks + bucket scatters)
        WITHOUT host sync; ``collect`` downloads and builds partials.
        The ``aggs.collect`` fault site fires here so an injected error
        surfaces through the batcher to the shard's host fallback."""
        faults.check("aggs.collect", index=self.index, shard=self.sid)
        import jax.numpy as jnp

        ex = self.ex
        t0 = time.perf_counter()
        q = self.query if self.query is not None else dsl.MatchAllQuery()
        seg_items = []
        for si, seg in enumerate(ex.reader.segments):
            n = seg.num_docs
            if n == 0:
                continue
            mask, scores = ex._exec(q, si)
            live = ex.reader.live_docs[si]
            if live is not None:
                mask = mask & jnp.asarray(live)
            tot, mx = agg_kernels.masked_total_and_max(mask, scores)
            topk = None
            if self.k > 0:
                topk = scoring.topk_hits(scores, mask, min(self.k, n))
            spec_outs = [
                sp.dispatch(ex, si, mask)
                if not isinstance(sp, _MetricSpec)
                else sp.dispatch_root(ex, si, mask)
                for _name, sp in self.specs
            ]
            seg_items.append((si, tot, mx, topk, spec_outs))
        return {"segs": seg_items, "t0": t0}

    def collect(self, pend: dict):
        ex = self.ex
        seg_items = pend["segs"]
        total = 0
        max_score = None
        cands: List[Tuple[float, int, int]] = []
        per_spec_pends: List[list] = [[] for _ in self.specs]
        for si, tot, mx, topk, spec_outs in seg_items:
            total += int(np.asarray(tot))
            mxf = float(np.asarray(mx))
            if np.isfinite(mxf):
                max_score = (
                    mxf if max_score is None else max(max_score, mxf)
                )
            if topk is not None:
                s, d = (np.asarray(x) for x in topk)
                finite = np.isfinite(s)
                for sc, doc in zip(s[finite], d[finite]):
                    cands.append((float(sc), si, int(doc)))
            for pi, out in enumerate(spec_outs):
                per_spec_pends[pi].append(out)
        partials = {}
        for (name, sp), pends in zip(self.specs, per_spec_pends):
            if isinstance(sp, _MetricSpec):
                partials[name] = sp.collect_root(pends)
            else:
                partials[name] = sp.collect(ex, pends)
        cands.sort(key=lambda c: (-c[0], c[1], c[2]))
        page = cands[: self.k]
        hits = [
            Hit(
                score=s,
                segment=si,
                local_doc=d,
                doc_id=ex.reader.segments[si].doc_ids[d],
            )
            for s, si, d in page
        ]
        td = TopDocs(
            total=total,
            hits=hits,
            max_score=(hits[0].score if hits else max_score),
            relation="eq",
        )
        note_kernel_ms((time.perf_counter() - pend["t0"]) * 1000.0)
        return td, partials


def try_compile(ex, nodes: Sequence[AggNode], mappings, index: str,
                sid: int, query, k: int) -> Optional[DeviceAggPlan]:
    """Compiles the tree to a device plan, or None when any node routes
    to the host (``ES_TPU_DEVICE_AGGS=force`` raises the reason instead
    so CI can assert device routing)."""
    from ..common.settings import device_aggs_mode

    mode = device_aggs_mode()
    if mode == "off":
        return None
    try:
        specs = [
            (n.name, _compile_node(ex, n, mappings))
            for n in nodes
            if n.type not in PIPELINE_TYPES
        ]
    except DeviceAggUnsupported:
        if mode == "force":
            raise
        return None
    except AggParseError:
        return None  # the host collector raises the user-facing error
    return DeviceAggPlan(ex, nodes, specs, index, sid, query, k)
