"""Shard-failure accounting for fault-tolerant search execution.

Reference analogs: org.elasticsearch.action.search.ShardSearchFailure
(the per-shard failure entries inside `_shards.failures`),
SearchPhaseExecutionException (the 503 raised when
allow_partial_search_results=false), and the per-request search
timeout (`SearchSourceBuilder.timeout()` → QueryPhase's cooperative
timer → `timed_out: true` with accumulated partial hits).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional


class SearchTimeoutError(Exception):
    """A shard exceeded the request's search timeout budget. The
    coordinator converts it into a timed-out shard entry + partial
    results rather than failing the request."""

    err_type = "timeout_exception"

    def __init__(self, reason: str = "search timed out"):
        super().__init__(reason)
        self.reason = reason


def failure_type(exc: BaseException) -> str:
    """Wire error type for an exception (ElasticsearchException
    .getExceptionName analog): explicit err_type attr when present,
    else the snake_cased class name."""
    et = getattr(exc, "err_type", None)
    if isinstance(et, str) and et:
        return et
    name = type(exc).__name__
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


def shard_failure(
    index: str, shard: int, node: Optional[str], exc: BaseException
) -> Dict[str, Any]:
    """One `_shards.failures[]` entry (ShardSearchFailure.toXContent
    shape: shard / index / node / nested reason {type, reason})."""
    return {
        "shard": int(shard),
        "index": index,
        "node": node,
        "reason": {"type": failure_type(exc), "reason": str(exc)},
    }


def parse_timeout(value) -> Optional[float]:
    """Request `timeout` → seconds. None / -1 / "-1" = no timeout;
    bare numbers are milliseconds (TimeValue's default search-timeout
    unit); "50ms"/"1s"/"2m" parse as usual. Malformed values raise."""
    if value is None:
        return None
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return None if value < 0 else float(value) / 1000.0
    s = str(value).strip()
    if s in ("", "-1"):
        return None
    for suffix, mult in (
        ("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0),
    ):
        if s.endswith(suffix):
            num = s[: -len(suffix)]
            try:
                return float(num) * mult
            except ValueError:
                break
    try:
        return float(s) / 1000.0
    except ValueError:
        raise ValueError(
            f"failed to parse setting [timeout] with value [{value}]"
        )


def parse_allow_partial(value, default: bool = True) -> bool:
    """allow_partial_search_results accepts bool or its string forms
    (the query-string path delivers strings)."""
    if value is None:
        return default
    if isinstance(value, bool):
        return value
    return str(value).lower() not in ("false", "0")


def deadline_from(body: dict) -> Optional[float]:
    """Monotonic deadline for a request body carrying `timeout`, or
    None when untimed."""
    t = parse_timeout(body.get("timeout"))
    if t is None:
        return None
    return time.monotonic() + t
