"""Highlighting: query-term fragments over stored _source text.

Reference analog: the highlight fetch sub-phase
(server/.../search/fetch/subphase/highlight/ — HighlightPhase with the
`unified` highlighter default, UnifiedHighlighter via Lucene). The
TPU-native engine stores no term vectors; like the unified highlighter's
re-analysis mode, the field's stored text is re-analyzed at fetch time,
matching tokens are located by their character offsets, and fragments of
~fragment_size characters are cut around match runs.

Term extraction walks the parsed query tree per field (the
WeightedSpanTermExtractor analog), including multi-term expansions
(prefix/wildcard/regexp/fuzzy are expanded against the segment term
dictionary by the caller's executor, so here we accept plain term sets).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from . import dsl
from .executor import expand_match_fields


def extract_highlight_terms(
    query: Optional[dsl.Query], mappings, analysis
) -> Dict[str, Set[str]]:
    """field → analyzed query terms that should highlight."""
    out: Dict[str, Set[str]] = {}

    def add(field: str, terms) -> None:
        out.setdefault(field, set()).update(terms)

    def analyzed(field: str, text: str) -> List[str]:
        mf = mappings.get(field)
        name = (mf.search_analyzer or mf.analyzer) if mf is not None else "standard"
        try:
            return analysis.get(name).terms(str(text))
        except ValueError:
            return [str(text)]

    def walk(q: Optional[dsl.Query]) -> None:
        if q is None:
            return
        if isinstance(q, dsl.MatchQuery):
            add(q.field, analyzed(q.field, q.query))
        elif isinstance(q, dsl.MatchPhraseQuery):
            add(q.field, analyzed(q.field, q.query))
        elif isinstance(q, dsl.TermQuery):
            add(q.field, [str(q.value).lower() if isinstance(q.value, str) else str(q.value)])
        elif isinstance(q, dsl.TermsQuery):
            add(q.field, [str(v) for v in q.values])
        elif isinstance(q, dsl.MultiMatchQuery):
            for fname, _ in expand_match_fields(mappings, q.fields):
                add(fname, analyzed(fname, q.query))
        elif isinstance(q, (dsl.PrefixQuery, dsl.WildcardQuery, dsl.RegexpQuery, dsl.FuzzyQuery)):
            # marker: caller may expand against the dictionary; highlight
            # the raw value as a best effort
            add(q.field, [q.value.lower()])
        elif isinstance(q, dsl.BoolQuery):
            for sub in list(q.must) + list(q.should):
                walk(sub)
            # filter/must_not clauses don't contribute highlights (ES:
            # only scoring clauses are extracted by default)
        elif isinstance(q, dsl.DisMaxQuery):
            for sub in q.queries:
                walk(sub)
        elif isinstance(q, dsl.BoostingQuery):
            walk(q.positive)
        elif isinstance(q, dsl.ConstantScoreQuery):
            walk(q.filter_query)
        elif isinstance(q, dsl.FunctionScoreQuery):
            walk(q.query)
        elif isinstance(q, dsl.QueryStringQuery):
            from .executor import rewrite_query_string

            walk(rewrite_query_string(q, mappings))

    walk(query)
    return out


def parse_highlight(body: dict) -> dict:
    """Normalizes the request's "highlight" object."""
    fields = body.get("fields")
    if not isinstance(fields, dict):
        raise dsl.QueryParseError("[highlight] requires [fields]")
    defaults = {
        "pre_tags": body.get("pre_tags", ["<em>"]),
        "post_tags": body.get("post_tags", ["</em>"]),
        "fragment_size": int(body.get("fragment_size", 100)),
        "number_of_fragments": int(body.get("number_of_fragments", 5)),
    }
    specs = {}
    for fname, cfg in fields.items():
        cfg = cfg or {}
        specs[fname] = {
            "pre": (cfg.get("pre_tags") or defaults["pre_tags"])[0],
            "post": (cfg.get("post_tags") or defaults["post_tags"])[0],
            "fragment_size": int(
                cfg.get("fragment_size", defaults["fragment_size"])
            ),
            "number_of_fragments": int(
                cfg.get("number_of_fragments", defaults["number_of_fragments"])
            ),
        }
    return specs


def highlight_field(
    text: str,
    terms: Set[str],
    analyzer,
    pre: str,
    post: str,
    fragment_size: int,
    number_of_fragments: int,
) -> List[str]:
    """Highlighted fragments for one field value (unified-style)."""
    if not text or not terms:
        return []
    tokens = analyzer.analyze(text)
    matches = [t for t in tokens if t.text in terms]
    if not matches:
        return []
    if number_of_fragments == 0:
        # whole-field highlighting
        return [_tag(text, matches, pre, post)]
    # group matches into fragments of ~fragment_size characters
    fragments: List[List] = []
    for m in matches:
        if fragments and m.start_offset - fragments[-1][0].start_offset < fragment_size:
            fragments[-1].append(m)
        else:
            fragments.append([m])
    out = []
    for group in fragments[:number_of_fragments]:
        first, last = group[0], group[-1]
        # expand the window to fragment_size, snapping to whitespace
        lo = max(0, first.start_offset - max(0, (fragment_size - (last.end_offset - first.start_offset)) // 2))
        hi = min(len(text), lo + max(fragment_size, last.end_offset - lo))
        if lo > 0:
            ws = text.rfind(" ", 0, lo + 1)
            lo = ws + 1 if ws >= 0 and lo - ws <= 20 else lo
        if hi < len(text):
            ws = text.find(" ", hi - 1)
            hi = ws if ws >= 0 and ws - hi <= 20 else hi
        frag = text[lo:hi]
        shifted = [
            t._replace(start_offset=t.start_offset - lo, end_offset=t.end_offset - lo)
            for t in group
            if t.start_offset >= lo and t.end_offset <= hi
        ]
        out.append(_tag(frag, shifted, pre, post))
    return out


def _tag(text: str, matches, pre: str, post: str) -> str:
    out = []
    cursor = 0
    for m in sorted(matches, key=lambda t: t.start_offset):
        if m.start_offset < cursor:
            continue  # overlapping token (ngrams); skip
        out.append(text[cursor : m.start_offset])
        out.append(pre)
        out.append(text[m.start_offset : m.end_offset])
        out.append(post)
        cursor = m.end_offset
    out.append(text[cursor:])
    return "".join(out)
