"""The `rescore` search phase: second-stage late-interaction reranking.

Reference analogs: org.elasticsearch.search.rescore — RescorerBuilder /
QueryRescorer (the `rescore` body element: window_size, query_weight,
rescore_query_weight) — with the rescore query replaced by a
late-interaction `rank_vectors` scorer (models/rerank.py): the
production multi-stage ranking shape (cheap first stage feeding a
ColBERT-style maxsim reranker over the top-k).

Execution shape (the GPUSparse lesson): the first stage's fused top-k
candidates already live on device at merge time, so reranking rides the
QueryBatcher as its own `rerank` job family BETWEEN merge and fetch —
one maxsim kernel launch per group (ops/rerank.py), one packed download
— instead of a host round trip per candidate. Sources are fetched only
AFTER the window is re-sorted. The numpy host oracle (host_rescore_*)
serves the numpy backend and is the float reference every device result
is parity-tested against; any device rerank-path failure degrades
DETERMINISTICALLY to the first-stage ranking (never a failed request).

DSL:

    "rescore": {
      "window_size": 50,
      "query": {
        "rescore_query": {"rank_vectors": {
            "field": "tok_emb", "query_vectors": [[...], ...]}},
        "query_weight": 1.0,
        "rescore_query_weight": 1.0
      }
    }

Window contract (QueryRescorer): the top `window_size` candidates are
re-sorted by `query_weight·first + rescore_query_weight·maxsim`
(ties keep first-stage order); candidates past the window keep their
first-stage score and order below the window.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..models import rerank as rerank_model
from . import dsl
from .executor import Hit, TopDocs


@dataclass(frozen=True)
class RescoreSpec:
    """Parsed `rescore` element. Frozen/hashable (vectors as tuples) so
    (spec, model) can ride batcher group keys."""

    field: str
    query_vectors: tuple  # tuple of tuples of float
    window_size: int
    query_weight: float = 1.0
    rescore_query_weight: float = 1.0


def parse_rescore(
    body: dict, validate_size: bool = True
) -> Optional[RescoreSpec]:
    """Parses (and request-scope validates) the body's `rescore`
    element; None when absent. `validate_size=False` skips the
    window-vs-page check — the shard re-parse sees the coordinator's
    collapsed size, so only the coordinator validates it."""
    raw = body.get("rescore")
    if raw is None:
        return None
    if isinstance(raw, list):
        if len(raw) != 1:
            raise dsl.QueryParseError(
                "[rescore] supports exactly one rescorer (this build)"
            )
        raw = raw[0]
    if not isinstance(raw, dict):
        raise dsl.QueryParseError("[rescore] malformed, expected an object")
    if validate_size and "sort" in body:
        raise dsl.QueryParseError(
            "Cannot use [sort] option in conjunction with [rescore]."
        )
    qblock = raw.get("query")
    if not isinstance(qblock, dict):
        raise dsl.QueryParseError("[rescore] requires a [query] element")
    rq = qblock.get("rescore_query")
    if not isinstance(rq, dict) or len(rq) != 1:
        raise dsl.QueryParseError(
            "[rescore] requires a [rescore_query]"
        )
    qname, params = next(iter(rq.items()))
    if qname != "rank_vectors":
        raise dsl.QueryParseError(
            f"[rescore] unsupported rescore_query [{qname}]: only "
            "[rank_vectors] late-interaction rescoring is supported "
            "(this build)"
        )
    if not isinstance(params, dict) or "field" not in params:
        raise dsl.QueryParseError("[rank_vectors] requires [field]")
    qv = params.get("query_vectors")
    if not isinstance(qv, list) or not qv:
        raise dsl.QueryParseError(
            "[rank_vectors] requires a non-empty [query_vectors] array"
        )
    rows = qv if isinstance(qv[0], (list, tuple)) else [qv]
    try:
        vecs = tuple(tuple(float(x) for x in row) for row in rows)
    except (TypeError, ValueError):
        raise dsl.QueryParseError(
            "[rank_vectors] query_vectors must be numeric vectors"
        )
    if len({len(r) for r in vecs}) != 1:
        raise dsl.QueryParseError(
            "[rank_vectors] query_vectors rows must share one dimension"
        )
    try:
        window = int(raw.get("window_size", 10))
    except (TypeError, ValueError):
        raise dsl.QueryParseError(
            f"[rescore] failed to parse [window_size]: "
            f"{raw.get('window_size')!r}"
        )
    if window < 1:
        raise dsl.QueryParseError(
            f"[rescore] [window_size] must be greater than 0, got "
            f"[{window}]"
        )
    if validate_size:
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        if window < size + from_:
            # KnnSearchBuilder-style request-scoped 400: a window
            # smaller than the page would silently leave page hits
            # un-rescored
            raise dsl.QueryParseError(
                f"[rescore] [window_size] must be at least the request "
                f"page (from + size = {size + from_}), got [{window}]"
            )
    try:
        qw = float(qblock.get("query_weight", 1.0))
        rw = float(qblock.get("rescore_query_weight", 1.0))
    except (TypeError, ValueError):
        raise dsl.QueryParseError(
            "[rescore] failed to parse rescore weights"
        )
    return RescoreSpec(
        field=str(params["field"]),
        query_vectors=vecs,
        window_size=window,
        query_weight=qw,
        rescore_query_weight=rw,
    )


# ---------------------------------------------------------------------------
# batcher plan (the `rerank` job family's payload)
# ---------------------------------------------------------------------------


class RerankPlan:
    """One request's rerank job: the prepared query-token matrix plus
    the first-stage candidates (global doc encoding over the shard's
    concatenated `rank_vectors` column). `sig` groups jobs that can
    share a maxsim launch: same model, same padded shapes, same blend
    weights and static window."""

    __slots__ = (
        "model", "spec", "qtoks", "first", "gdocs", "wb", "qb",
        "win_static", "sig", "field",
    )

    def __init__(self, model, spec: RescoreSpec, qtoks: np.ndarray,
                 first: np.ndarray, gdocs: np.ndarray):
        from ..ops import scoring

        self.model = model
        self.spec = spec
        self.qtoks = qtoks  # f32 [Qt, d] (prepared/normalized)
        self.first = first  # f32 [W_real] first-stage scores (desc)
        self.gdocs = gdocs  # i64 [W_real] global (segment-base + doc)
        self.field = model.field
        self.wb = max(16, scoring.next_bucket(max(len(first), 1), 16))
        self.qb = max(4, scoring.next_bucket(max(len(qtoks), 1), 4))
        self.win_static = min(int(spec.window_size), self.wb)
        self.sig = (
            model, self.wb, self.qb, self.win_static,
            float(spec.query_weight), float(spec.rescore_query_weight),
        )


def build_plan(reader, model, spec: RescoreSpec, cands) -> RerankPlan:
    """cands: [(score, segment, local_doc)] in first-stage order (score
    desc, (segment, doc) asc). Encodes (segment, doc) as global doc ids
    over the shard-level concatenated rerank column (segment bases are
    cumulative segment sizes — the same encoding rerank_column uses)."""
    bases = np.zeros(len(reader.segments) + 1, np.int64)
    np.cumsum([s.num_docs for s in reader.segments], out=bases[1:])
    qtoks = rerank_model.prepare_query_vectors(
        spec.query_vectors, model.dims, model.similarity
    )
    first = np.asarray([c[0] for c in cands], np.float32)
    gdocs = np.asarray(
        [bases[c[1]] + c[2] for c in cands], np.int64
    )
    return RerankPlan(model, spec, qtoks, first, gdocs)


def apply_perm_to_topdocs(
    td: TopDocs, scores: np.ndarray, perm: np.ndarray
) -> TopDocs:
    """Rebuilds a TopDocs from the rerank result: `perm[i]` is the
    first-stage rank now sitting at position i, `scores[i]` its blended
    (or retained first-stage) score."""
    hits: List[Hit] = []
    for s, p in zip(scores, perm):
        if not np.isfinite(s):
            break
        h = td.hits[int(p)]
        hits.append(
            Hit(score=float(s), segment=h.segment,
                local_doc=h.local_doc, doc_id=h.doc_id)
        )
    return TopDocs(
        total=td.total,
        hits=hits,
        max_score=hits[0].score if hits else None,
        relation=td.relation,
    )


# ---------------------------------------------------------------------------
# host float oracle application (numpy backend + parity reference)
# ---------------------------------------------------------------------------


def host_blend(
    reader, model, spec: RescoreSpec, cands
) -> Tuple[np.ndarray, np.ndarray]:
    """(scores, perm) for first-stage candidates [(score, segment,
    doc)], numpy float path — the reference the device kernel is
    parity-tested against. Same window/ordering contract."""
    qtoks = rerank_model.prepare_query_vectors(
        spec.query_vectors, model.dims, model.similarity
    )
    n = len(cands)
    w = min(int(spec.window_size), n)
    blended = np.empty(w, np.float64)
    for i, (score, si, doc) in enumerate(cands[:w]):
        mvf = reader.segments[si].multi_vectors.get(model.field)
        if mvf is None:
            msim = 0.0
        else:
            s0 = int(mvf.tok_offsets[doc])
            s1 = int(mvf.tok_offsets[doc + 1])
            msim = rerank_model.host_maxsim(qtoks, mvf.tok_vectors[s0:s1])
        blended[i] = (
            np.float32(spec.query_weight) * np.float32(score)
            + np.float32(spec.rescore_query_weight) * np.float32(msim)
        )
    order = sorted(range(w), key=lambda i: (-blended[i], i))
    perm = np.asarray(order + list(range(w, n)), np.int32)
    scores = np.concatenate(
        [
            blended[order].astype(np.float32),
            np.asarray([c[0] for c in cands[w:]], np.float32),
        ]
    )
    return scores, perm


def host_rescore_topdocs(reader, model, spec: RescoreSpec,
                         td: TopDocs) -> TopDocs:
    """Applies the host-oracle rescore to one shard's TopDocs."""
    cands = [(h.score, h.segment, h.local_doc) for h in td.hits]
    scores, perm = host_blend(reader, model, spec, cands)
    rerank_model.note_rescore(min(spec.window_size, len(cands)),
                              device=False)
    return apply_perm_to_topdocs(td, scores, perm)
