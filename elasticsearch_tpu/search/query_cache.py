"""Device-resident query & request caching.

Reference analogs (SURVEY.md §2.1 caching rows):

* ``FilterBitsetCache`` — org.apache.lucene.search.LRUQueryCache behind
  Elasticsearch's IndicesQueryCache: filter-context queries evaluate
  once per (shard, searchable-state generation, segment) into a bitset
  that is reused across requests. TPU-native twist: on the jax backend
  the cached bitset is the DEVICE-RESIDENT boolean mask the scoring
  kernels consume directly (HBM is the cache medium, charged to the
  ``query_cache`` ledger category); the NumPy oracle caches host-side
  packed bitmaps (``np.packbits``, one bit per doc).

* ``ShardRequestCache`` — org.elasticsearch.indices.IndicesRequestCache:
  whole shard-level responses for ``size: 0`` / aggregation-only
  requests, keyed by the canonical request bytes. Entries are stored as
  JSON strings so hits deserialize to fresh objects (no aliasing into
  the cache).

Invalidation model (both caches): the cache key embeds the shard
engine's ``change_generation`` — the counter ``index/engine.py`` bumps
whenever the searchable state changes (refresh that applied anything,
merge). A refresh-after-update/delete therefore can NEVER serve a stale
entry; superseded generations are purged eagerly when the shard's
executor regenerates and lazily by LRU pressure otherwise.

Memory policy (degrade-don't-fail, mirroring common/memory.py): before
an insert would exceed the cache budget or the HBM ledger, LRU entries
are EVICTED; if the entry still cannot fit the insert is skipped and
counted as a degraded allocation — the breaker never trips on a cache
fill, because an uncached filter is an optimization lost, not an error.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple


class CacheCtx:
    """Identity of one shard's searchable state for cache keying:
    ``shard_key`` is "<index uuid>[<shard id>]", ``generation`` the
    engine's change_generation at executor creation, ``backend`` tags
    the bitset flavor ("jax" device masks vs "np" packed host bits) so
    the two executors over one shard never alias entries."""

    __slots__ = ("shard_key", "generation", "backend")

    def __init__(self, shard_key: str, generation: int, backend: str):
        self.shard_key = shard_key
        self.generation = generation
        self.backend = backend

    @property
    def index_uuid(self) -> str:
        return self.shard_key.split("[", 1)[0]


def _zeroed_stats() -> Dict[str, int]:
    return {
        "memory_size_in_bytes": 0,
        "hit_count": 0,
        "miss_count": 0,
        "evictions": 0,
        "cache_count": 0,
    }


class _LruStatsMixin:
    """Shared LRU bookkeeping: entries ordered by recency, byte
    accounting, node-level + per-index-uuid counters."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Tuple, Tuple[Any, int]]" = OrderedDict()
        self._mem = 0
        self._node = _zeroed_stats()
        self._by_uuid: Dict[str, Dict[str, int]] = {}

    def _uuid_stats(self, uuid: str) -> Dict[str, int]:
        st = self._by_uuid.get(uuid)
        if st is None:
            st = self._by_uuid[uuid] = _zeroed_stats()
        return st

    def _count(self, uuid: str, stat: str, delta: int = 1) -> None:
        self._node[stat] += delta
        self._uuid_stats(uuid)[stat] += delta

    def _key_uuid(self, key: Tuple) -> str:
        return str(key[0]).split("[", 1)[0]

    def _pop_entry(self, key: Tuple, stat: str) -> int:
        _, nbytes = self._entries.pop(key)
        self._mem -= nbytes
        uuid = self._key_uuid(key)
        self._count(uuid, "memory_size_in_bytes", -nbytes)
        self._count(uuid, "cache_count", -1)
        if stat:
            self._count(uuid, stat)
        return nbytes

    def node_stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._node)

    def stats_for_index(self, uuid: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_uuid.get(uuid) or _zeroed_stats())

    def clear(self, uuids: Optional[Iterable[str]] = None) -> int:
        """Drops entries (for the given index uuids, or everything).
        Returns the number of entries removed."""
        wanted = set(uuids) if uuids is not None else None
        with self._lock:
            victims = [
                k
                for k in self._entries
                if wanted is None or self._key_uuid(k) in wanted
            ]
            for k in victims:
                self._release(k, self._pop_entry(k, ""))
            return len(victims)

    # subclasses release external accounting (the HBM ledger) here
    def _release(self, key: Tuple, nbytes: int) -> None:  # pragma: no cover
        pass


def _query_cache_budget() -> int:
    """Byte budget for cached filter bitsets: an explicit override, else
    a 10% share of the HBM ledger budget (the shape of ES's default
    ``indices.queries.cache.size: 10%``)."""
    env = os.environ.get("ES_TPU_QUERY_CACHE_BUDGET_BYTES")
    if env:
        return int(env)
    from ..common.memory import hbm_ledger

    return hbm_ledger.budget // 10


class FilterBitsetCache(_LruStatsMixin):
    """LRU cache of evaluated filter-context bitsets, keyed
    (shard_key, backend, generation, segment index, canonical filter
    key). Bytes are charged to the HBM ledger's ``query_cache``
    category; eviction runs BEFORE the ledger would trip."""

    CATEGORY = "query_cache"

    def get(self, ctx: CacheCtx, si: int, fkey: str):
        key = (ctx.shard_key, ctx.backend, ctx.generation, si, fkey)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._count(ctx.index_uuid, "miss_count")
                return None
            self._entries.move_to_end(key)
            self._count(ctx.index_uuid, "hit_count")
            return entry[0]

    def put(self, ctx: CacheCtx, si: int, fkey: str, mask, nbytes: int) -> bool:
        """Inserts a bitset, LRU-evicting to make room; returns False
        (and counts a degraded allocation) when the bitset cannot fit
        even with the cache emptied."""
        from ..common.memory import hbm_ledger

        key = (ctx.shard_key, ctx.backend, ctx.generation, si, fkey)
        with self._lock:
            if key in self._entries:
                return True
            budget = _query_cache_budget()
            while self._entries and (
                self._mem + nbytes > budget
                or not hbm_ledger.would_fit(nbytes)
            ):
                old = next(iter(self._entries))
                self._release(old, self._pop_entry(old, "evictions"))
            if self._mem + nbytes > budget or not hbm_ledger.would_fit(nbytes):
                hbm_ledger.note_degraded()
                return False
            hbm_ledger.add(self.CATEGORY, nbytes, breaker=False)
            self._entries[key] = (mask, nbytes)
            self._mem += nbytes
            uuid = ctx.index_uuid
            self._count(uuid, "memory_size_in_bytes", nbytes)
            self._count(uuid, "cache_count")
            return True

    def invalidate_shard(self, shard_key: str, keep_generation: int) -> int:
        """Eagerly drops every generation but ``keep_generation`` for one
        shard (called when the shard's executor regenerates after a
        refresh/merge — the key's generation already guarantees no stale
        HIT; this reclaims the superseded bitsets' HBM)."""
        with self._lock:
            victims = [
                k
                for k in self._entries
                if k[0] == shard_key and k[2] != keep_generation
            ]
            for k in victims:
                self._release(k, self._pop_entry(k, "evictions"))
            return len(victims)

    def _release(self, key: Tuple, nbytes: int) -> None:
        from ..common.memory import hbm_ledger

        hbm_ledger.release(self.CATEGORY, nbytes)


def _request_cache_budget() -> int:
    env = os.environ.get("ES_TPU_REQUEST_CACHE_BUDGET_BYTES")
    if env:
        return int(env)
    return 64 * 1024 * 1024


class ShardRequestCache(_LruStatsMixin):
    """LRU cache of whole shard-level responses for size:0/agg-only
    requests, keyed (shard_key, refresh generation, canonical request
    bytes). Host memory with its own byte budget (request responses are
    JSON, not device arrays)."""

    def get(self, shard_key: str, generation: int, body_key: str):
        key = (shard_key, generation, body_key)
        with self._lock:
            entry = self._entries.get(key)
            uuid = self._key_uuid(key)
            if entry is None:
                self._count(uuid, "miss_count")
                return None
            self._entries.move_to_end(key)
            self._count(uuid, "hit_count")
        # deserialize OUTSIDE the lock: hits must hand back fresh
        # objects (reducers mutate responses)
        return json.loads(entry[0])

    def put(self, shard_key: str, generation: int, body_key: str,
            response: dict) -> bool:
        try:
            blob = json.dumps(response)
        except (TypeError, ValueError):
            return False  # non-JSON payload (exotic agg partial): skip
        nbytes = len(blob) + len(body_key)
        key = (shard_key, generation, body_key)
        with self._lock:
            if key in self._entries:
                return True
            # purge superseded generations of this shard eagerly: the
            # refresh that bumped the generation made them unreachable
            stale = [
                k
                for k in self._entries
                if k[0] == shard_key and k[1] != generation
            ]
            for k in stale:
                self._pop_entry(k, "evictions")
            budget = _request_cache_budget()
            if nbytes > budget:
                return False
            while self._entries and self._mem + nbytes > budget:
                old = next(iter(self._entries))
                self._pop_entry(old, "evictions")
            self._entries[key] = (blob, nbytes)
            self._mem += nbytes
            uuid = self._key_uuid(key)
            self._count(uuid, "memory_size_in_bytes", nbytes)
            self._count(uuid, "cache_count")
            return True

    def invalidate_shard(self, shard_key: str, keep_generation: int) -> int:
        with self._lock:
            victims = [
                k
                for k in self._entries
                if k[0] == shard_key and k[1] != keep_generation
            ]
            for k in victims:
                self._pop_entry(k, "evictions")
            return len(victims)


# keys whose presence anywhere in a search body makes the response
# non-deterministic or side-effectful — never request-cached (the
# reference's "requests that use now/scripts are not cached")
_RC_FORBIDDEN_KEYS = frozenset(
    {
        "script",
        "script_fields",
        "script_score",
        "random_score",
        "percolate",
        "more_like_this",
        "pit",
        "search_after",
    }
)


def request_cacheable_body(node: Any) -> bool:
    """True when no forbidden key appears anywhere in the body tree."""
    if isinstance(node, dict):
        return all(
            k not in _RC_FORBIDDEN_KEYS and request_cacheable_body(v)
            for k, v in node.items()
        )
    if isinstance(node, (list, tuple)):
        return all(request_cacheable_body(v) for v in node)
    return True


# process-wide singletons (node-level caches, like IndicesQueryCache /
# IndicesRequestCache being node services in the reference)
filter_cache = FilterBitsetCache()
request_cache = ShardRequestCache()
