"""Learned-sparse routing + observability for the `sparse_vector` path.

The decision layer between the DSL and the impact kernels
(ops/impact.py): an index picks its impact storage via
`index.sparse.quantization` (`int8` — the default, 4x smaller postings
with per-term symmetric scales — or `none` for full-fidelity fp32); a
request opts into the fp32 column regardless via a body-level
`"exact": true` (the same escape hatch the ANN tier honors). Pruning
is always the exact impact-ordered block-max pass — it never changes
the returned hits, only how many tiles get scored — so there is no
recall knob to resolve here; the only lossy choice is int8 storage,
and even that is gated by a recall@10 ≥ 0.95 floor in tier-1.

The dense host oracle (NumpyExecutor's term-at-a-time fp32 scorer) is
never removed: every device-path failure (injected `sparse.score`
fault, HBM budget breach, missing column) deterministically falls back
to it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class SparseSpec:
    """Resolved per-request sparse serving parameters. Frozen/hashable
    so it can ride the batcher's group key (int8 and fp32 servings of
    the same field never share a launch) and key the executor's
    per-generation column cache."""

    quantized: bool


def resolve(settings, body_exact: bool) -> SparseSpec:
    """SparseSpec for one sparse_vector query under one index's
    settings. Unlike ANN there is no exact-vs-approximate fork in the
    *plan* — only the storage column changes."""
    quant = str(settings.get("sparse.quantization", "int8")) == "int8"
    if body_exact and quant:
        note("exact_searches")
        quant = False
    return SparseSpec(quantized=quant)


# ---------------------------------------------------------------------------
# observability: the `sparse` block of `_nodes/stats`
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
SPARSE_STATS = {
    "searches": 0,  # (job × segment) scorings served from impact tiles
    "quantized_searches": 0,  # of those, served from the int8 column
    "exact_searches": 0,  # body-level exact:true escape-hatch routings
    "fallbacks": 0,  # device-path failures → host dense oracle
    "tiles_scored": 0,  # Σ tiles actually launched
    "tiles_pruned": 0,  # Σ tail tiles dropped by block-max bounds
    "pruned_searches": 0,  # scorings where at least one tile dropped
    # bytes of the impact VALUE planes actually uploaded vs what the
    # same planes would cost at fp32 — the headline int8 compression
    # ratio (4x per plane; ≥2x smaller gated in tier-1). The doc-id
    # planes are identical in both modes and are counted in
    # `ledger_bytes` with the rest of the upload.
    "impact_bytes": 0,
    "impact_fp32_equivalent_bytes": 0,
}


def note(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        SPARSE_STATS[key] += n


def note_search(
    jobs: int, quantized: bool, tiles_scored: int, tiles_pruned: int
) -> None:
    """One impact-tile scoring of `jobs` queries against one segment."""
    with _STATS_LOCK:
        SPARSE_STATS["searches"] += jobs
        if quantized:
            SPARSE_STATS["quantized_searches"] += jobs
        SPARSE_STATS["tiles_scored"] += tiles_scored
        SPARSE_STATS["tiles_pruned"] += tiles_pruned
        if tiles_pruned:
            SPARSE_STATS["pruned_searches"] += jobs


def stats_snapshot() -> dict:
    """The `sparse` stats block (ledger bytes from the `impacts` HBM
    category joined in)."""
    from ..common.memory import hbm_ledger

    with _STATS_LOCK:
        out = dict(SPARSE_STATS)
    out["ledger_bytes"] = int(
        hbm_ledger.stats()["by_category"].get("impacts", 0)
    )
    return out


def reset_stats() -> None:
    """Test hook: zero the counters."""
    with _STATS_LOCK:
        for k in SPARSE_STATS:
            SPARSE_STATS[k] = 0
