"""Query DSL: JSON → query node tree.

Parity target: org.elasticsearch.index.query — AbstractQueryBuilder
parsing and the concrete builders (MatchQueryBuilder, BoolQueryBuilder,
TermQueryBuilder, TermsQueryBuilder, MultiMatchQueryBuilder,
RangeQueryBuilder, ExistsQueryBuilder, MatchAllQueryBuilder,
ConstantScoreQueryBuilder, MatchPhraseQueryBuilder), plus the top-level
`knn` search section (KnnSearchBuilder, server/.../search/vectors/).

The tree is executor-agnostic; both the NumPy oracle and the JAX executor
walk it producing dense (match-mask, score) pairs per segment — the
TPU-native replacement for Lucene's Weight/Scorer pull iterators.
"""

from __future__ import annotations

import math

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional


class QueryParseError(ValueError):
    pass


@dataclass
class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class MatchQuery(Query):
    field: str = ""
    query: str = ""
    operator: str = "or"  # or | and
    minimum_should_match: Optional[str] = None
    analyzer: Optional[str] = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    query: str = ""
    slop: int = 0
    analyzer: Optional[str] = None


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: List[Any] = dc_field(default_factory=list)


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class MultiMatchQuery(Query):
    query: str = ""
    fields: List[str] = dc_field(default_factory=list)  # may carry ^boost
    type: str = "best_fields"  # best_fields | most_fields | cross_fields
    operator: str = "or"
    tie_breaker: float = 0.0


@dataclass
class BoolQuery(Query):
    must: List[Query] = dc_field(default_factory=list)
    should: List[Query] = dc_field(default_factory=list)
    filter: List[Query] = dc_field(default_factory=list)
    must_not: List[Query] = dc_field(default_factory=list)
    minimum_should_match: Optional[Any] = None


@dataclass
class ConstantScoreQuery(Query):
    filter_query: Query = None  # type: ignore[assignment]


@dataclass
class ScoreFunction:
    """One function_score entry: optional filter + weight and/or
    field_value_factor (FunctionScoreQueryBuilder.FilterFunctionBuilder)."""

    filter: Optional[Query] = None
    weight: Optional[float] = None
    field_value_factor: Optional[dict] = None  # {field, factor, modifier, missing}
    random_score: Optional[dict] = None  # {seed, field}
    script_score: Optional[dict] = None  # {"script": {...}} (ScriptScoreFunction)


@dataclass
class FunctionScoreQuery(Query):
    query: Query = None  # type: ignore[assignment]
    functions: List[ScoreFunction] = dc_field(default_factory=list)
    score_mode: str = "multiply"  # multiply | sum | avg | max | min | first
    boost_mode: str = "multiply"  # multiply | sum | replace | avg | max | min
    max_boost: Optional[float] = None
    min_score: Optional[float] = None


@dataclass
class MatchPhrasePrefixQuery(Query):
    """match_phrase_prefix: phrase whose LAST term is a prefix expanded
    against the term dictionary (MatchPhrasePrefixQueryBuilder)."""

    field: str = ""
    query: str = ""
    slop: int = 0
    max_expansions: int = 50
    analyzer: Optional[str] = None


@dataclass
class SpanTermQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class SpanNearQuery(Query):
    """span_near over span_term clauses on one field: proximity with
    slop + in_order (SpanNearQueryBuilder)."""

    clauses: List[SpanTermQuery] = dc_field(default_factory=list)
    slop: int = 0
    in_order: bool = True


@dataclass
class MoreLikeThisQuery(Query):
    """more_like_this: select interesting terms from the liked text/docs
    by tf-idf, rewrite to a should-bool (MoreLikeThisQueryBuilder)."""

    fields: List[str] = dc_field(default_factory=list)
    like: List[Any] = dc_field(default_factory=list)  # strings | {"_id": x}
    max_query_terms: int = 25
    min_term_freq: int = 2
    min_doc_freq: int = 5
    minimum_should_match: str = "30%"


@dataclass
class GeoDistanceQuery(Query):
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0


@dataclass
class GeoBoundingBoxQuery(Query):
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0


@dataclass
class NestedQuery(Query):
    """nested query: the inner query must match WITHIN one nested
    object (NestedQueryBuilder). Objects are evaluated per document
    against _source — the semantics the reference gets from separate
    hidden Lucene docs."""

    path: str = ""
    query: dict = dc_field(default_factory=dict)  # raw DSL, per-object eval
    score_mode: str = "avg"
    inner_hits: Optional[dict] = None  # {name?, size?, _source?}


@dataclass
class PercolateQuery(Query):
    """percolate query: match STORED queries against provided docs
    (modules/percolator — PercolateQueryBuilder)."""

    field: str = "query"
    documents: List[dict] = dc_field(default_factory=list)


@dataclass
class ScriptScoreQuery(Query):
    """script_score query: base query matches, the script replaces the
    score (ScriptScoreQueryBuilder — the reference's brute-force kNN
    vehicle via cosineSimilarity, SURVEY.md §3.4)."""

    query: Query = None  # type: ignore[assignment]
    script: Any = None
    min_score: Optional[float] = None


@dataclass
class ScriptQuery(Query):
    """script query (filter context): the script decides matching per
    doc (ScriptQueryBuilder)."""

    script: Any = None


@dataclass
class IdsQuery(Query):
    values: List[str] = dc_field(default_factory=list)


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class WildcardQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class RegexpQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: str = "AUTO"
    prefix_length: int = 0
    max_expansions: int = 50


@dataclass
class DisMaxQuery(Query):
    queries: List[Query] = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class BoostingQuery(Query):
    positive: Query = None  # type: ignore[assignment]
    negative: Query = None  # type: ignore[assignment]
    negative_boost: float = 0.0


@dataclass
class QueryStringQuery(Query):
    """query_string / simple_query_string lite: terms, field:term,
    quoted phrases, AND/OR/NOT (query_string) — no grouping parens."""

    query: str = ""
    default_field: Optional[str] = None
    fields: List[str] = dc_field(default_factory=list)
    default_operator: str = "or"
    simple: bool = False


@dataclass
class KnnSection:
    """Top-level `knn` search element (can also appear as a query clause)."""

    field: str
    query_vector: List[float]
    k: int = 10
    num_candidates: int = 100
    filter: Optional[Query] = None
    boost: float = 1.0
    similarity: Optional[float] = None  # min-similarity cutoff
    nprobe: Optional[int] = None  # per-request IVF probe override
    # resolved search/ann.AnnSpec (set by IndexService when the index
    # routes this section through the IVF tier; None = exact path)
    ann: Optional[object] = None


_SINGLE_KEY_ERR = "[%s] query malformed, no start_object after query name"


def parse_query(body: Any) -> Query:
    """Parses one query object ({"match": {...}} etc.)."""
    if not isinstance(body, dict) or len(body) != 1:
        if isinstance(body, dict) and len(body) == 0:
            raise QueryParseError("query malformed, empty clause found")
        raise QueryParseError(
            "[bool] malformed query, expected a single query name"
        )
    name, params = next(iter(body.items()))
    parser = _PARSERS.get(name)
    if parser is None:
        raise QueryParseError(f"unknown query [{name}]")
    node = parser(params)
    # ES rejects negative boost at parse time (AbstractQueryBuilder
    # .boost); a negative weight would also corrupt the fused kernel's
    # sign-encoded count flag
    if getattr(node, "boost", 1.0) < 0:
        raise QueryParseError(
            f"[{name}] negative [boost] is not allowed"
        )
    return node


def _field_params(params: dict, qname: str) -> tuple:
    if not isinstance(params, dict) or len(params) != 1:
        raise QueryParseError(f"[{qname}] query doesn't support multiple fields")
    fname, cfg = next(iter(params.items()))
    return fname, cfg


def _parse_match(params):
    fname, cfg = _field_params(params, "match")
    if isinstance(cfg, dict):
        return MatchQuery(
            field=fname,
            query=str(cfg.get("query", "")),
            operator=str(cfg.get("operator", "or")).lower(),
            minimum_should_match=cfg.get("minimum_should_match"),
            analyzer=cfg.get("analyzer"),
            boost=float(cfg.get("boost", 1.0)),
        )
    return MatchQuery(field=fname, query=str(cfg))


def _parse_match_phrase(params):
    fname, cfg = _field_params(params, "match_phrase")
    if isinstance(cfg, dict):
        return MatchPhraseQuery(
            field=fname,
            query=str(cfg.get("query", "")),
            slop=int(cfg.get("slop", 0)),
            analyzer=cfg.get("analyzer"),
            boost=float(cfg.get("boost", 1.0)),
        )
    return MatchPhraseQuery(field=fname, query=str(cfg))


def _parse_term(params):
    fname, cfg = _field_params(params, "term")
    if isinstance(cfg, dict):
        return TermQuery(
            field=fname, value=cfg.get("value"), boost=float(cfg.get("boost", 1.0))
        )
    return TermQuery(field=fname, value=cfg)


def _parse_terms(params):
    params = dict(params)
    boost = float(params.pop("boost", 1.0))
    if len(params) != 1:
        raise QueryParseError("[terms] query requires exactly one field")
    fname, values = next(iter(params.items()))
    if not isinstance(values, list):
        raise QueryParseError("[terms] query requires an array of values")
    return TermsQuery(field=fname, values=values, boost=boost)


def _parse_range(params):
    fname, cfg = _field_params(params, "range")
    if not isinstance(cfg, dict):
        raise QueryParseError("[range] query malformed")
    known = {"gte", "gt", "lte", "lt", "boost", "format", "relation", "time_zone"}
    for k in cfg:
        if k not in known:
            raise QueryParseError(f"[range] query does not support [{k}]")
    return RangeQuery(
        field=fname,
        gte=cfg.get("gte"),
        gt=cfg.get("gt"),
        lte=cfg.get("lte"),
        lt=cfg.get("lt"),
        boost=float(cfg.get("boost", 1.0)),
    )


def _parse_exists(params):
    if "field" not in params:
        raise QueryParseError("[exists] query requires [field]")
    return ExistsQuery(field=params["field"], boost=float(params.get("boost", 1.0)))


def _parse_multi_match(params):
    if "query" not in params:
        raise QueryParseError("[multi_match] query requires [query]")
    return MultiMatchQuery(
        query=str(params["query"]),
        fields=list(params.get("fields", [])),
        type=params.get("type", "best_fields"),
        operator=str(params.get("operator", "or")).lower(),
        tie_breaker=float(params.get("tie_breaker", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _as_list(v):
    return v if isinstance(v, list) else [v]


def _parse_bool(params):
    return BoolQuery(
        must=[parse_query(q) for q in _as_list(params.get("must", []))],
        should=[parse_query(q) for q in _as_list(params.get("should", []))],
        filter=[parse_query(q) for q in _as_list(params.get("filter", []))],
        must_not=[parse_query(q) for q in _as_list(params.get("must_not", []))],
        minimum_should_match=params.get("minimum_should_match"),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_constant_score(params):
    if "filter" not in params:
        raise QueryParseError("[constant_score] requires a filter")
    return ConstantScoreQuery(
        filter_query=parse_query(params["filter"]),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_match_all(params):
    params = params or {}
    return MatchAllQuery(boost=float(params.get("boost", 1.0)))


def _parse_match_none(params):
    return MatchNoneQuery()


def _parse_knn_query(params):
    return KnnQueryWrapper(parse_knn(params))


@dataclass
class SparseVectorQuery(Query):
    """`sparse_vector` query over a learned term→weight map (ES 8.15
    SparseVectorQueryBuilder shape): score = Σ query_weight · impact
    over the terms both sides share. Served from the device-resident
    impact-ordered postings (ops/impact.py) with the dense fp32 host
    scorer as oracle."""

    field: str = ""
    query_vector: Dict[str, float] = dc_field(default_factory=dict)
    boost: float = 1.0
    # resolved search/sparse.SparseSpec (set by IndexService from the
    # index's sparse.quantization setting + body-level exact flag)
    sparse: Optional[object] = None


def parse_sparse_vector(params) -> SparseVectorQuery:
    if not isinstance(params, dict) or "field" not in params:
        raise QueryParseError("[sparse_vector] requires [field]")
    qv = params.get("query_vector")
    if not isinstance(qv, dict) or not qv:
        # missing, wrong-shaped and {}-empty maps are all the same
        # request bug; catching it at parse keeps it a 400, not a
        # shard-side 500
        raise QueryParseError(
            "[sparse_vector] requires a non-empty [query_vector] "
            "term→weight object"
        )
    terms: Dict[str, float] = {}
    for t, w in qv.items():
        if isinstance(w, bool) or not isinstance(w, (int, float)):
            raise QueryParseError(
                f"[sparse_vector] weight for term [{t}] must be a "
                f"number, got [{w!r}]"
            )
        w = float(w)
        if not math.isfinite(w):
            raise QueryParseError(
                f"[sparse_vector] weight for term [{t}] must be finite"
            )
        terms[str(t)] = w
    return SparseVectorQuery(
        field=str(params["field"]),
        query_vector=terms,
        boost=float(params.get("boost", 1.0)),
    )


@dataclass
class KnnQueryWrapper(Query):
    """`knn` used as a query clause (ES 8.12+)."""

    knn: KnnSection = None  # type: ignore[assignment]


def parse_knn(params: dict) -> KnnSection:
    if "field" not in params or "query_vector" not in params:
        raise QueryParseError("[knn] requires [field] and [query_vector]")
    try:
        k = int(params.get("k", 10))
    except (TypeError, ValueError):
        raise QueryParseError(f"[knn] failed to parse [k]: {params.get('k')!r}")
    if k < 1:
        raise QueryParseError(f"[knn] [k] must be greater than 0, got [{k}]")
    try:
        num_candidates = int(params.get("num_candidates", max(100, k)))
    except (TypeError, ValueError):
        raise QueryParseError(
            "[knn] failed to parse [num_candidates]: "
            f"{params.get('num_candidates')!r}"
        )
    if num_candidates < k:
        # request-scoped 400 (KnnSearchBuilder's "[num_candidates] cannot
        # be less than [k]"), not a server-side error downstream
        raise QueryParseError(
            f"[knn] [num_candidates] cannot be less than [k]; got "
            f"num_candidates=[{num_candidates}], k=[{k}]"
        )
    nprobe = params.get("nprobe")
    if nprobe is not None:
        try:
            nprobe = int(nprobe)
        except (TypeError, ValueError):
            raise QueryParseError(
                f"[knn] failed to parse [nprobe]: {params.get('nprobe')!r}"
            )
        if nprobe < 1:
            raise QueryParseError(
                f"[knn] [nprobe] must be greater than 0, got [{nprobe}]"
            )
    return KnnSection(
        field=params["field"],
        query_vector=[float(x) for x in params["query_vector"]],
        k=k,
        num_candidates=num_candidates,
        filter=parse_query(params["filter"]) if params.get("filter") else None,
        boost=float(params.get("boost", 1.0)),
        similarity=params.get("similarity"),
        nprobe=nprobe,
    )


def _parse_ids(params):
    values = params.get("values")
    if not isinstance(values, list):
        raise QueryParseError("[ids] query requires [values] array")
    return IdsQuery(values=[str(v) for v in values], boost=float(params.get("boost", 1.0)))


def _parse_simple_pattern(cls, qname):
    def parse(params):
        fname, cfg = _field_params(params, qname)
        if isinstance(cfg, dict):
            value = cfg.get("value", cfg.get(qname, ""))
            if qname == "wildcard" and value == "" and "wildcard" in cfg:
                value = cfg["wildcard"]
            return cls(
                field=fname,
                value=str(value),
                case_insensitive=bool(cfg.get("case_insensitive", False)),
                boost=float(cfg.get("boost", 1.0)),
            )
        return cls(field=fname, value=str(cfg))

    return parse


def _parse_fuzzy(params):
    fname, cfg = _field_params(params, "fuzzy")
    if isinstance(cfg, dict):
        return FuzzyQuery(
            field=fname,
            value=str(cfg.get("value", "")),
            fuzziness=str(cfg.get("fuzziness", "AUTO")),
            prefix_length=int(cfg.get("prefix_length", 0)),
            max_expansions=int(cfg.get("max_expansions", 50)),
            boost=float(cfg.get("boost", 1.0)),
        )
    return FuzzyQuery(field=fname, value=str(cfg))


def _parse_dis_max(params):
    qs = params.get("queries")
    if not isinstance(qs, list) or not qs:
        raise QueryParseError("[dis_max] query requires [queries] array")
    return DisMaxQuery(
        queries=[parse_query(q) for q in qs],
        tie_breaker=float(params.get("tie_breaker", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_boosting(params):
    if "positive" not in params or "negative" not in params:
        raise QueryParseError("[boosting] requires [positive] and [negative]")
    return BoostingQuery(
        positive=parse_query(params["positive"]),
        negative=parse_query(params["negative"]),
        negative_boost=float(params.get("negative_boost", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_function_score(params):
    inner = (
        parse_query(params["query"]) if "query" in params else MatchAllQuery()
    )
    functions: List[ScoreFunction] = []
    raw_fns = params.get("functions")
    if raw_fns is None:
        raw_fns = []
        # single-function shorthand at the top level
        single = {
            k: params[k]
            for k in ("weight", "field_value_factor", "random_score", "script_score")
            if k in params
        }
        if single:
            raw_fns = [single]
    for fn in raw_fns:
        if not isinstance(fn, dict):
            raise QueryParseError("[function_score] malformed function")
        known = {
            "filter", "weight", "field_value_factor", "random_score",
            "script_score",
        }
        unknown = set(fn) - known
        if unknown:
            raise QueryParseError(
                f"[function_score] unsupported function [{sorted(unknown)[0]}]"
            )
        functions.append(
            ScoreFunction(
                filter=parse_query(fn["filter"]) if "filter" in fn else None,
                weight=float(fn["weight"]) if "weight" in fn else None,
                field_value_factor=fn.get("field_value_factor"),
                random_score=fn.get("random_score"),
                script_score=fn.get("script_score"),
            )
        )
    return FunctionScoreQuery(
        query=inner,
        functions=functions,
        score_mode=str(params.get("score_mode", "multiply")),
        boost_mode=str(params.get("boost_mode", "multiply")),
        max_boost=float(params["max_boost"]) if "max_boost" in params else None,
        min_score=params.get("min_score"),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_match_phrase_prefix(params):
    if not isinstance(params, dict) or len(params) != 1:
        raise QueryParseError("[match_phrase_prefix] requires one field")
    field, spec = next(iter(params.items()))
    if isinstance(spec, dict):
        return MatchPhrasePrefixQuery(
            field=field,
            query=str(spec.get("query", "")),
            slop=int(spec.get("slop", 0)),
            max_expansions=int(spec.get("max_expansions", 50)),
            analyzer=spec.get("analyzer"),
            boost=float(spec.get("boost", 1.0)),
        )
    return MatchPhrasePrefixQuery(field=field, query=str(spec))


def _parse_span_term(params):
    if not isinstance(params, dict) or len(params) != 1:
        raise QueryParseError("[span_term] requires one field")
    field, spec = next(iter(params.items()))
    if isinstance(spec, dict):
        return SpanTermQuery(
            field=field,
            value=str(spec.get("value", "")),
            boost=float(spec.get("boost", 1.0)),
        )
    return SpanTermQuery(field=field, value=str(spec))


def _parse_span_near(params):
    raw = params.get("clauses")
    if not isinstance(raw, list) or not raw:
        raise QueryParseError("[span_near] requires [clauses]")
    clauses = []
    for c in raw:
        q = parse_query(c)
        if not isinstance(q, SpanTermQuery):
            raise QueryParseError(
                "[span_near] clauses must be span_term queries (this build)"
            )
        clauses.append(q)
    if len({c.field for c in clauses}) != 1:
        raise QueryParseError("[span_near] clauses must target one field")
    return SpanNearQuery(
        clauses=clauses,
        slop=int(params.get("slop", 0)),
        in_order=bool(params.get("in_order", True)),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_more_like_this(params):
    like = params.get("like")
    if like is None:
        raise QueryParseError("[more_like_this] requires [like]")
    return MoreLikeThisQuery(
        fields=list(params.get("fields", [])),
        like=like if isinstance(like, list) else [like],
        max_query_terms=int(params.get("max_query_terms", 25)),
        min_term_freq=int(params.get("min_term_freq", 2)),
        min_doc_freq=int(params.get("min_doc_freq", 5)),
        minimum_should_match=str(params.get("minimum_should_match", "30%")),
        boost=float(params.get("boost", 1.0)),
    )


def _geo_point(v):
    try:
        if isinstance(v, dict):
            return float(v["lat"]), float(v["lon"])
        if isinstance(v, str):
            parts = v.split(",")
            if len(parts) != 2:
                raise QueryParseError(f"malformed geo point [{v}]")
            return float(parts[0]), float(parts[1])
        if isinstance(v, (list, tuple)) and len(v) == 2:
            return float(v[1]), float(v[0])  # GeoJSON [lon, lat]
    except (TypeError, ValueError, KeyError):
        raise QueryParseError(f"malformed geo point [{v}]")
    raise QueryParseError(f"malformed geo point [{v}]")


_DIST_UNITS = {
    "mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
    "mi": 1609.344, "miles": 1609.344, "yd": 0.9144, "ft": 0.3048,
    "in": 0.0254, "nmi": 1852.0, "NM": 1852.0,
}


def parse_distance_meters(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    txt = str(s).strip()
    try:
        for unit in sorted(_DIST_UNITS, key=len, reverse=True):
            if txt.endswith(unit):
                return float(txt[: -len(unit)]) * _DIST_UNITS[unit]
        return float(txt)
    except ValueError:
        raise QueryParseError(f"failed to parse distance [{s}]")


def _parse_geo_distance(params):
    dist = params.get("distance")
    if dist is None:
        raise QueryParseError("[geo_distance] requires [distance]")
    field = None
    point = None
    for k, v in params.items():
        if k in ("distance", "distance_type", "validation_method", "boost"):
            continue
        field, point = k, v
    if field is None:
        raise QueryParseError("[geo_distance] requires a field")
    lat, lon = _geo_point(point)
    return GeoDistanceQuery(
        field=field,
        lat=lat,
        lon=lon,
        distance_m=parse_distance_meters(dist),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_geo_bounding_box(params):
    field = None
    spec = None
    for k, v in params.items():
        if k in ("validation_method", "type", "boost"):
            continue
        field, spec = k, v
    if field is None or not isinstance(spec, dict):
        raise QueryParseError("[geo_bounding_box] requires a field")
    tl = spec.get("top_left")
    br = spec.get("bottom_right")
    if tl is None or br is None:
        raise QueryParseError(
            "[geo_bounding_box] requires [top_left] and [bottom_right]"
        )
    top, left = _geo_point(tl)
    bottom, right = _geo_point(br)
    return GeoBoundingBoxQuery(
        field=field, top=top, left=left, bottom=bottom, right=right,
        boost=float(params.get("boost", 1.0)),
    )


def _parse_nested(params):
    if "path" not in params or "query" not in params:
        raise QueryParseError("[nested] requires [path] and [query]")
    return NestedQuery(
        path=str(params["path"]),
        query=params["query"],
        score_mode=str(params.get("score_mode", "avg")),
        inner_hits=params.get("inner_hits"),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_percolate(params):
    field = params.get("field")
    if not field:
        raise QueryParseError("[percolate] requires [field]")
    docs = params.get("documents")
    if docs is None:
        doc = params.get("document")
        if doc is None:
            raise QueryParseError(
                "[percolate] requires [document] or [documents]"
            )
        docs = [doc]
    return PercolateQuery(
        field=str(field),
        documents=list(docs),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_script_score(params):
    if "query" not in params or "script" not in params:
        raise QueryParseError("[script_score] requires [query] and [script]")
    return ScriptScoreQuery(
        query=parse_query(params["query"]),
        script=params["script"],
        min_score=(
            float(params["min_score"]) if "min_score" in params else None
        ),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_script_query(params):
    if "script" not in params:
        raise QueryParseError("[script] requires [script]")
    return ScriptQuery(script=params["script"], boost=float(params.get("boost", 1.0)))


def _parse_query_string(params):
    if "query" not in params:
        raise QueryParseError("[query_string] requires [query]")
    return QueryStringQuery(
        query=str(params["query"]),
        default_field=params.get("default_field"),
        fields=list(params.get("fields", [])),
        default_operator=str(params.get("default_operator", "or")).lower(),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_simple_query_string(params):
    q = _parse_query_string(params)
    q.simple = True
    return q


_PARSERS = {
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": _parse_exists,
    "multi_match": _parse_multi_match,
    "bool": _parse_bool,
    "constant_score": _parse_constant_score,
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "knn": _parse_knn_query,
    "sparse_vector": parse_sparse_vector,
    "ids": _parse_ids,
    "prefix": lambda p: _parse_simple_pattern(PrefixQuery, "prefix")(p),
    "wildcard": lambda p: _parse_simple_pattern(WildcardQuery, "wildcard")(p),
    "regexp": lambda p: _parse_simple_pattern(RegexpQuery, "regexp")(p),
    "fuzzy": _parse_fuzzy,
    "dis_max": _parse_dis_max,
    "boosting": _parse_boosting,
    "function_score": _parse_function_score,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "span_term": _parse_span_term,
    "span_near": _parse_span_near,
    "more_like_this": _parse_more_like_this,
    "geo_distance": _parse_geo_distance,
    "geo_bounding_box": _parse_geo_bounding_box,
    "nested": _parse_nested,
    "percolate": _parse_percolate,
    "script_score": _parse_script_score,
    "script": _parse_script_query,
    "query_string": _parse_query_string,
    "simple_query_string": _parse_simple_query_string,
}


def term_token(value: Any) -> str:
    """Normalizes a term-query value to its index token: JSON booleans
    index as "true"/"false" (shared by executors, the serve-plan
    extractor, and the can_match prefilter — str(True) would probe the
    nonexistent token "True")."""
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def parse_minimum_should_match(msm: Any, num_clauses: int) -> int:
    """Lucene Queries.calculateMinShouldMatch subset: integers, negatives,
    and percentages (incl. negative percentages)."""
    if msm is None:
        return 0
    s = str(msm).strip()
    try:
        if s.endswith("%"):
            pct = float(s[:-1])
            if pct < 0:
                return num_clauses - int(-pct / 100.0 * num_clauses)
            return int(pct / 100.0 * num_clauses)
        v = int(s)
        if v < 0:
            return max(0, num_clauses + v)
        return min(v, num_clauses)
    except ValueError as e:
        raise QueryParseError(f"invalid minimum_should_match [{msm}]") from e


# ---------------------------------------------------------------------------
# Canonical cache keys (query & request caching, search/query_cache.py)
# ---------------------------------------------------------------------------

def canonical_key(q: Any) -> str:
    """Stable canonical serialization of a parsed query node — the
    filter-bitset cache key. Keying the PARSED tree (not the raw JSON)
    makes equivalent spellings share one bitset: {"term": {"f": "x"}}
    and {"term": {"f": {"value": "x"}}} parse identically, so they hit
    the same cache entry (the shape Lucene gets from Query.equals)."""
    from dataclasses import fields as dc_fields

    def enc(v: Any):
        if isinstance(v, Query):
            return [
                type(v).__name__,
                {f.name: enc(getattr(v, f.name)) for f in dc_fields(v)},
            ]
        if isinstance(v, (list, tuple)):
            return [enc(x) for x in v]
        if isinstance(v, dict):
            return {str(k): enc(x) for k, x in v.items()}
        if isinstance(v, (str, int, float, bool)) or v is None:
            return v
        return repr(v)

    import json

    return json.dumps(enc(q), sort_keys=True, separators=(",", ":"))


def canonical_body_key(body: dict, exclude: tuple = ("request_cache",
                                                     "preference",
                                                     "_cache_only",
                                                     "allow_degraded")) -> str:
    """Canonical request bytes for the shard request cache: the search
    body minus per-request control flags that don't change the result
    (`_cache_only` is the tier-3 brownout marker — the degraded request
    must hit the same entry the healthy one populated)."""
    import json

    return json.dumps(
        {k: v for k, v in body.items() if k not in exclude},
        sort_keys=True,
        separators=(",", ":"),
        default=repr,
    )


# Node types that never enter the filter-bitset cache (the analog of
# UsageTrackingQueryCachingPolicy's never-cache list):
#   * scripted / stateful nodes — not a pure function of the segment;
#   * match_all / match_none — trivially cheap, caching wastes slots;
#   * multi_match / query_string — field expansion reads the LIVE
#     mappings dict, which dynamic mapping can grow without a refresh
#     generation bump, so a cached bitset could go stale;
#   * knn wrappers / function_score — per-request candidate cuts and
#     score functions (random_score, scripts) aren't segment-pure;
#   * percolate / more_like_this — evaluate against other documents.
_UNCACHEABLE_FILTERS = (
    "MatchAllQuery", "MatchNoneQuery", "MultiMatchQuery",
    "QueryStringQuery", "FunctionScoreQuery", "ScriptScoreQuery",
    "ScriptQuery", "PercolateQuery", "MoreLikeThisQuery",
    "KnnQueryWrapper",
)


def is_cacheable_filter(q: Any) -> bool:
    """True when a filter-context node is a pure function of one
    segment's immutable data + the shard's searchable generation — the
    gate for the filter-bitset cache. Compounds are cacheable iff every
    child is."""
    if not isinstance(q, Query):
        return False
    if type(q).__name__ in _UNCACHEABLE_FILTERS:
        return False
    if isinstance(q, BoolQuery):
        kids = (
            list(q.must) + list(q.should) + list(q.filter) + list(q.must_not)
        )
        return bool(kids) and all(is_cacheable_filter(c) for c in kids)
    if isinstance(q, ConstantScoreQuery):
        return is_cacheable_filter(q.filter_query)
    if isinstance(q, DisMaxQuery):
        return bool(q.queries) and all(is_cacheable_filter(c) for c in q.queries)
    if isinstance(q, BoostingQuery):
        return is_cacheable_filter(q.positive) and is_cacheable_filter(
            q.negative
        )
    if isinstance(q, SpanNearQuery):
        return all(is_cacheable_filter(c) for c in q.clauses)
    return True
