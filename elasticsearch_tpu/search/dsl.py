"""Query DSL: JSON → query node tree.

Parity target: org.elasticsearch.index.query — AbstractQueryBuilder
parsing and the concrete builders (MatchQueryBuilder, BoolQueryBuilder,
TermQueryBuilder, TermsQueryBuilder, MultiMatchQueryBuilder,
RangeQueryBuilder, ExistsQueryBuilder, MatchAllQueryBuilder,
ConstantScoreQueryBuilder, MatchPhraseQueryBuilder), plus the top-level
`knn` search section (KnnSearchBuilder, server/.../search/vectors/).

The tree is executor-agnostic; both the NumPy oracle and the JAX executor
walk it producing dense (match-mask, score) pairs per segment — the
TPU-native replacement for Lucene's Weight/Scorer pull iterators.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional


class QueryParseError(ValueError):
    pass


@dataclass
class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class MatchQuery(Query):
    field: str = ""
    query: str = ""
    operator: str = "or"  # or | and
    minimum_should_match: Optional[str] = None
    analyzer: Optional[str] = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    query: str = ""
    slop: int = 0
    analyzer: Optional[str] = None


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: List[Any] = dc_field(default_factory=list)


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class MultiMatchQuery(Query):
    query: str = ""
    fields: List[str] = dc_field(default_factory=list)  # may carry ^boost
    type: str = "best_fields"  # best_fields | most_fields | cross_fields
    operator: str = "or"
    tie_breaker: float = 0.0


@dataclass
class BoolQuery(Query):
    must: List[Query] = dc_field(default_factory=list)
    should: List[Query] = dc_field(default_factory=list)
    filter: List[Query] = dc_field(default_factory=list)
    must_not: List[Query] = dc_field(default_factory=list)
    minimum_should_match: Optional[Any] = None


@dataclass
class ConstantScoreQuery(Query):
    filter_query: Query = None  # type: ignore[assignment]


@dataclass
class ScoreFunction:
    """One function_score entry: optional filter + weight and/or
    field_value_factor (FunctionScoreQueryBuilder.FilterFunctionBuilder)."""

    filter: Optional[Query] = None
    weight: Optional[float] = None
    field_value_factor: Optional[dict] = None  # {field, factor, modifier, missing}
    random_score: Optional[dict] = None  # {seed, field}
    script_score: Optional[dict] = None  # {"script": {...}} (ScriptScoreFunction)


@dataclass
class FunctionScoreQuery(Query):
    query: Query = None  # type: ignore[assignment]
    functions: List[ScoreFunction] = dc_field(default_factory=list)
    score_mode: str = "multiply"  # multiply | sum | avg | max | min | first
    boost_mode: str = "multiply"  # multiply | sum | replace | avg | max | min
    max_boost: Optional[float] = None
    min_score: Optional[float] = None


@dataclass
class ScriptScoreQuery(Query):
    """script_score query: base query matches, the script replaces the
    score (ScriptScoreQueryBuilder — the reference's brute-force kNN
    vehicle via cosineSimilarity, SURVEY.md §3.4)."""

    query: Query = None  # type: ignore[assignment]
    script: Any = None
    min_score: Optional[float] = None


@dataclass
class ScriptQuery(Query):
    """script query (filter context): the script decides matching per
    doc (ScriptQueryBuilder)."""

    script: Any = None


@dataclass
class IdsQuery(Query):
    values: List[str] = dc_field(default_factory=list)


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class WildcardQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class RegexpQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: str = "AUTO"
    prefix_length: int = 0
    max_expansions: int = 50


@dataclass
class DisMaxQuery(Query):
    queries: List[Query] = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class BoostingQuery(Query):
    positive: Query = None  # type: ignore[assignment]
    negative: Query = None  # type: ignore[assignment]
    negative_boost: float = 0.0


@dataclass
class QueryStringQuery(Query):
    """query_string / simple_query_string lite: terms, field:term,
    quoted phrases, AND/OR/NOT (query_string) — no grouping parens."""

    query: str = ""
    default_field: Optional[str] = None
    fields: List[str] = dc_field(default_factory=list)
    default_operator: str = "or"
    simple: bool = False


@dataclass
class KnnSection:
    """Top-level `knn` search element (can also appear as a query clause)."""

    field: str
    query_vector: List[float]
    k: int = 10
    num_candidates: int = 100
    filter: Optional[Query] = None
    boost: float = 1.0
    similarity: Optional[float] = None  # min-similarity cutoff


_SINGLE_KEY_ERR = "[%s] query malformed, no start_object after query name"


def parse_query(body: Any) -> Query:
    """Parses one query object ({"match": {...}} etc.)."""
    if not isinstance(body, dict) or len(body) != 1:
        if isinstance(body, dict) and len(body) == 0:
            raise QueryParseError("query malformed, empty clause found")
        raise QueryParseError(
            "[bool] malformed query, expected a single query name"
        )
    name, params = next(iter(body.items()))
    parser = _PARSERS.get(name)
    if parser is None:
        raise QueryParseError(f"unknown query [{name}]")
    return parser(params)


def _field_params(params: dict, qname: str) -> tuple:
    if not isinstance(params, dict) or len(params) != 1:
        raise QueryParseError(f"[{qname}] query doesn't support multiple fields")
    fname, cfg = next(iter(params.items()))
    return fname, cfg


def _parse_match(params):
    fname, cfg = _field_params(params, "match")
    if isinstance(cfg, dict):
        return MatchQuery(
            field=fname,
            query=str(cfg.get("query", "")),
            operator=str(cfg.get("operator", "or")).lower(),
            minimum_should_match=cfg.get("minimum_should_match"),
            analyzer=cfg.get("analyzer"),
            boost=float(cfg.get("boost", 1.0)),
        )
    return MatchQuery(field=fname, query=str(cfg))


def _parse_match_phrase(params):
    fname, cfg = _field_params(params, "match_phrase")
    if isinstance(cfg, dict):
        return MatchPhraseQuery(
            field=fname,
            query=str(cfg.get("query", "")),
            slop=int(cfg.get("slop", 0)),
            analyzer=cfg.get("analyzer"),
            boost=float(cfg.get("boost", 1.0)),
        )
    return MatchPhraseQuery(field=fname, query=str(cfg))


def _parse_term(params):
    fname, cfg = _field_params(params, "term")
    if isinstance(cfg, dict):
        return TermQuery(
            field=fname, value=cfg.get("value"), boost=float(cfg.get("boost", 1.0))
        )
    return TermQuery(field=fname, value=cfg)


def _parse_terms(params):
    params = dict(params)
    boost = float(params.pop("boost", 1.0))
    if len(params) != 1:
        raise QueryParseError("[terms] query requires exactly one field")
    fname, values = next(iter(params.items()))
    if not isinstance(values, list):
        raise QueryParseError("[terms] query requires an array of values")
    return TermsQuery(field=fname, values=values, boost=boost)


def _parse_range(params):
    fname, cfg = _field_params(params, "range")
    if not isinstance(cfg, dict):
        raise QueryParseError("[range] query malformed")
    known = {"gte", "gt", "lte", "lt", "boost", "format", "relation", "time_zone"}
    for k in cfg:
        if k not in known:
            raise QueryParseError(f"[range] query does not support [{k}]")
    return RangeQuery(
        field=fname,
        gte=cfg.get("gte"),
        gt=cfg.get("gt"),
        lte=cfg.get("lte"),
        lt=cfg.get("lt"),
        boost=float(cfg.get("boost", 1.0)),
    )


def _parse_exists(params):
    if "field" not in params:
        raise QueryParseError("[exists] query requires [field]")
    return ExistsQuery(field=params["field"], boost=float(params.get("boost", 1.0)))


def _parse_multi_match(params):
    if "query" not in params:
        raise QueryParseError("[multi_match] query requires [query]")
    return MultiMatchQuery(
        query=str(params["query"]),
        fields=list(params.get("fields", [])),
        type=params.get("type", "best_fields"),
        operator=str(params.get("operator", "or")).lower(),
        tie_breaker=float(params.get("tie_breaker", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _as_list(v):
    return v if isinstance(v, list) else [v]


def _parse_bool(params):
    return BoolQuery(
        must=[parse_query(q) for q in _as_list(params.get("must", []))],
        should=[parse_query(q) for q in _as_list(params.get("should", []))],
        filter=[parse_query(q) for q in _as_list(params.get("filter", []))],
        must_not=[parse_query(q) for q in _as_list(params.get("must_not", []))],
        minimum_should_match=params.get("minimum_should_match"),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_constant_score(params):
    if "filter" not in params:
        raise QueryParseError("[constant_score] requires a filter")
    return ConstantScoreQuery(
        filter_query=parse_query(params["filter"]),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_match_all(params):
    params = params or {}
    return MatchAllQuery(boost=float(params.get("boost", 1.0)))


def _parse_match_none(params):
    return MatchNoneQuery()


def _parse_knn_query(params):
    return KnnQueryWrapper(parse_knn(params))


@dataclass
class KnnQueryWrapper(Query):
    """`knn` used as a query clause (ES 8.12+)."""

    knn: KnnSection = None  # type: ignore[assignment]


def parse_knn(params: dict) -> KnnSection:
    if "field" not in params or "query_vector" not in params:
        raise QueryParseError("[knn] requires [field] and [query_vector]")
    k = int(params.get("k", 10))
    return KnnSection(
        field=params["field"],
        query_vector=[float(x) for x in params["query_vector"]],
        k=k,
        num_candidates=int(params.get("num_candidates", max(100, k))),
        filter=parse_query(params["filter"]) if params.get("filter") else None,
        boost=float(params.get("boost", 1.0)),
        similarity=params.get("similarity"),
    )


def _parse_ids(params):
    values = params.get("values")
    if not isinstance(values, list):
        raise QueryParseError("[ids] query requires [values] array")
    return IdsQuery(values=[str(v) for v in values], boost=float(params.get("boost", 1.0)))


def _parse_simple_pattern(cls, qname):
    def parse(params):
        fname, cfg = _field_params(params, qname)
        if isinstance(cfg, dict):
            value = cfg.get("value", cfg.get(qname, ""))
            if qname == "wildcard" and value == "" and "wildcard" in cfg:
                value = cfg["wildcard"]
            return cls(
                field=fname,
                value=str(value),
                case_insensitive=bool(cfg.get("case_insensitive", False)),
                boost=float(cfg.get("boost", 1.0)),
            )
        return cls(field=fname, value=str(cfg))

    return parse


def _parse_fuzzy(params):
    fname, cfg = _field_params(params, "fuzzy")
    if isinstance(cfg, dict):
        return FuzzyQuery(
            field=fname,
            value=str(cfg.get("value", "")),
            fuzziness=str(cfg.get("fuzziness", "AUTO")),
            prefix_length=int(cfg.get("prefix_length", 0)),
            max_expansions=int(cfg.get("max_expansions", 50)),
            boost=float(cfg.get("boost", 1.0)),
        )
    return FuzzyQuery(field=fname, value=str(cfg))


def _parse_dis_max(params):
    qs = params.get("queries")
    if not isinstance(qs, list) or not qs:
        raise QueryParseError("[dis_max] query requires [queries] array")
    return DisMaxQuery(
        queries=[parse_query(q) for q in qs],
        tie_breaker=float(params.get("tie_breaker", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_boosting(params):
    if "positive" not in params or "negative" not in params:
        raise QueryParseError("[boosting] requires [positive] and [negative]")
    return BoostingQuery(
        positive=parse_query(params["positive"]),
        negative=parse_query(params["negative"]),
        negative_boost=float(params.get("negative_boost", 0.0)),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_function_score(params):
    inner = (
        parse_query(params["query"]) if "query" in params else MatchAllQuery()
    )
    functions: List[ScoreFunction] = []
    raw_fns = params.get("functions")
    if raw_fns is None:
        raw_fns = []
        # single-function shorthand at the top level
        single = {
            k: params[k]
            for k in ("weight", "field_value_factor", "random_score", "script_score")
            if k in params
        }
        if single:
            raw_fns = [single]
    for fn in raw_fns:
        if not isinstance(fn, dict):
            raise QueryParseError("[function_score] malformed function")
        known = {
            "filter", "weight", "field_value_factor", "random_score",
            "script_score",
        }
        unknown = set(fn) - known
        if unknown:
            raise QueryParseError(
                f"[function_score] unsupported function [{sorted(unknown)[0]}]"
            )
        functions.append(
            ScoreFunction(
                filter=parse_query(fn["filter"]) if "filter" in fn else None,
                weight=float(fn["weight"]) if "weight" in fn else None,
                field_value_factor=fn.get("field_value_factor"),
                random_score=fn.get("random_score"),
                script_score=fn.get("script_score"),
            )
        )
    return FunctionScoreQuery(
        query=inner,
        functions=functions,
        score_mode=str(params.get("score_mode", "multiply")),
        boost_mode=str(params.get("boost_mode", "multiply")),
        max_boost=float(params["max_boost"]) if "max_boost" in params else None,
        min_score=params.get("min_score"),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_script_score(params):
    if "query" not in params or "script" not in params:
        raise QueryParseError("[script_score] requires [query] and [script]")
    return ScriptScoreQuery(
        query=parse_query(params["query"]),
        script=params["script"],
        min_score=(
            float(params["min_score"]) if "min_score" in params else None
        ),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_script_query(params):
    if "script" not in params:
        raise QueryParseError("[script] requires [script]")
    return ScriptQuery(script=params["script"], boost=float(params.get("boost", 1.0)))


def _parse_query_string(params):
    if "query" not in params:
        raise QueryParseError("[query_string] requires [query]")
    return QueryStringQuery(
        query=str(params["query"]),
        default_field=params.get("default_field"),
        fields=list(params.get("fields", [])),
        default_operator=str(params.get("default_operator", "or")).lower(),
        boost=float(params.get("boost", 1.0)),
    )


def _parse_simple_query_string(params):
    q = _parse_query_string(params)
    q.simple = True
    return q


_PARSERS = {
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": _parse_exists,
    "multi_match": _parse_multi_match,
    "bool": _parse_bool,
    "constant_score": _parse_constant_score,
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "knn": _parse_knn_query,
    "ids": _parse_ids,
    "prefix": lambda p: _parse_simple_pattern(PrefixQuery, "prefix")(p),
    "wildcard": lambda p: _parse_simple_pattern(WildcardQuery, "wildcard")(p),
    "regexp": lambda p: _parse_simple_pattern(RegexpQuery, "regexp")(p),
    "fuzzy": _parse_fuzzy,
    "dis_max": _parse_dis_max,
    "boosting": _parse_boosting,
    "function_score": _parse_function_score,
    "script_score": _parse_script_score,
    "script": _parse_script_query,
    "query_string": _parse_query_string,
    "simple_query_string": _parse_simple_query_string,
}


def parse_minimum_should_match(msm: Any, num_clauses: int) -> int:
    """Lucene Queries.calculateMinShouldMatch subset: integers, negatives,
    and percentages (incl. negative percentages)."""
    if msm is None:
        return 0
    s = str(msm).strip()
    try:
        if s.endswith("%"):
            pct = float(s[:-1])
            if pct < 0:
                return num_clauses - int(-pct / 100.0 * num_clauses)
            return int(pct / 100.0 * num_clauses)
        v = int(s)
        if v < 0:
            return max(0, num_clauses + v)
        return min(v, num_clauses)
    except ValueError as e:
        raise QueryParseError(f"invalid minimum_should_match [{msm}]") from e
