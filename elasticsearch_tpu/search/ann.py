"""ANN (IVF) routing + observability for the vector serving path.

The decision layer between the DSL and the kernels: an index opts into
IVF via `index.knn.type: ivf` (with `index.knn.nlist` / default
`index.knn.nprobe` knobs and the existing `index.knn.quantization`
selector for the int8 twin); a request opts back OUT via `?exact=true`
(or a body-level `"exact": true`), and each `knn` section may override
`nprobe`. Segments below the small-segment floor
(`ES_TPU_ANN_MIN_DOCS`, default 4096) always score exact, so
correctness never depends on cluster quality for tiny segments.

The exact brute-force path is the float oracle and is never removed:
every ANN failure (injected `ann.probe` fault, HBM budget breach,
missing index) deterministically falls back to it.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import List, Optional

ANN_MIN_DOCS_ENV = "ES_TPU_ANN_MIN_DOCS"
ANN_MIN_DOCS_DEFAULT = 4096
DEFAULT_NPROBE = 8


def ann_min_docs() -> int:
    """Small-segment exact floor (read per call so tests can lower it)."""
    raw = os.environ.get(ANN_MIN_DOCS_ENV, "")
    try:
        v = int(raw) if raw else ANN_MIN_DOCS_DEFAULT
    except ValueError:
        v = ANN_MIN_DOCS_DEFAULT
    return max(0, v)


@dataclass(frozen=True)
class AnnSpec:
    """Resolved per-request ANN parameters. Frozen/hashable so it can
    ride the batcher's kNN group key (jobs with different probe widths
    or build shapes never share a launch) and key the executor's
    per-generation index cache."""

    nlist: int  # 0 = auto (~sqrt N per segment)
    nprobe: int
    quantized: bool
    min_docs: int


def resolve(settings, sec, body_exact: bool) -> Optional[AnnSpec]:
    """AnnSpec for one knn section under one index's settings, or None
    for the exact path. `settings` is the index's flat settings dict."""
    if str(settings.get("knn.type", "exact")) != "ivf":
        return None
    if body_exact:
        note("exact_searches")
        return None
    nprobe = sec.nprobe
    if nprobe is None:
        try:
            nprobe = int(settings.get("knn.nprobe", DEFAULT_NPROBE))
        except (TypeError, ValueError):
            nprobe = DEFAULT_NPROBE
    try:
        nlist = int(settings.get("knn.nlist", 0))
    except (TypeError, ValueError):
        nlist = 0
    quant = str(settings.get("knn.quantization", "none")) == "int8"
    return AnnSpec(
        nlist=max(0, nlist),
        nprobe=max(1, int(nprobe)),
        quantized=quant,
        min_docs=ann_min_docs(),
    )


def annotate(secs: List, settings, body: Optional[dict]) -> None:
    """Resolves + attaches the AnnSpec to each parsed KnnSection (the
    `ann` field the executors and plan extractors consult)."""
    body_exact = bool((body or {}).get("exact"))
    for sec in secs or []:
        sec.ann = resolve(settings, sec, body_exact)


# ---------------------------------------------------------------------------
# observability: the `knn.ann` block of `_nodes/stats`
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
ANN_STATS = {
    "ann_searches": 0,  # (job × segment) scorings served by IVF probes
    "exact_searches": 0,  # ?exact=true escape-hatch routings
    "small_segment_exact": 0,  # under-floor segments served exact
    "exact_fallbacks": 0,  # probe-path failures → brute force
    "probes": 0,  # Σ nprobe over ann_searches
    "clusters_scanned": 0,  # Σ probed clusters (== probes, capped at nlist)
    "clusters_total": 0,  # Σ nlist over ann_searches
    "builds": 0,  # k-means index builds
    "build_ms": 0.0,  # Σ build wall time
}


def note(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        ANN_STATS[key] += n


def note_search(nprobe: int, nlist: int, jobs: int = 1) -> None:
    """One probed scoring of `jobs` queries against one segment."""
    scanned = min(nprobe, nlist)
    with _STATS_LOCK:
        ANN_STATS["ann_searches"] += jobs
        ANN_STATS["probes"] += nprobe * jobs
        ANN_STATS["clusters_scanned"] += scanned * jobs
        ANN_STATS["clusters_total"] += nlist * jobs



def note_build(build_ms: float) -> None:
    with _STATS_LOCK:
        ANN_STATS["builds"] += 1
        ANN_STATS["build_ms"] += build_ms


def stats_snapshot() -> dict:
    """The `knn.ann` stats block (ledger bytes from the `ann` HBM
    category joined in)."""
    from ..common.memory import hbm_ledger

    with _STATS_LOCK:
        out = dict(ANN_STATS)
    out["build_ms"] = round(out["build_ms"], 2)
    out["ledger_bytes"] = int(
        hbm_ledger.stats()["by_category"].get("ann", 0)
    )
    return out


def reset_stats() -> None:
    """Test hook: zero the counters."""
    with _STATS_LOCK:
        for k in ANN_STATS:
            ANN_STATS[k] = 0 if k != "build_ms" else 0.0
