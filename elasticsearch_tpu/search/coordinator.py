"""Coordinator-side merge of per-shard results.

Reference analog: SearchPhaseController.reducedQueryPhase /
QueryPhaseResultConsumer (server/.../action/search/) — merge-sort the
per-shard top-k by (score desc, shard asc, doc asc), sum totals, keep
max_score. The device-side equivalent for mesh-resident shards is the
all_gather merge in parallel/sharded.py; this host-side version serves
the engine/REST path where each shard produced a TopDocs via its
executor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from .executor import Hit, TopDocs


@dataclass
class ShardHit:
    score: float
    shard: int
    segment: int
    local_doc: int
    doc_id: str


def merge_top_docs(
    shard_results: Sequence[TopDocs], from_: int = 0, size: int = 10
) -> tuple:
    """Returns (total, max_score, List[ShardHit]) for the global page."""
    total = sum(td.total for td in shard_results)
    max_score: Optional[float] = None
    entries: List[tuple] = []
    for si, td in enumerate(shard_results):
        if td.max_score is not None:
            max_score = (
                td.max_score if max_score is None else max(max_score, td.max_score)
            )
        for h in td.hits:
            entries.append((-h.score, si, h.segment, h.local_doc, h))
    entries.sort(key=lambda e: e[:4])
    page = entries[from_ : from_ + size]
    hits = [
        ShardHit(
            score=h.score,
            shard=si,
            segment=h.segment,
            local_doc=h.local_doc,
            doc_id=h.doc_id,
        )
        for _, si, _, _, h in page
    ]
    return total, max_score, hits
