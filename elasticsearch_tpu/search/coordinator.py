"""Coordinator-side merge of per-shard results.

Reference analog: SearchPhaseController.reducedQueryPhase /
QueryPhaseResultConsumer (server/.../action/search/) — merge-sort the
per-shard top-k by (score desc, shard asc, doc asc), sum totals, keep
max_score. The device-side equivalent for mesh-resident shards is the
all_gather merge in parallel/sharded.py; this host-side version serves
the engine/REST path where each shard produced a TopDocs via its
executor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from .executor import Hit, TopDocs


@dataclass
class ShardHit:
    score: float
    shard: int
    segment: int
    local_doc: int
    doc_id: str


class _Rev:
    """Reverses comparison for desc string columns in merge keys."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


def _col_key(value, spec):
    missing_rank = 1 if spec["missing"] == "_last" else -1
    if value is None or (isinstance(value, float) and value != value):
        return (missing_rank, 0)
    if spec["order"] == "desc":
        if isinstance(value, (int, float)):
            return (0, -value)
        return (0, _Rev(value))
    return (0, value)


def merge_sorted(
    shard_results: Sequence[Optional[TopDocs]],
    shard_sort_values: Sequence[Sequence[list]],
    sort_specs: Sequence[dict],
    from_: int,
    size: int,
) -> tuple:
    """Coordinator merge for field-sorted results: compare raw sort
    values per column with direction/missing applied (TopFieldDocs merge
    in SearchPhaseController). Returns (total, None, hits, hit_sorts).

    A ``None`` entry is a FAILED shard (the partial-results contract of
    the fault-tolerant fan-out): it contributes nothing, and surviving
    shards keep their original shard indices for tie-breaks so a
    degraded merge is the healthy merge minus the failed shards' hits."""
    total = sum(td.total for td in shard_results if td is not None)
    entries = []
    for si, td in enumerate(shard_results):
        if td is None:
            continue
        svals = shard_sort_values[si]
        for i, h in enumerate(td.hits):
            vals = svals[i] if i < len(svals) else []
            key = tuple(
                _col_key(v, spec) for v, spec in zip(vals, sort_specs)
            )
            entries.append((key, si, h.segment, h.local_doc, h, vals))
    entries.sort(key=lambda e: e[:4])
    page = entries[from_ : from_ + size]
    hits = [
        ShardHit(
            score=h.score,
            shard=si,
            segment=h.segment,
            local_doc=h.local_doc,
            doc_id=h.doc_id,
        )
        for _, si, _, _, h, _ in page
    ]
    hit_sorts = [vals for *_, vals in page]
    return total, None, hits, hit_sorts


def merge_top_docs(
    shard_results: Sequence[Optional[TopDocs]], from_: int = 0, size: int = 10
) -> tuple:
    """Returns (total, max_score, List[ShardHit]) for the global page.
    ``None`` entries are failed shards (see merge_sorted): skipped, with
    surviving shard indices preserved for the (score, shard, doc)
    tie-break ordering."""
    total = sum(td.total for td in shard_results if td is not None)
    max_score: Optional[float] = None
    entries: List[tuple] = []
    for si, td in enumerate(shard_results):
        if td is None:
            continue
        if td.max_score is not None:
            max_score = (
                td.max_score if max_score is None else max(max_score, td.max_score)
            )
        for h in td.hits:
            entries.append((-h.score, si, h.segment, h.local_doc, h))
    entries.sort(key=lambda e: e[:4])
    page = entries[from_ : from_ + size]
    hits = [
        ShardHit(
            score=h.score,
            shard=si,
            segment=h.segment,
            local_doc=h.local_doc,
            doc_id=h.doc_id,
        )
        for _, si, _, _, h in page
    ]
    return total, max_score, hits
