"""Distributed execution: device meshes, sharded indexes, SPMD search.

Reference analogs: OperationRouting / AbstractSearchAsyncAction /
SearchPhaseController (SURVEY.md §2.6-§2.7) — redesigned as mesh-sharded
arrays + XLA collectives instead of RPC scatter/gather.
"""

from .mesh import (
    DATA_AXIS,
    SHARD_AXIS,
    fold_factor,
    make_mesh,
    mesh_shape,
    single_device_mesh,
)
from .mesh_executor import MeshExecutor, MeshUnavailable
from .sharded import (
    ShardedIndex,
    ShardedTopK,
    build_mesh_knn_step,
    build_mesh_text_step,
    build_sharded_bm25_step,
    build_sharded_knn_step,
    rrf_fuse,
)

__all__ = [
    "DATA_AXIS",
    "SHARD_AXIS",
    "fold_factor",
    "make_mesh",
    "mesh_shape",
    "single_device_mesh",
    "MeshExecutor",
    "MeshUnavailable",
    "ShardedIndex",
    "ShardedTopK",
    "build_mesh_knn_step",
    "build_mesh_text_step",
    "build_sharded_bm25_step",
    "build_sharded_knn_step",
    "rrf_fuse",
]
