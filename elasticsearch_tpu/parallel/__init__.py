"""Distributed execution: device meshes, sharded indexes, SPMD search.

Reference analogs: OperationRouting / AbstractSearchAsyncAction /
SearchPhaseController (SURVEY.md §2.6-§2.7) — redesigned as mesh-sharded
arrays + XLA collectives instead of RPC scatter/gather.
"""

from .mesh import DATA_AXIS, SHARD_AXIS, make_mesh, mesh_shape, single_device_mesh
from .sharded import (
    ShardedIndex,
    ShardedTopK,
    build_sharded_bm25_step,
    build_sharded_knn_step,
    rrf_fuse,
)

__all__ = [
    "DATA_AXIS",
    "SHARD_AXIS",
    "make_mesh",
    "mesh_shape",
    "single_device_mesh",
    "ShardedIndex",
    "ShardedTopK",
    "build_sharded_bm25_step",
    "build_sharded_knn_step",
    "rrf_fuse",
]
