"""Device-mesh construction for distributed search.

Reference analog: Elasticsearch's distribution model (SURVEY.md §2.6) —
an index is split into primary shards (`OperationRouting.shardId =
hash(_routing) % P`) and every search fans out to one copy of each shard
(`AbstractSearchAsyncAction`). On TPU the fan-out is not RPC: shards are
a named mesh axis and the per-shard arrays are laid out with
`jax.sharding.NamedSharding`, so "send the query to every shard" is just
running one `shard_map`ped program over the mesh, and "merge shard
responses" is an `all_gather` over the ICI.

Two mesh axes:
  - ``shards``: partitions of the document space (ES data parallelism);
  - ``data``:   concurrent query batches (the ES coordinator serving many
                searches at once — replica/ARS throughput scaling).

Layouts need not be square or even divisible: when there are FEWER
devices than shards, multiple shards fold onto one device via a leading
stacked axis (the stacked arrays are padded to ``axis_size * fold`` rows
and each device scores its ``fold`` local shards with a vmap before the
ICI merge — see parallel/sharded.py). ``make_mesh`` therefore never
rejects a layout for having too few devices; it returns the widest
``shards`` axis the device set supports and callers size the stack with
``fold_factor``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"
DATA_AXIS = "data"


def make_mesh(
    n_shards: int,
    n_data: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Builds a (data, shards) mesh for ``n_shards`` shard stacks.

    The ``shards`` axis gets ``min(n_shards, len(devices) // n_data)``
    devices — non-power-of-two shard counts use exactly that many
    devices, and when fewer devices than shards are available the axis
    is simply narrower and shards fold onto devices (``fold_factor``
    per device) instead of raising.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_shards < 1 or n_data < 1:
        raise ValueError(
            f"mesh axes must be >= 1 (data={n_data} x shards={n_shards})"
        )
    if len(devices) < n_data:
        raise ValueError(
            f"mesh needs at least {n_data} devices for the data axis, "
            f"have {len(devices)}"
        )
    g = min(n_shards, len(devices) // n_data)
    grid = np.asarray(devices[: n_data * g]).reshape(n_data, g)
    return Mesh(grid, (DATA_AXIS, SHARD_AXIS))


def fold_factor(mesh: Mesh, n_entries: int) -> int:
    """Shards (stacked entries) per device on the ``shards`` axis: the
    stacked arrays must carry ``mesh.shape[SHARD_AXIS] * fold_factor``
    rows (trailing rows padded empty) so each device holds an equal
    fold of the stack."""
    g = mesh.shape[SHARD_AXIS]
    return max(1, -(-max(n_entries, 1) // g))


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1)


def mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    return mesh.shape[DATA_AXIS], mesh.shape[SHARD_AXIS]
