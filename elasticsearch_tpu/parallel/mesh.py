"""Device-mesh construction for distributed search.

Reference analog: Elasticsearch's distribution model (SURVEY.md §2.6) —
an index is split into primary shards (`OperationRouting.shardId =
hash(_routing) % P`) and every search fans out to one copy of each shard
(`AbstractSearchAsyncAction`). On TPU the fan-out is not RPC: shards are
a named mesh axis and the per-shard arrays are laid out with
`jax.sharding.NamedSharding`, so "send the query to every shard" is just
running one `shard_map`ped program over the mesh, and "merge shard
responses" is an `all_gather` over the ICI.

Two mesh axes:
  - ``shards``: partitions of the document space (ES data parallelism);
  - ``data``:   concurrent query batches (the ES coordinator serving many
                searches at once — replica/ARS throughput scaling).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"
DATA_AXIS = "data"


def make_mesh(
    n_shards: int,
    n_data: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Builds a (data, shards) mesh over ``n_data * n_shards`` devices."""
    devices = list(devices if devices is not None else jax.devices())
    need = n_shards * n_data
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices (data={n_data} x shards={n_shards}), "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:need]).reshape(n_data, n_shards)
    return Mesh(grid, (DATA_AXIS, SHARD_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1)


def mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    return mesh.shape[DATA_AXIS], mesh.shape[SHARD_AXIS]
