"""MeshExecutor — the serving backend that puts every chip behind `_search`.

Round-5 verdict: the production `_search` path scored shards one device
at a time while the 8-device `shard_map` pipeline existed only as a
dryrun. This module promotes it: a `MeshExecutor` materializes a stacked
device-resident view of an index's LIVE shards (every (shard, segment)
pair is one entry on the ``shards`` mesh axis, folded when there are
more entries than devices) and executes whole same-plan query groups as
ONE SPMD program — per-entry scoring + local top-k on each device, an
`all_gather` + k-way merge over the ICI, `psum` totals — replacing S
sequential kernel dispatches and S host round-trips with one packed
download.

Design contract (float-exactness with the single-device path):

  * entries are (shard, segment) pairs in (shard asc, segment asc)
    order, so per-entry scoring is the SAME computation the sequential
    ChunkedScorer/segment kernels run — same block-aligned tilings
    (ops/wand.get_tiling), same shard-level BM25 weights
    (JaxExecutor._segment_weights via BlockMaxIndex), same
    `w - w/(1 + tf·inv)` accumulation in the same tile order, same
    live-doc masking — and the device merge's (score desc, slot asc)
    order equals the coordinator's (score desc, shard asc, segment asc,
    doc asc) tie-break. Only the merge topology changes.
  * no pruning on the mesh path: totals come out exact (relation "eq"),
    which is the sequential path's behavior whenever its capped-total
    proof does not fire.

Lifecycle: the stacked view is rebuilt LAZILY when any shard's engine
`change_generation` moves (refresh/merge/delete); stale snapshots keep
serving in-flight launches until the references die. Every stacked
upload charges the HBM ledger's ``mesh`` category up front and the
build DEGRADES to the single-device path (`MeshUnavailable`) instead of
tripping the breaker when the budget cannot fit it.

Knobs (common/settings.py): ES_TPU_MESH (auto|force|off),
ES_TPU_MESH_DEVICES, ES_TPU_MESH_DATA, ES_TPU_MESH_T_MAX.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.settings import (
    batch_buckets,
    bucket_for,
    mesh_data_axis,
    mesh_devices_cap,
    mesh_mode,
    mesh_t_max,
)
from ..index.segment import INVALID_DOC, TILE
from ..ops import scoring
from .mesh import DATA_AXIS, SHARD_AXIS, fold_factor, make_mesh
from .sharded import (
    build_mesh_agg_step,
    build_mesh_ann_step,
    build_mesh_knn_step,
    build_mesh_rerank_step,
    build_mesh_sparse_step,
    build_mesh_text_step,
)

BPAD = scoring.BPAD

# Process-wide SPMD launch lock: two batcher workers enqueueing mesh
# programs concurrently could interleave per-device enqueue order
# (worker A lands first on device 0, worker B first on device 1),
# inverting the collectives' rendezvous order across devices — a
# deadlock on any backend. Holding the lock around the ENQUEUE (the
# jitted step call, which returns before execution completes) keeps
# every device's queue identically ordered; execution and the packed
# downloads still overlap freely.
_LAUNCH_LOCK = threading.Lock()


class MeshUnavailable(Exception):
    """The mesh path cannot serve this group (no devices, HBM budget
    breach, slot overflow, unsupported plan shape). Callers degrade to
    the single-device sequential path — never an error surface.
    ``budget`` marks the HBM-ledger degrade specifically."""

    def __init__(self, msg: str, budget: bool = False):
        super().__init__(msg)
        self.budget = budget


class MeshHit(NamedTuple):
    score: float
    shard: int
    segment: int
    local_doc: int
    doc_id: str


class MeshTopDocs(NamedTuple):
    """One query's globally merged mesh result. `snapshot` pins the
    reader generation the hits were scored against so the fetch phase
    reads the same point-in-time sources."""

    total: int
    relation: str
    max_score: Optional[float]
    hits: List[MeshHit]
    snapshot: "_MeshSnapshot"


class _MeshSnapshot:
    """One generation's stacked device view of the index's shards."""

    def __init__(self, mesh, fold, entries, readers, executors, gens):
        self.mesh = mesh
        self.fold = fold
        self.entries = entries  # [(sid, si)] in (shard, segment) asc order
        self.readers = readers  # sid -> ShardReader
        self.executors = executors  # sid -> JaxExecutor
        self.gens = gens
        g = mesh.shape[SHARD_AXIS]
        self.e_pad = g * fold
        self.n_docs_max = max(
            (readers[sid].segments[si].num_docs for sid, si in entries),
            default=1,
        )
        self.charges: List[Tuple[str, int]] = []
        self.live = None  # bool[E_pad, Nmax] device (live ∧ in-range)
        self.text: Dict[str, dict] = {}  # field -> stacked text arrays
        self.knn: Dict[str, dict] = {}  # field -> stacked vector arrays
        self.aggs: Dict[tuple, dict] = {}  # stacked agg column views
        self.steps: Dict[tuple, object] = {}
        self.closed = False
        # ---- incremental rebuild state: per-entry identity keys
        # ((sid, shard generation, si) — a bumped shard invalidates ALL
        # its entries, since inverse norms / idf weights are shard-level
        # stats) and the host staging copies of every stacked view.
        # When the next generation rebuilds, rows whose key is unchanged
        # copy from the previous stack instead of re-extracting (and
        # re-downloading) tilings — a one-shard NRT refresh rebuilds
        # only that shard's rows. ----
        gen_of = dict(gens)
        self.entry_keys = [
            (sid, gen_of.get(sid), si) for sid, si in entries
        ]
        self.host_stacks: Dict[object, dict] = {}
        # (prev entry_keys, prev host_stacks) captured at build — plain
        # data, NOT a reference to the previous snapshot, so the old
        # generation's device arrays die on schedule
        self.reuse_src: Optional[tuple] = None

    @property
    def device_ids(self) -> Tuple[int, ...]:
        return tuple(
            getattr(d, "id", i)
            for i, d in enumerate(self.mesh.devices.ravel())
        )

    def charge(self, nbytes: int) -> None:
        from ..common.memory import hbm_ledger

        if not hbm_ledger.would_fit(nbytes):
            hbm_ledger.note_degraded()
            raise MeshUnavailable(
                f"mesh stack of {nbytes} bytes exceeds the HBM budget",
                budget=True,
            )
        hbm_ledger.add("mesh", nbytes, breaker=False)
        self.charges.append(("mesh", nbytes))

    def release(self) -> None:
        from ..common.memory import hbm_ledger

        self.closed = True
        charges, self.charges = self.charges, []
        for cat, nbytes in charges:
            hbm_ledger.release(cat, nbytes)


class MeshAggPlan:
    """A compiled mesh agg body — the batcher's ``mesh_agg`` job plan.
    ``sig`` groups structurally identical dashboard shapes into one
    SPMD launch; the query (match plan terms / match_all) varies per
    row. ``terms``/``boost``/``msm`` delegate to the match plan so the
    mesh text packers can treat agg jobs like match jobs."""

    def __init__(self, nodes, specs, mplan):
        self.nodes = nodes
        self.specs = specs
        self.mplan = mplan  # batcher MatchPlan | None (match_all)
        self.sig = (tuple(specs), mplan is not None)

    @property
    def terms(self):
        return self.mplan.terms if self.mplan is not None else ()

    @property
    def boost(self) -> float:
        return self.mplan.boost if self.mplan is not None else 1.0

    @property
    def msm(self) -> int:
        return self.mplan.msm if self.mplan is not None else 1


class MeshExecutor:
    """Mesh-parallel serving engine of ONE index (owned by IndexService).

    The QueryBatcher routes same-plan query groups here (job kinds
    ``mesh_match`` / ``mesh_serve`` / ``mesh_knn``): `dispatch_*`
    launches the SPMD step asynchronously, `collect_*` performs the one
    packed download and finishes the waiters — the same dispatch/collect
    split (and pipeline depth) as the single-device families.
    """

    def __init__(self, service):
        self.service = service
        self._lock = threading.RLock()
        self._snapshot: Optional[_MeshSnapshot] = None
        self.stats = {
            "routed": 0,  # requests served start-to-finish by the mesh
            "launches": 0,  # SPMD programs dispatched
            "jobs": 0,  # queries carried by those programs
            "rebuilds": 0,  # snapshot rebuilds on generation bumps
            "incremental_rebuilds": 0,  # rebuilds that reused prev rows
            "entries_reused": 0,  # stacked rows copied, not re-extracted
            "degraded": 0,  # HBM-budget degrades to single-device
            "fallbacks": 0,  # routed requests that fell back mid-flight
        }

    # ---- routing predicate ----

    def available(self) -> bool:
        mode = mesh_mode()
        if mode == "off":
            return False
        svc = self.service
        if svc.routing is not None and not self._all_shards_local():
            # distributed mode: the stack can only serve when every
            # shard has a queryable copy on this node
            return False
        if str(svc.settings.get("search.backend")) != "jax":
            return False
        if mode == "force":
            return True
        try:
            n_dev = len(self._devices())
        except Exception:  # pragma: no cover - no jax backend
            return False
        return n_dev >= 2 and svc.num_shards >= 2

    def _all_shards_local(self) -> bool:
        """Distributed-mode gate: every shard of the index must have a
        QUERYABLE copy here — an installed engine whose node is the
        primary or an in-sync replica. A relocation-driven routing
        change that adds/removes local engines bumps the `_gens()` key
        (engine set changes), so the next ensure_snapshot rebuilds
        incrementally while in-flight launches keep serving off their
        pinned snapshot reference."""
        svc = self.service
        if svc.local_node is None:
            return True
        for sid in range(svc.num_shards):
            if sid not in svc._local:
                return False
            e = svc._entry(sid) or {}
            if (e.get("primary") != svc.local_node
                    and svc.local_node not in (e.get("in_sync") or [])):
                return False
        return True

    def _devices(self):
        devs = list(jax.devices())
        cap = mesh_devices_cap()
        return devs[:cap] if cap else devs

    @property
    def device_ids(self) -> Tuple[int, ...]:
        snap = self._snapshot
        if snap is not None and not snap.closed:
            return snap.device_ids
        return tuple(
            getattr(d, "id", i) for i, d in enumerate(self._devices())
        )

    # ---- snapshot lifecycle ----

    def _gens(self) -> tuple:
        svc = self.service
        try:
            return tuple(
                (sid, svc.local_shard(sid).change_generation)
                for sid in range(svc.num_shards)
            )
        except KeyError as e:
            # a shard relocated away between available() and here: the
            # caller degrades to the per-shard path for this request
            raise MeshUnavailable(str(e))

    def fresh(self) -> bool:
        snap = self._snapshot
        return snap is not None and not snap.closed and snap.gens == self._gens()

    def ensure_snapshot(self) -> _MeshSnapshot:
        gens = self._gens()
        snap = self._snapshot
        if snap is not None and not snap.closed and snap.gens == gens:
            return snap
        with self._lock:
            snap = self._snapshot
            gens = self._gens()
            if snap is not None and not snap.closed and snap.gens == gens:
                return snap
            new = self._build_snapshot(gens)
            old, self._snapshot = self._snapshot, new
            if old is not None:
                self.stats["rebuilds"] += 1
                # in-flight launches hold their own snapshot reference;
                # the ledger charge is released now, the arrays die with
                # the last reference (same contract as executor close)
                old.release()
            return new

    def _build_snapshot(self, gens) -> _MeshSnapshot:
        svc = self.service
        readers = {}
        executors = {}
        entries = []
        for sid in range(svc.num_shards):
            try:
                shard = svc.local_shard(sid)
            except KeyError as e:
                raise MeshUnavailable(str(e))
            ex = svc._executor(shard)
            from ..search.executor import NumpyExecutor

            if isinstance(ex, NumpyExecutor):
                raise MeshUnavailable("numpy backend shard")
            executors[sid] = ex
            readers[sid] = ex.reader
            for si, seg in enumerate(ex.reader.segments):
                if seg.num_docs > 0:
                    entries.append((sid, si))
        if not entries:
            raise MeshUnavailable("index has no live segments")
        devices = self._devices()
        if not devices:
            raise MeshUnavailable("no devices")
        n_data = mesh_data_axis()
        if BPAD % n_data or n_data > len(devices):
            n_data = 1
        mesh = make_mesh(len(entries), n_data=n_data, devices=devices)
        fold = fold_factor(mesh, len(entries))
        snap = _MeshSnapshot(mesh, fold, entries, readers, executors, gens)
        # incremental rebuild: adopt the PREVIOUS snapshot's host
        # staging stacks (plain arrays, not the snapshot itself) so
        # views can copy unchanged-entry rows instead of re-extracting —
        # a one-shard NRT refresh re-stages only that shard's rows
        old = self._snapshot
        if old is not None and not old.closed and old.host_stacks:
            snap.reuse_src = (old.entry_keys, old.host_stacks)
        # live ∧ in-range mask, shared by every family
        live = np.zeros((snap.e_pad, snap.n_docs_max), bool)

        def _fill_live(e: int) -> None:
            sid, si = snap.entries[e]
            n = readers[sid].segments[si].num_docs
            l = readers[sid].live_docs[si]
            live[e, :n] = True if l is None else l

        self._fill_stack(snap, "live", {"live": live}, _fill_live)
        snap.charge(live.nbytes)
        snap.live = jax.device_put(
            live, NamedSharding(mesh, P(SHARD_AXIS, None))
        )
        return snap

    def _fill_stack(self, snap, key, arrays, fill_entry) -> int:
        """Fills the leading-entry-axis rows of a stacked host view:
        entries whose (sid, shard-generation, si) key is unchanged from
        the previous snapshot copy their previous row (same envelope
        shape required); everything else re-extracts via `fill_entry`.
        Registers the stack for the NEXT rebuild and returns the reused
        row count."""
        prev_map = None
        prev_arrays = None
        if snap.reuse_src is not None:
            prev_keys, prev_stacks = snap.reuse_src
            got = prev_stacks.get(key)
            # ROW-shape compatibility only: appending a segment changes
            # the entry padding (leading axis) but unchanged shards'
            # rows still copy over as long as the per-row envelope
            # (t_max / n_docs_max / dims) is stable
            if got is not None and set(got) >= set(arrays) and all(
                got[name].shape[1:] == arr.shape[1:]
                and got[name].dtype == arr.dtype
                for name, arr in arrays.items()
            ):
                prev_arrays = got
                prev_map = {k: i for i, k in enumerate(prev_keys)}
        reused = 0
        for e in range(len(snap.entries)):
            pi = (
                prev_map.get(snap.entry_keys[e])
                if prev_map is not None
                else None
            )
            if pi is not None:
                for name, arr in arrays.items():
                    arr[e] = prev_arrays[name][pi]
                reused += 1
            else:
                fill_entry(e)
        if reused:
            self.stats["entries_reused"] += reused
            if not getattr(snap, "_counted_incremental", False):
                snap._counted_incremental = True
                self.stats["incremental_rebuilds"] += 1
        snap.host_stacks[key] = arrays
        return reused

    def close(self) -> None:
        with self._lock:
            snap, self._snapshot = self._snapshot, None
            if snap is not None:
                snap.release()

    # ---- stacked field views (lazy, per snapshot) ----

    def _text_view(self, snap: _MeshSnapshot, field: str) -> dict:
        view = snap.text.get(field)
        if view is not None:
            return view
        with self._lock:
            view = snap.text.get(field)
            if view is not None:
                return view
            bmxs = []
            tilings = []
            t_max = 1
            for sid, si in snap.entries:
                bmx = snap.executors[sid].block_index(si, field)
                bmxs.append(bmx)
                tilings.append(None if bmx is None else bmx.tiling)
                if bmx is not None:
                    t_max = max(t_max, int(bmx.tiling.doc_ids.shape[0]))
            doc_ids = np.full(
                (snap.e_pad, t_max, TILE), INVALID_DOC, np.int32
            )
            tfs = np.zeros((snap.e_pad, t_max, TILE), np.int32)
            inv = np.zeros((snap.e_pad, snap.n_docs_max), np.float32)

            def _fill_text(e: int) -> None:
                sid, si = snap.entries[e]
                tiling = tilings[e]
                if tiling is not None:
                    nt = int(tiling.doc_ids.shape[0])
                    doc_ids[e, :nt] = np.asarray(tiling.doc_ids)
                    tfs[e, :nt] = np.asarray(tiling.tfs)
                n = snap.readers[sid].segments[si].num_docs
                ex = snap.executors[sid]
                inv[e, :n] = np.asarray(ex._inv_norm(si, field, n))

            # unchanged-shard rows copy from the previous generation's
            # staging stack (no tiling download, no norm re-extract)
            self._fill_stack(
                snap,
                ("text", field),
                {"doc_ids": doc_ids, "tfs": tfs, "inv": inv},
                _fill_text,
            )
            nbytes = doc_ids.nbytes + tfs.nbytes + inv.nbytes
            snap.charge(nbytes)
            sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "doc_ids": jax.device_put(doc_ids, sh3),
                "tfs": jax.device_put(tfs, sh3),
                "inv_norm": jax.device_put(inv, sh2),
                "bmxs": bmxs,
            }
            snap.text[field] = view
            return view

    def _knn_view(self, snap: _MeshSnapshot, field: str) -> dict:
        view = snap.knn.get(field)
        if view is not None:
            return view
        with self._lock:
            view = snap.knn.get(field)
            if view is not None:
                return view
            mats = []
            for sid, si in snap.entries:
                vf = snap.readers[sid].segments[si].vectors.get(field)
                if vf is None:
                    mats.append(None)
                    continue
                mat = (
                    vf.unit_vectors
                    if vf.similarity == "cosine" and vf.unit_vectors is not None
                    else vf.vectors
                )
                mats.append((mat, vf))
            present = [m for m in mats if m is not None]
            if not present:
                raise MeshUnavailable(f"no entry has vector field [{field}]")
            dims = int(present[0][0].shape[1])
            similarity = present[0][1].similarity
            dtype = np.result_type(*[m[0].dtype for m in present])
            vectors = np.zeros((snap.e_pad, snap.n_docs_max, dims), dtype)
            cand = np.zeros((snap.e_pad, snap.n_docs_max), bool)
            n_per_entry = np.zeros(snap.e_pad, np.int64)
            live_stack = snap.host_stacks.get("live")
            live_host = (
                live_stack["live"]
                if live_stack is not None
                else np.asarray(jax.device_get(snap.live))
            )
            for e, (sid, si) in enumerate(snap.entries):
                got = mats[e]
                if got is None:
                    continue
                mat, vf = got
                if int(mat.shape[1]) != dims or vf.similarity != similarity:
                    raise MeshUnavailable(
                        f"vector field [{field}] has mixed dims/similarity"
                    )
                n_per_entry[e] = snap.readers[sid].segments[si].num_docs

            def _fill_knn(e: int) -> None:
                got = mats[e]
                if got is None:
                    return
                mat, vf = got
                n = int(n_per_entry[e])
                vectors[e, :n] = mat
                cand[e, :n] = vf.exists & live_host[e, :n]

            self._fill_stack(
                snap,
                ("knn", field, dims, similarity),
                {"vectors": vectors, "cand": cand},
                _fill_knn,
            )
            snap.charge(vectors.nbytes + cand.nbytes)
            sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "vectors": jax.device_put(vectors, sh3),
                "cand": jax.device_put(cand, sh2),
                "dims": dims,
                "similarity": similarity,
                "n_per_entry": n_per_entry,
            }
            snap.knn[field] = view
            return view

    def _sparse_view(
        self, snap: _MeshSnapshot, field: str, quantized: bool
    ) -> dict:
        """Stacked impact-ordered postings for one `sparse_vector`
        field: each entry's tile planes padded to the widest tile count,
        plus the per-entry SparseField handles (each entry has its OWN
        term dictionary / tile layout / dequant scales, so plan packing
        resolves per entry). Only the serving column for this
        `quantized` mode is stacked — the other column never rides the
        ICI."""
        key = ("sparse", field, bool(quantized))
        view = snap.text.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.text.get(key)
            if view is not None:
                return view
            sfs = []
            t_max = 1
            for sid, si in snap.entries:
                sf = (
                    getattr(snap.readers[sid].segments[si], "sparse", None)
                    or {}
                ).get(field)
                sfs.append(sf)
                if sf is not None:
                    t_max = max(t_max, int(sf.n_tiles))
            if all(sf is None for sf in sfs):
                raise MeshUnavailable(
                    f"no entry has sparse_vector field [{field}]"
                )
            vdtype = np.int8 if quantized else np.float32
            doc_ids = np.full(
                (snap.e_pad, t_max, TILE), INVALID_DOC, np.int32
            )
            values = np.zeros((snap.e_pad, t_max, TILE), vdtype)

            def _fill_sparse(e: int) -> None:
                sf = sfs[e]
                if sf is None:
                    return
                nt = int(sf.n_tiles)
                doc_ids[e, :nt] = np.asarray(sf.doc_ids)
                values[e, :nt] = np.asarray(
                    sf.qweights if quantized else sf.weights
                )

            self._fill_stack(
                snap,
                key,
                {"doc_ids": doc_ids, "values": values},
                _fill_sparse,
            )
            snap.charge(doc_ids.nbytes + values.nbytes)
            sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
            view = {
                "doc_ids": jax.device_put(doc_ids, sh3),
                "values": jax.device_put(values, sh3),
                "sfs": sfs,
            }
            snap.text[key] = view
            return view

    def _ann_view(self, snap: _MeshSnapshot, field: str, spec) -> dict:
        """Stacked IVF view: per-entry centroids (replicated scan),
        cluster-major permuted blocks + CSR bounds (clusters stay
        sharded with their entries). Reuses each entry's OWNING
        executor's IvfSegmentIndex, so the mesh path probes the exact
        same centroids/permutation as the per-shard path — parity by
        construction. Any entry without an index (small-segment floor,
        HBM degrade) raises MeshUnavailable and the per-shard
        coordinator serves the request with its own exact floor."""
        key = ("ann", field, spec)
        view = snap.knn.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.knn.get(key)
            if view is not None:
                return view
            idxs = []
            for sid, si in snap.entries:
                idx = snap.executors[sid].ann_index(si, field, spec)
                if idx is None:
                    raise MeshUnavailable(
                        f"entry [{sid}][{si}] has no IVF index for "
                        f"[{field}] (exact floor / HBM degrade)"
                    )
                idxs.append(idx)
            dims = idxs[0].dims
            similarity = idxs[0].similarity
            for idx in idxs:
                if idx.dims != dims or idx.similarity != similarity:
                    raise MeshUnavailable(
                        f"vector field [{field}] has mixed dims/similarity"
                    )
            quant = bool(spec.quantized) and all(
                i.host_qvecs_flat is not None for i in idxs
            )
            e_pad = snap.e_pad
            nlist_max = max(i.nlist for i in idxs)
            fmax = max(i.host_perm.shape[0] for i in idxs)
            cmax = max(i.cmax for i in idxs)
            cents = np.zeros((e_pad, nlist_max, dims), np.float32)
            cvalid = np.zeros((e_pad, nlist_max), bool)
            starts = np.zeros((e_pad, nlist_max), np.int32)
            counts = np.zeros((e_pad, nlist_max), np.int32)
            perm = np.zeros((e_pad, fmax), np.int32)
            if quant:
                vecs = np.zeros((e_pad, fmax, dims), np.int8)
                scales = np.zeros((e_pad, fmax), np.float32)
            else:
                vdt = np.result_type(
                    *[i.host_vecs_flat.dtype for i in idxs]
                )
                vecs = np.zeros((e_pad, fmax, dims), vdt)
                scales = None
            v2 = (
                np.zeros((e_pad, fmax), np.float32)
                if similarity == "l2_norm"
                else None
            )
            cand = np.zeros((e_pad, fmax), bool)
            n_per_entry = np.zeros(e_pad, np.int64)
            live_host = np.asarray(jax.device_get(snap.live))
            for e, ((sid, si), idx) in enumerate(zip(snap.entries, idxs)):
                vf = snap.readers[sid].segments[si].vectors[field]
                n = snap.readers[sid].segments[si].num_docs
                nl = idx.nlist
                F = idx.host_perm.shape[0]
                cents[e, :nl] = idx.host_centroids
                cvalid[e, :nl] = True
                starts[e, :nl] = idx.host_starts
                counts[e, :nl] = idx.host_counts
                perm[e, :F] = idx.host_perm
                if quant:
                    vecs[e, :F] = idx.host_qvecs_flat
                    scales[e, :F] = idx.host_scales_flat
                else:
                    vecs[e, :F] = idx.host_vecs_flat
                if v2 is not None:
                    hv = idx.host_vecs_flat.astype(np.float32)
                    v2[e, :F] = (hv * hv).sum(axis=1)
                base = vf.exists & live_host[e, :n]
                # candidate mask permuted into flat slot order (pad
                # slots stay False; the rank<count test masks them too)
                cand[e, : idx.n] = base[idx.host_perm[: idx.n]]
                n_per_entry[e] = n
            nbytes = (
                cents.nbytes + cvalid.nbytes + starts.nbytes
                + counts.nbytes + perm.nbytes + vecs.nbytes
                + cand.nbytes
                + (scales.nbytes if scales is not None else 0)
                + (v2.nbytes if v2 is not None else 0)
            )
            snap.charge(nbytes)
            sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "centroids": jax.device_put(cents, sh3),
                "cvalid": jax.device_put(cvalid, sh2),
                "starts": jax.device_put(starts, sh2),
                "counts": jax.device_put(counts, sh2),
                "perm": jax.device_put(perm, sh2),
                "vecs": jax.device_put(vecs, sh3),
                "scales": (
                    jax.device_put(scales, sh2) if scales is not None
                    else None
                ),
                "v2": jax.device_put(v2, sh2) if v2 is not None else None,
                "cand": jax.device_put(cand, sh2),
                "dims": dims,
                "similarity": similarity,
                "cmax": cmax,
                "nlists": [i.nlist for i in idxs],
                "n_per_entry": n_per_entry,
            }
            snap.knn[key] = view
            return view

    def _ann_step(self, snap, field, spec, kc):
        key = ("ann_step", field, spec, kc)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    view = self._ann_view(snap, field, spec)
                    step = build_mesh_ann_step(
                        snap.mesh,
                        view["centroids"],
                        view["cvalid"],
                        view["starts"],
                        view["counts"],
                        view["perm"],
                        view["vecs"],
                        view["scales"],
                        view["v2"],
                        view["cand"],
                        view["similarity"],
                        spec.nprobe,
                        kc,
                        view["cmax"],
                    )
                    snap.steps[key] = step
        return step

    # ---- stacked aggregation views (lazy, per snapshot) ----

    def _agg_num_view(self, snap: _MeshSnapshot, field: str) -> dict:
        """Stacked float32 doc-value column (min/max), exact int32 copy
        (sums), and exists mask [E, Nmax]."""
        from ..search import aggs_device

        key = ("num", field)
        view = snap.aggs.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.aggs.get(key)
            if view is not None:
                return view
            vals = np.zeros((snap.e_pad, snap.n_docs_max), np.float32)
            ivals = np.zeros((snap.e_pad, snap.n_docs_max), np.int32)
            exists = np.zeros((snap.e_pad, snap.n_docs_max), bool)
            for e, (sid, si) in enumerate(snap.entries):
                nf = snap.readers[sid].segments[si].numerics.get(field)
                if nf is None:
                    continue
                n = len(nf.values)
                vals[e, :n] = nf.values.astype(np.float32)
                exists[e, :n] = nf.exists
                p = aggs_device.col_profile(snap.executors[sid], si, field)
                if p.sum_exact and p.n_exist:
                    col = np.zeros(n, np.int32)
                    col[nf.exists] = (
                        nf.values[nf.exists].astype(np.int64).astype(
                            np.int32
                        )
                    )
                    ivals[e, :n] = col
            snap.charge(vals.nbytes + ivals.nbytes + exists.nbytes)
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "values": jax.device_put(vals, sh2),
                "ivalues": jax.device_put(ivals, sh2),
                "exists": jax.device_put(exists, sh2),
            }
            snap.aggs[key] = view
            return view

    def _agg_ord_view(self, snap: _MeshSnapshot, field: str) -> dict:
        """GLOBAL ordinal table + stacked per-entry multi-value CSR
        mapped onto it: the ordinal-table union across the ``shards``
        axis happens here at snapshot build, so the device step only
        scatter-adds per-entry count vectors and ``psum``s them."""
        key = ("ord", field)
        view = snap.aggs.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.aggs.get(key)
            if view is not None:
                return view
            per_entry = []
            vocab = set()
            l_max = 1
            for sid, si in snap.entries:
                of = snap.readers[sid].segments[si].ordinals.get(field)
                per_entry.append(of)
                if of is not None:
                    vocab.update(of.ord_terms)
                    l_max = max(l_max, len(of.mv_ords))
            gterms = sorted(vocab)
            gmap = {t: i for i, t in enumerate(gterms)}
            gords = np.zeros((snap.e_pad, l_max), np.int32)
            edocs = np.zeros((snap.e_pad, l_max), np.int32)
            evalid = np.zeros((snap.e_pad, l_max), bool)
            for e, of in enumerate(per_entry):
                if of is None or not len(of.mv_ords):
                    continue
                L = len(of.mv_ords)
                remap = np.array(
                    [gmap[t] for t in of.ord_terms], np.int32
                )
                gords[e, :L] = remap[of.mv_ords]
                edocs[e, :L] = np.repeat(
                    np.arange(len(of.mv_offsets) - 1, dtype=np.int32),
                    np.diff(of.mv_offsets),
                )
                evalid[e, :L] = True
            snap.charge(gords.nbytes + edocs.nbytes + evalid.nbytes)
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "gterms": gterms,
                "gords": jax.device_put(gords, sh2),
                "edocs": jax.device_put(edocs, sh2),
                "evalid": jax.device_put(evalid, sh2),
            }
            snap.aggs[key] = view
            return view

    def _agg_histo_view(
        self, snap: _MeshSnapshot, field: str, interval: int, offset: int
    ) -> dict:
        """Stacked GLOBAL-relative histogram bucket ids (host int64
        floor-division, exact at any span) + exists [E, Nmax]."""
        key = ("histo", field, int(interval), int(offset))
        view = snap.aggs.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.aggs.get(key)
            if view is not None:
                return view
            qs = []
            for sid, si in snap.entries:
                nf = snap.readers[sid].segments[si].numerics.get(field)
                if nf is None or not nf.exists.any():
                    qs.append(None)
                    continue
                qs.append(
                    (nf.values[nf.exists].astype(np.int64) - offset)
                    // interval
                )
            qmins = [int(q.min()) for q in qs if q is not None]
            if not qmins:
                raise MeshUnavailable(f"no entry has field [{field}]")
            qmin = min(qmins)
            nb = max(int(q.max()) for q in qs if q is not None) - qmin + 1
            from ..search.aggs_device import MAX_DEVICE_BUCKETS

            if nb > MAX_DEVICE_BUCKETS:
                raise MeshUnavailable(f"histogram would make {nb} buckets")
            ids = np.zeros((snap.e_pad, snap.n_docs_max), np.int32)
            exists = np.zeros((snap.e_pad, snap.n_docs_max), bool)
            for e, ((sid, si), q) in enumerate(zip(snap.entries, qs)):
                if q is None:
                    continue
                nf = snap.readers[sid].segments[si].numerics.get(field)
                n = len(nf.values)
                col = np.zeros(n, np.int32)
                col[nf.exists] = (q - qmin).astype(np.int32)
                ids[e, :n] = col
                exists[e, :n] = nf.exists
            snap.charge(ids.nbytes + exists.nbytes)
            sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
            view = {
                "qmin": qmin,
                "nb": nb,
                "nbpad": scoring.next_bucket(nb, 16),
                "ids": jax.device_put(ids, sh2),
                "exists": jax.device_put(exists, sh2),
            }
            snap.aggs[key] = view
            return view

    # ---- compiled step cache ----

    def _text_step(self, snap, fields, kb, t_shapes, with_cnt,
                   count_signed, combine, tie):
        key = ("text", fields, kb, t_shapes, with_cnt, count_signed,
               combine, tie)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    views = [self._text_view(snap, f) for f in fields]
                    step = build_mesh_text_step(
                        snap.mesh,
                        [v["doc_ids"] for v in views],
                        [v["tfs"] for v in views],
                        [v["inv_norm"] for v in views],
                        snap.live,
                        kb,
                        with_cnt=with_cnt,
                        count_signed=count_signed,
                        combine=combine,
                        tie=tie,
                    )
                    snap.steps[key] = step
        return step

    def _rerank_view(self, snap: _MeshSnapshot, model) -> dict:
        """Stacked `rank_vectors` view for one RerankModel: per-entry
        CSR bounds over LOCAL doc ids plus each entry's flat token
        block (tail-padded with `tmax` zero rows, the ops/ivf gather
        trick), int8 + per-token scales for quantized models. Entries
        without the field read as zero-token docs (maxsim 0) — exactly
        the per-shard column's semantics."""
        key = ("rerank", model)
        view = snap.text.get(key)
        if view is not None:
            return view
        with self._lock:
            view = snap.text.get(key)
            if view is not None:
                return view
            from ..models import rerank as rerank_model

            n_max = snap.n_docs_max
            tmax = 1
            flat_max = 1
            mvfs = []
            for sid, si in snap.entries:
                mvf = snap.readers[sid].segments[si].multi_vectors.get(
                    model.field
                )
                mvfs.append(mvf)
                if mvf is not None and len(mvf.tok_vectors):
                    tmax = max(tmax, mvf.max_tokens)
                    flat_max = max(flat_max, int(len(mvf.tok_vectors)))
            dims = int(model.dims) or next(
                (
                    int(m.tok_vectors.shape[1])
                    for m in mvfs
                    if m is not None and len(m.tok_vectors)
                ),
                1,
            )
            fmax = flat_max + tmax
            starts = np.zeros((snap.e_pad, n_max), np.int32)
            counts = np.zeros((snap.e_pad, n_max), np.int32)
            toks = np.zeros((snap.e_pad, fmax, dims), np.float32)
            for e, mvf in enumerate(mvfs):
                if mvf is None or not len(mvf.tok_vectors):
                    continue
                n = len(mvf.tok_offsets) - 1
                offs = mvf.tok_offsets.astype(np.int64)
                starts[e, :n] = offs[:-1]
                counts[e, :n] = np.diff(offs)
                toks[e, : len(mvf.tok_vectors)] = mvf.tok_vectors
            scales_dev = None
            if model.quantized:
                flat = toks.reshape(-1, dims)
                qv, scales = rerank_model.quantize_tokens(flat)
                toks_q = qv.reshape(snap.e_pad, fmax, dims)
                scales = scales.reshape(snap.e_pad, fmax)
                nbytes = (
                    starts.nbytes + counts.nbytes + toks_q.nbytes
                    + scales.nbytes
                )
                snap.charge(nbytes)
                sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
                sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
                toks_dev = jax.device_put(toks_q, sh3)
                scales_dev = jax.device_put(scales, sh2)
            else:
                nbytes = starts.nbytes + counts.nbytes + toks.nbytes
                snap.charge(nbytes)
                sh3 = NamedSharding(snap.mesh, P(SHARD_AXIS, None, None))
                sh2 = NamedSharding(snap.mesh, P(SHARD_AXIS, None))
                toks_dev = jax.device_put(toks, sh3)
            view = {
                "starts": jax.device_put(
                    starts, NamedSharding(snap.mesh, P(SHARD_AXIS, None))
                ),
                "counts": jax.device_put(
                    counts, NamedSharding(snap.mesh, P(SHARD_AXIS, None))
                ),
                "toks": toks_dev,
                "scales": scales_dev,
                "tmax": int(tmax),
                "dims": dims,
            }
            snap.text[key] = view
            return view

    def _rerank_step(self, snap, field, kb, t_shape, with_cnt, model,
                     k_req, window, qb):
        key = ("rerank", field, model, kb, t_shape, with_cnt, k_req,
               window, qb)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    view = self._text_view(snap, field)
                    rview = self._rerank_view(snap, model)
                    step = build_mesh_rerank_step(
                        snap.mesh,
                        view["doc_ids"],
                        view["tfs"],
                        view["inv_norm"],
                        snap.live,
                        rview["starts"],
                        rview["counts"],
                        rview["toks"],
                        rview["scales"],
                        kb,
                        k_req,
                        window,
                        rview["tmax"],
                        with_cnt=with_cnt,
                    )
                    snap.steps[key] = step
        return step

    def _knn_step(self, snap, field, kc):
        key = ("knn", field, kc)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    view = self._knn_view(snap, field)
                    step = build_mesh_knn_step(
                        snap.mesh,
                        view["vectors"],
                        view["cand"],
                        view["similarity"],
                        kc,
                    )
                    snap.steps[key] = step
        return step

    def _sparse_step(self, snap, field, quantized, kb, t_shape):
        key = ("sparse", field, bool(quantized), kb, t_shape)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    view = self._sparse_view(snap, field, quantized)
                    step = build_mesh_sparse_step(
                        snap.mesh,
                        view["doc_ids"],
                        view["values"],
                        snap.live,
                        kb,
                    )
                    snap.steps[key] = step
        return step

    # ---- plan packing (host side; mirrors the sequential builders) ----

    def _rows_for(self, snap, n_jobs: int) -> int:
        """The SPMD launch's query-row bucket: the same pad-bucket
        ladder as the single-device batcher, constrained to a multiple
        of the mesh ``data`` axis (the query batch is sharded along it)
        so routing a single query through the mesh doesn't reintroduce
        the full BPAD-row floor."""
        n_data = int(snap.mesh.shape.get(DATA_AXIS, 1))
        return min(
            bucket_for(n_jobs, batch_buckets(BPAD), multiple_of=n_data),
            max(BPAD, n_data),
        )

    def _pack_match(self, snap, view, jobs, t_cap, rows: int):
        """Per-(entry, job) tile plans in EXACTLY the sequential
        _run_group order: BlockMaxIndex.plan term order, all tiles
        essential (no pruning on the mesh path)."""
        e_pad = snap.e_pad
        lists: List[List[Tuple[np.ndarray, np.ndarray]]] = []
        t_max = 1
        slots = 0
        for e in range(len(snap.entries)):
            bmx = view["bmxs"][e]
            row = []
            for j in jobs:
                if bmx is None:
                    row.append((None, None))
                    continue
                plans = bmx.plan(list(j.plan.terms), j.plan.boost)
                tl = [
                    np.arange(
                        p.tile_start, p.tile_start + p.tile_count,
                        dtype=np.int64,
                    )
                    for p in plans
                ]
                wl = [
                    np.full(p.tile_count, p.weight, np.float32)
                    for p in plans
                ]
                ti = np.concatenate(tl) if tl else np.empty(0, np.int64)
                tw = np.concatenate(wl) if wl else np.empty(0, np.float32)
                if len(ti) > t_cap:
                    raise MeshUnavailable(
                        f"match plan overflows mesh tile cap [{t_cap}]"
                    )
                t_max = max(t_max, len(ti))
                slots += len(ti)
                row.append((ti, tw))
            lists.append(row)
        T = scoring.next_bucket(t_max)
        ti_a = np.zeros((e_pad, rows, T), np.int32)
        tw_a = np.zeros((e_pad, rows, T), np.float32)
        tv_a = np.zeros((e_pad, rows, T), bool)
        for e, row in enumerate(lists):
            for ji, (ti, tw) in enumerate(row):
                if ti is None or not len(ti):
                    continue
                ti_a[e, ji, : len(ti)] = ti
                tw_a[e, ji, : len(ti)] = tw
                tv_a[e, ji, : len(ti)] = True
        return ti_a, tw_a, tv_a, T, slots

    def _pack_serve_field(self, snap, view, jobs, field, t_cap, rows: int):
        """One field's signed-weight tile plans (the MultiFusedScorer
        weight-sign convention via JaxExecutor.fused_plan_field's float
        path: w = weights[tid] * boost * term_boost, negated when the
        term only scores)."""
        e_pad = snap.e_pad
        lists = []
        t_max = 1
        slots = 0
        for e in range(len(snap.entries)):
            bmx = view["bmxs"][e]
            row = []
            for j in jobs:
                group = next(
                    g for g in j.plan.groups if g.field == field
                )
                if bmx is None:
                    row.append((None, None))
                    continue
                tiling = bmx.tiling
                tl: List[np.ndarray] = []
                wl: List[np.ndarray] = []
                for t, tb, counted in group.terms:
                    tid = bmx._term_index.get(t)
                    if tid is None or not int(tiling.term_tile_count[tid]):
                        continue
                    w = float(bmx.weights[tid]) * j.plan.boost * tb
                    if w < 0.0:
                        raise MeshUnavailable("negative term weight")
                    if w == 0.0:
                        w = 1e-30
                    if not counted:
                        w = -w
                    s0 = int(tiling.term_tile_start[tid])
                    c = int(tiling.term_tile_count[tid])
                    tl.append(np.arange(s0, s0 + c, dtype=np.int64))
                    wl.append(np.full(c, w, np.float32))
                ti = np.concatenate(tl) if tl else np.empty(0, np.int64)
                tw = np.concatenate(wl) if wl else np.empty(0, np.float32)
                if len(ti) > t_cap:
                    raise MeshUnavailable(
                        f"serve plan overflows mesh tile cap [{t_cap}]"
                    )
                t_max = max(t_max, len(ti))
                slots += len(ti)
                row.append((ti, tw))
            lists.append(row)
        T = scoring.next_bucket(t_max)
        ti_a = np.zeros((e_pad, rows, T), np.int32)
        tw_a = np.zeros((e_pad, rows, T), np.float32)
        tv_a = np.zeros((e_pad, rows, T), bool)
        for e, row in enumerate(lists):
            for ji, (ti, tw) in enumerate(row):
                if ti is None or not len(ti):
                    continue
                ti_a[e, ji, : len(ti)] = ti
                tw_a[e, ji, : len(ti)] = tw
                tv_a[e, ji, : len(ti)] = True
        return ti_a, tw_a, tv_a, T, slots

    def _pack_sparse(self, snap, view, jobs, quantized, t_cap, rows: int):
        """Per-(entry, job) impact-tile plans in EXACTLY the sequential
        _dispatch_sparse_group order: ops/impact.impact_tile_lists term
        order with each entry's dequant scales folded on host, every
        tile essential (no pruning on the mesh path)."""
        from ..ops import impact as impact_ops

        e_pad = snap.e_pad
        lists: List[List[Tuple[Optional[np.ndarray], Optional[np.ndarray]]]] = []
        t_max = 1
        slots = 0
        for e in range(len(snap.entries)):
            sf = view["sfs"][e]
            row = []
            for j in jobs:
                if sf is None or not sf.n_tiles:
                    row.append((None, None))
                    continue
                _tids, tws, _bws, starts, counts = impact_ops.impact_tile_lists(
                    sf, j.plan.terms, j.plan.weights, quantized
                )
                tl = [
                    np.arange(s0, s0 + c, dtype=np.int64)
                    for s0, c in zip(starts, counts)
                ]
                wl = [
                    np.full(int(c), w, np.float32)
                    for c, w in zip(counts, tws)
                ]
                ti = np.concatenate(tl) if tl else np.empty(0, np.int64)
                tw = np.concatenate(wl) if wl else np.empty(0, np.float32)
                if len(ti) > t_cap:
                    raise MeshUnavailable(
                        f"sparse plan overflows mesh tile cap [{t_cap}]"
                    )
                t_max = max(t_max, len(ti))
                slots += len(ti)
                row.append((ti, tw))
            lists.append(row)
        T = scoring.next_bucket(t_max)
        ti_a = np.zeros((e_pad, rows, T), np.int32)
        tw_a = np.zeros((e_pad, rows, T), np.float32)
        tv_a = np.zeros((e_pad, rows, T), bool)
        for e, row in enumerate(lists):
            for ji, (ti, tw) in enumerate(row):
                if ti is None or not len(ti):
                    continue
                ti_a[e, ji, : len(ti)] = ti
                tw_a[e, ji, : len(ti)] = tw
                tv_a[e, ji, : len(ti)] = True
        return ti_a, tw_a, tv_a, T, slots

    # ---- dispatch / collect (batcher worker entry points) ----

    def dispatch_match(self, jobs, kb: int):
        snap = self.ensure_snapshot()
        field = jobs[0].plan.field
        view = self._text_view(snap, field)
        rows = self._rows_for(snap, len(jobs))
        ti, tw, tv, T, slots = self._pack_match(
            snap, view, jobs, mesh_t_max(), rows
        )
        msm = np.ones(rows, np.int32)
        msm[: len(jobs)] = [j.plan.msm for j in jobs]
        with_cnt = any(j.plan.msm > 1 for j in jobs)
        rescore = getattr(jobs[0].plan, "rescore", None)
        if rescore is not None:
            return self._dispatch_match_rescore(
                snap, jobs, field, kb, rows, ti, tw, tv, msm, with_cnt,
                slots, rescore,
            )
        step = self._text_step(
            snap, (field,), kb, (T,), with_cnt, False, "sum", 0.0
        )
        with _LAUNCH_LOCK:
            out = step((ti,), (tw,), (tv,), msm)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        flops = scoring.text_plan_flops(slots, 0, 0)
        return {"snap": snap, "out": out, "flops": flops, "rows": rows}

    def _dispatch_match_rescore(self, snap, jobs, field, kb, rows,
                                ti, tw, tv, msm, with_cnt, slots,
                                rescore):
        """The fused first-stage + rerank SPMD launch: each entry
        rescores its own local top-k BEFORE the all_gather, so the ICI
        carries already-reranked candidates. Routing precondition: one
        live segment per shard — that makes the per-entry window
        identical to the per-shard path's post-merge window, so the
        two paths agree bit-for-bit."""
        from ..common.faults import faults as _faults
        from ..models import rerank as rerank_model
        from ..ops import rerank as rerank_ops

        model, spec = rescore
        _faults.check("rerank.score", field=model.field, mesh=1)
        sids = [sid for sid, _si in snap.entries]
        if len(set(sids)) != len(sids):
            raise MeshUnavailable(
                "mesh rescore needs one live segment per shard"
            )
        rview = self._rerank_view(snap, model)
        k_req = int(jobs[0].k)
        window = min(int(spec.window_size), k_req)
        qv = rerank_model.prepare_query_vectors(
            spec.query_vectors, model.dims, model.similarity
        )
        qb = max(4, scoring.next_bucket(max(len(qv), 1), 4))
        qtoks = np.zeros((rows, qb, rview["dims"]), np.float32)
        qvalid = np.zeros((rows, qb), bool)
        qtoks[:, : len(qv)] = qv[None, :, :]
        qvalid[:, : len(qv)] = True
        weights = np.asarray(
            [spec.query_weight, spec.rescore_query_weight], np.float32
        )
        T = int(ti.shape[2])
        step = self._rerank_step(
            snap, field, kb, T, with_cnt, model, k_req, window, qb
        )
        with _LAUNCH_LOCK:
            out = step(ti, tw, tv, msm, qtoks, qvalid, weights)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        flops = scoring.text_plan_flops(slots, 0, 0) + (
            rerank_ops.rerank_flops(
                len(jobs), qb, min(kb, snap.n_docs_max),
                rview["tmax"], rview["dims"],
            )
            * snap.e_pad
        )
        return {
            "snap": snap, "out": out, "flops": flops, "rows": rows,
            "rescored": (model, spec, window),
        }

    def dispatch_serve(self, jobs, kb: int):
        snap = self.ensure_snapshot()
        plan0 = jobs[0].plan
        fields = plan0.fields
        t_cap = mesh_t_max()
        rows = self._rows_for(snap, len(jobs))
        ti_f, tw_f, tv_f, t_shapes = [], [], [], []
        slots = 0
        for f in fields:
            view = self._text_view(snap, f)
            ti, tw, tv, T, s = self._pack_serve_field(
                snap, view, jobs, f, t_cap, rows
            )
            ti_f.append(ti)
            tw_f.append(tw)
            tv_f.append(tv)
            t_shapes.append(T)
            slots += s
        msm = np.ones(rows, np.int32)
        msm[: len(jobs)] = [j.plan.msm for j in jobs]
        step = self._text_step(
            snap, fields, kb, tuple(t_shapes), True, True,
            plan0.combine, float(plan0.tie),
        )
        with _LAUNCH_LOCK:
            out = step(tuple(ti_f), tuple(tw_f), tuple(tv_f), msm)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        flops = scoring.text_plan_flops(slots, 0, 0)
        return {"snap": snap, "out": out, "flops": flops, "rows": rows}

    def collect_match(self, jobs, pend):
        self._collect_text(jobs, pend)

    collect_serve = collect_match

    def _collect_text(self, jobs, pend):
        snap = pend["snap"]
        ms, me, md, tot = jax.device_get(pend["out"])
        rescored = pend.get("rescored")
        if rescored is not None:
            from ..models import rerank as rerank_model

            _model, _spec, window = rescored
        for ji, j in enumerate(jobs):
            finite = np.isfinite(ms[ji])
            hits = [
                self._hit(snap, float(s), int(e), int(d))
                for s, e, d in zip(
                    ms[ji][finite][: j.k],
                    me[ji][finite][: j.k],
                    md[ji][finite][: j.k],
                )
            ]
            if rescored is not None:
                rerank_model.note_rescore(window, device=True)
            j.result = MeshTopDocs(
                total=int(tot[ji]),
                relation="eq",
                max_score=hits[0].score if hits else None,
                hits=hits,
                snapshot=snap,
            )
            j.event.set()

    def dispatch_sparse(self, jobs, kb: int):
        """One SPMD learned-sparse launch for a same-(field, spec) job
        group. The `sparse.score` fault site fires with mesh=1 here —
        an injected error degrades the whole request to the per-shard
        path (indices._mesh_search's fallback), where the site fires
        again per segment with the host dense oracle as the terminal
        backstop."""
        from ..common.faults import faults as _faults
        from ..ops import impact as impact_ops
        from ..search import sparse as sparse_mod

        snap = self.ensure_snapshot()
        plan0 = jobs[0].plan
        field = plan0.field
        quantized = bool(plan0.spec.quantized)
        _faults.check("sparse.score", field=field, mesh=1)
        view = self._sparse_view(snap, field, quantized)
        rows = self._rows_for(snap, len(jobs))
        ti, tw, tv, T, slots = self._pack_sparse(
            snap, view, jobs, quantized, mesh_t_max(), rows
        )
        step = self._sparse_step(snap, field, quantized, kb, T)
        with _LAUNCH_LOCK:
            out = step(ti, tw, tv)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        sparse_mod.note_search(len(jobs), quantized, slots, 0)
        flops = impact_ops.sparse_flops(slots)
        return {"snap": snap, "out": out, "flops": flops, "rows": rows}

    def collect_sparse(self, jobs, pend):
        self._collect_text(jobs, pend)

    def dispatch_knn(self, jobs, kb: int):
        snap = self.ensure_snapshot()
        field = jobs[0].plan.field
        if any(j.plan.boost <= 0.0 for j in jobs):
            # a zero/negative boost would reorder under the
            # post-selection multiply — same host-merge rule as the
            # sequential collect
            raise MeshUnavailable("non-positive knn boost")
        spec = jobs[0].plan.ann  # shared: ann rides the group key
        if spec is not None:
            # IVF tier on the mesh: the `ann.probe` fault site fires
            # here too (ctx mesh=1) — an injected error surfaces to
            # _mesh_search, which degrades to the per-shard path (its
            # own ann.probe checks then prove the exact fallback)
            from ..common.faults import faults as _faults

            _faults.check("ann.probe", field=field, mesh=1)
            view = self._ann_view(snap, field, spec)
        else:
            view = self._knn_view(snap, field)
        dims = view["dims"]
        n_max = snap.n_docs_max
        rows = self._rows_for(snap, len(jobs))
        q = np.zeros((rows, dims), np.float32)
        nc = np.zeros((snap.e_pad, rows), np.int32)
        max_nc = 1
        for ji, j in enumerate(jobs):
            if len(j.plan.vector) != dims:
                raise MeshUnavailable("query vector dims mismatch")
            q[ji] = np.asarray(j.plan.vector, np.float32)
            for e in range(len(snap.entries)):
                n = int(view["n_per_entry"][e])
                if n:
                    nc[e, ji] = min(j.plan.num_candidates, n)
            max_nc = max(max_nc, min(j.plan.num_candidates, n_max))
        kc = min(max(scoring.next_bucket(max_nc, 16), 16), n_max)
        if spec is not None:
            from ..ops import ivf
            from ..search import ann as ann_mod

            step = self._ann_step(snap, field, spec, kc)
            with _LAUNCH_LOCK:
                out = step(q, nc)
            with self._lock:
                self.stats["launches"] += 1
                self.stats["jobs"] += len(jobs)
            flops = sum(
                ivf.ann_flops(
                    len(jobs), nl, spec.nprobe, view["cmax"], dims
                )
                for nl in view["nlists"]
            )
            for nl in view["nlists"]:
                ann_mod.note_search(spec.nprobe, nl, jobs=len(jobs))
            return {"snap": snap, "out": out, "flops": flops, "rows": rows}
        step = self._knn_step(snap, field, kc)
        with _LAUNCH_LOCK:
            out = step(q, nc)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        total_docs = int(view["n_per_entry"].sum())
        flops = scoring.knn_flops(len(jobs), total_docs, dims)
        return {"snap": snap, "out": out, "flops": flops, "rows": rows}

    def collect_knn(self, jobs, pend):
        from ..common.faults import faults

        faults.check("knn.collect", jobs=len(jobs), mesh=1)
        snap = pend["snap"]
        ms, me, md, counts = jax.device_get(pend["out"])
        shard_of = [sid for sid, _si in snap.entries]
        n_entries = len(shard_of)
        for ji, j in enumerate(jobs):
            boost = j.plan.boost
            # the sequential path cuts at k PER SHARD (each shard's
            # page is its top min(plan.k, size) after the nc rank cut)
            # before the coordinator's global page: walk the ordered
            # stream applying the same per-shard caps
            cap_shard = min(j.plan.k, j.k)
            taken: Dict[int, int] = {}
            hits: List[MeshHit] = []
            row_s, row_e, row_d = ms[ji], me[ji], md[ji]
            for pos in range(len(row_s)):
                s = row_s[pos]
                if not np.isfinite(s):
                    break  # score-desc stream: only -inf padding left
                e = int(row_e[pos])
                if e >= n_entries:  # pragma: no cover - padded entry
                    continue
                sid = shard_of[e]
                got = taken.get(sid, 0)
                if got >= cap_shard:
                    continue
                taken[sid] = got + 1
                hits.append(
                    self._hit(snap, float(s) * boost, e, int(row_d[pos]))
                )
                if len(hits) >= j.k:
                    break
            # the sequential coordinator's total is Σ per-shard totals,
            # each capped at k — reproduce it from the per-entry counts
            per_shard: Dict[int, int] = {}
            for e, sid in enumerate(shard_of):
                per_shard[sid] = per_shard.get(sid, 0) + int(counts[ji, e])
            total = sum(min(c, j.plan.k) for c in per_shard.values())
            j.result = MeshTopDocs(
                total=total,
                relation="eq",
                max_score=hits[0].score if hits else None,
                hits=hits,
                snapshot=snap,
            )
            j.event.set()

    # ---- mesh aggregations (one SPMD launch per agg-body group) ----

    def compile_agg(self, nodes, mplan, mappings) -> "MeshAggPlan":
        """Compiles a size:0 agg body for the mesh step. Supported on
        this path: metric leaves sum/avg/min/max/value_count/stats,
        keyword terms, histogram / date_histogram (fixed intervals) —
        all WITHOUT subs; anything else raises MeshUnavailable and the
        per-shard path (with its own device engine) serves the request.
        The same float-exactness profiles as search/aggs_device gate
        routing, with the sum window tightened to the GLOBAL Σ|v| since
        psum accumulates float32 partial sums across the whole index."""
        from ..index.mapping import KEYWORD
        from ..search import aggs_device
        from ..search.aggs import PIPELINE_TYPES, _int_param, _norm_order
        from ..search.aggs_device import (
            I32_SUM_BOUND,
            _METRIC_KINDS,
            _NEEDS_CMP,
            _NEEDS_SUM,
            _parse_dh_interval,
        )

        snap = self.ensure_snapshot()
        specs = []
        for node in nodes:
            if node.type in PIPELINE_TYPES:
                continue
            if node.subs:
                raise MeshUnavailable("mesh aggs do not nest")
            if node.type in _METRIC_KINDS and node.type != "percentiles":
                field = node.params.get("field")
                if field is None:
                    raise MeshUnavailable("metric without a field")
                mf = mappings.get(field)
                if mf is not None and mf.type in ("keyword", "text"):
                    raise MeshUnavailable("keyword metric")
                abs_total = 0.0
                for sid, si in snap.entries:
                    p = aggs_device.col_profile(
                        snap.executors[sid], si, field
                    )
                    abs_total += p.abs_sum
                    if node.type in _NEEDS_SUM and not (
                        not p.present or p.n_exist == 0 or p.integer_valued
                    ):
                        raise MeshUnavailable("non-integer sum column")
                    if node.type in _NEEDS_CMP and not p.cmp_exact:
                        raise MeshUnavailable("non-f32-exact column")
                if node.type in _NEEDS_SUM and abs_total >= I32_SUM_BOUND:
                    raise MeshUnavailable("sum outside the int32 window")
                specs.append(
                    ("metric", node.name, node.type, field)
                )
            elif node.type == "terms":
                field = node.params.get("field")
                mf = mappings.get(field) if field else None
                if mf is None or mf.type != KEYWORD:
                    raise MeshUnavailable("mesh terms needs keyword")
                order = _norm_order(
                    node.params.get("order", {"_count": "desc"})
                )
                if next(iter(order)) not in ("_count", "_key"):
                    raise MeshUnavailable("terms order")
                size = _int_param(node, "size", 10)
                shard_size = _int_param(
                    node, "shard_size", max(int(size * 1.5) + 10, size)
                )
                specs.append(
                    ("terms_kw", node.name, field, size, shard_size,
                     tuple(order.items()))
                )
            elif node.type in ("histogram", "date_histogram"):
                field = node.params.get("field")
                if field is None:
                    raise MeshUnavailable("histogram without a field")
                date = node.type == "date_histogram"
                if date:
                    interval, cal = _parse_dh_interval(node.params)
                    if cal is not None:
                        raise MeshUnavailable("calendar interval")
                    offset = 0
                else:
                    interval = float(node.params.get("interval", 0))
                    offset = float(node.params.get("offset", 0))
                    if (
                        interval <= 0
                        or interval != int(interval)
                        or offset != int(offset)
                    ):
                        raise MeshUnavailable("non-integer interval")
                for sid, si in snap.entries:
                    p = aggs_device.col_profile(
                        snap.executors[sid], si, field
                    )
                    if p.present and p.n_exist and not p.integer_valued:
                        raise MeshUnavailable("non-integer histogram col")
                specs.append(
                    ("histo", node.name, field, int(interval), int(offset),
                     date)
                )
            else:
                raise MeshUnavailable(f"mesh agg type [{node.type}]")
        return MeshAggPlan(nodes, specs, mplan)

    def dispatch_agg(self, jobs):
        snap = self.ensure_snapshot()
        plan0 = jobs[0].plan
        rows = self._rows_for(snap, len(jobs))
        node_descs = []
        collect_meta = []
        for spec in plan0.specs:
            kind = spec[0]
            if kind == "metric":
                view = self._agg_num_view(snap, spec[3])
                node_descs.append(
                    ("metric", view["values"], view["ivalues"],
                     view["exists"])
                )
                collect_meta.append((spec, None))
            elif kind == "terms_kw":
                view = self._agg_ord_view(snap, spec[2])
                nbpad = scoring.next_bucket(
                    max(len(view["gterms"]), 1), 16
                )
                node_descs.append(
                    ("counts_entry", view["gords"], view["edocs"],
                     view["evalid"], nbpad)
                )
                collect_meta.append((spec, view["gterms"]))
            else:  # histo
                view = self._agg_histo_view(
                    snap, spec[2], spec[3], spec[4]
                )
                node_descs.append(
                    ("counts_doc", view["ids"], view["exists"],
                     view["nbpad"])
                )
                collect_meta.append((spec, view["qmin"]))
        with_cnt = any(j.plan.msm > 1 for j in jobs)
        if plan0.mplan is not None:
            field = plan0.mplan.field
            tview = self._text_view(snap, field)
            ti, tw, tv, T, slots = self._pack_match(
                snap, tview, jobs, mesh_t_max(), rows
            )
            text = (
                tview["doc_ids"], tview["tfs"], tview["inv_norm"]
            )
        else:
            field = None
            T = 1
            slots = 0
            ti = np.zeros((snap.e_pad, rows, 1), np.int32)
            tw = np.zeros((snap.e_pad, rows, 1), np.float32)
            tv = np.zeros((snap.e_pad, rows, 1), bool)
            text = None
        msm = np.ones(rows, np.int32)
        msm[: len(jobs)] = [j.plan.msm for j in jobs]
        key = ("agg", plan0.sig, field, T, rows, with_cnt)
        step = snap.steps.get(key)
        if step is None:
            with self._lock:
                step = snap.steps.get(key)
                if step is None:
                    step = build_mesh_agg_step(
                        snap.mesh, snap.live, node_descs, text,
                        with_cnt,
                    )
                    snap.steps[key] = step
        with _LAUNCH_LOCK:
            out = step(ti, tw, tv, msm)
        with self._lock:
            self.stats["launches"] += 1
            self.stats["jobs"] += len(jobs)
        n_total = sum(
            snap.readers[sid].segments[si].num_docs
            for sid, si in snap.entries
        )
        from ..ops.agg_kernels import agg_flops

        flops = scoring.text_plan_flops(slots, 0, 0) + agg_flops(
            n_total, len(node_descs)
        )
        return {
            "snap": snap, "out": out, "meta": collect_meta,
            "flops": flops, "rows": rows,
        }

    def collect_agg(self, jobs, pend):
        from ..search import aggs_device
        from ..search.aggs import _bkey, _order_buckets
        from ..search.aggs_device import _metric_partial

        snap = pend["snap"]
        outs = jax.device_get(pend["out"])
        totals, maxs = outs[0], outs[1]
        for ji, j in enumerate(jobs):
            partials = {}
            idx = 2
            for spec, extra in pend["meta"]:
                kind, name = spec[0], spec[1]
                if kind == "metric":
                    c = int(outs[idx][ji])
                    s = float(outs[idx + 1][ji])
                    mn = float(outs[idx + 2][ji])
                    mx = float(outs[idx + 3][ji])
                    idx += 4
                    partials[name] = _metric_partial(
                        spec[2], c, s if c else 0.0,
                        mn if c else None, mx if c else None,
                    )
                elif kind == "terms_kw":
                    row = np.asarray(outs[idx][ji])
                    idx += 1
                    gterms = extra
                    counts = {
                        gterms[int(o)]: int(row[o])
                        for o in np.nonzero(row[: len(gterms)])[0]
                    }
                    _sp, _name, _field, size, shard_size, order_t = spec
                    order = dict(order_t)
                    top = _order_buckets(counts, order)[:shard_size]
                    shard_error = (
                        top[-1][1]
                        if len(counts) > shard_size and top
                        else 0
                    )
                    partials[name] = {
                        "t": "terms",
                        "buckets": {
                            _bkey(k): {
                                "key": k, "doc_count": c2, "subs": {}
                            }
                            for k, c2 in top
                        },
                        "sum_docs": sum(counts.values()),
                        "size": size,
                        "order": order,
                        "shard_error": shard_error,
                    }
                else:  # histo
                    row = np.asarray(outs[idx][ji])
                    idx += 1
                    qmin = extra
                    _sp, _name, _field, interval, offset, date = spec
                    buckets = {}
                    for rel in np.nonzero(row)[0]:
                        raw = (qmin + int(rel)) * interval + offset
                        k = int(raw) if date else float(raw)
                        buckets[k] = {
                            "key": k,
                            "doc_count": int(row[rel]),
                            "subs": {},
                        }
                    partials[name] = {
                        "t": "date_histogram" if date else "histogram",
                        "buckets": buckets,
                    }
            mx = float(maxs[ji])
            j.result = {
                "total": int(totals[ji]),
                "max_score": mx if np.isfinite(mx) else None,
                "partials": partials,
                "snapshot": snap,
            }
            j.event.set()

    def _hit(self, snap, score, entry, doc) -> MeshHit:
        sid, si = snap.entries[entry]
        return MeshHit(
            score=score,
            shard=sid,
            segment=si,
            local_doc=doc,
            doc_id=snap.readers[sid].segments[si].doc_ids[doc],
        )

    def note_routed(self) -> None:
        with self._lock:
            self.stats["routed"] += 1

    def note_fallback(self) -> None:
        with self._lock:
            self.stats["fallbacks"] += 1

    def note_degraded(self) -> None:
        with self._lock:
            self.stats["degraded"] += 1

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
        snap = self._snapshot
        out["entries"] = len(snap.entries) if snap and not snap.closed else 0
        out["devices"] = len(self.device_ids)
        return out
