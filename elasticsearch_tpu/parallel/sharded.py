"""Sharded (multi-chip) search execution over a device mesh.

Reference analog: the coordinator scatter/gather pipeline
(`TransportSearchAction` → per-shard `SearchService.executeQueryPhase` →
`SearchPhaseController.reducedQueryPhase`, SURVEY.md §3.3). The TPU-native
redesign collapses the whole round-trip into ONE SPMD program:

  - every shard's tiled postings live stacked on the ``shards`` mesh axis
    (`doc_ids[S, T, 128]` with `PartitionSpec('shards', None, None)`);
  - a query batch is sharded over the ``data`` axis (many concurrent
    searches — the ES coordinator's in-flight search set);
  - inside `shard_map`, each device scores ITS shard for ITS slice of the
    query batch (QueryPhase), takes a local top-k, and the shard-merge
    (`QueryPhaseResultConsumer` / reduce) is a `lax.all_gather` over the
    ICI followed by a k-way `top_k` — no transport layer, no
    serialization, no per-shard RPC correlation.

Tie-break parity: Lucene's coordinator merge orders (score desc,
shard asc, doc asc). `lax.top_k` keeps the lowest index among equal
scores, and the gathered axis is laid out shard-major with per-shard
results already doc-ascending among ties, so the merged ordering matches.

Totals (`hits.total.value`) reduce with a `psum` over ``shards`` — the
analog of summing each shard's `QuerySearchResult.totalHits`.

Shard folding: the stacked axis may carry MORE entries than the mesh's
``shards`` axis has devices — entries are padded to ``axis * fold`` rows
and each device vmaps over its ``fold`` local entries before the ICI
merge, so non-power-of-two layouts and fewer-devices-than-shards both
work (parallel/mesh.py fold_factor).

Two families of step builders live here:

  * ``build_sharded_bm25_step`` / ``build_sharded_knn_step`` — the
    original ShardedIndex demo steps (driver dryrun, tests);
  * ``build_mesh_text_step`` / ``build_mesh_knn_step`` — the SERVING
    steps behind `parallel/mesh_executor.MeshExecutor`: stacked entries
    are (shard, segment) pairs so per-entry scoring reproduces the
    sequential per-segment kernels float-exactly (same tile plans, same
    scatter order, same live-mask semantics), and only the merge moves
    from the host to the ICI.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..index.segment import INVALID_DOC, TILE, Segment
from ..models import bm25
from ..ops.scoring import _score_tiles_inner, bm25_tile_contrib, next_bucket
from .mesh import DATA_AXIS, SHARD_AXIS, fold_factor

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    # older jax (< 0.6): the API lives in jax.experimental and the
    # replication-check kwarg is named check_rep, not check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=bool(check_vma),
        )


class ShardedTopK(NamedTuple):
    scores: jax.Array  # float32[B, k] merged, score desc
    global_docs: jax.Array  # int32[B, k] doc_base[shard] + local doc (-1 pad)
    totals: jax.Array  # int32[B] total matching docs across shards


@dataclass
class _ShardPostings:
    """Host-side per-shard postings handle for one field."""

    segment: Segment
    field: str
    inv_norm: np.ndarray  # float32[n_docs_padded]


class ShardedIndex:
    """Stacks S single-shard segments into mesh-sharded device arrays.

    The ES analog of an index with `number_of_shards: S` whose shards are
    pinned one-per-chip (BASELINE.json north star: "shards pinned to
    distinct chips"). Each shard is an independent Segment (its own term
    dictionary, norms, stats — exactly like an ES shard is a full Lucene
    index); this class pads them to a common dense shape and lays the
    stack out over the ``shards`` mesh axis. With fewer devices than
    shards the stack is padded to ``axis * fold`` rows and each device
    scores its fold of shards (mesh.py fold_factor).
    """

    def __init__(
        self,
        mesh: Mesh,
        segments: Sequence[Segment],
        field: str,
        k1: float = bm25.DEFAULT_K1,
        b: float = bm25.DEFAULT_B,
        vector_field: Optional[str] = None,
    ):
        g = mesh.shape[SHARD_AXIS]
        self.fold = fold_factor(mesh, len(segments))
        if g * self.fold < len(segments):
            raise ValueError(
                f"{len(segments)} shards but mesh '{SHARD_AXIS}' axis is "
                f"{g} (fold {self.fold})"
            )
        self.mesh = mesh
        self.segments = list(segments)
        self.field = field
        self.n_shards = len(segments)
        # stacked rows: shards padded to an equal fold per device
        self.n_stack = g * self.fold
        self.k1 = k1
        self.b = b

        # ---- per-shard BM25 term weights (each shard uses ITS OWN stats,
        # like per-shard IDF without the optional DFS phase) ----
        self._weights: List[Dict[str, float]] = []
        self._inv_norms: List[np.ndarray] = []
        n_tiles_max = 1
        n_docs_max = 1
        for seg in self.segments:
            pf = seg.postings.get(field)
            if pf is None or pf.n_tiles == 0:
                self._weights.append({})
                self._inv_norms.append(np.zeros(max(seg.num_docs, 1), np.float32))
                n_docs_max = max(n_docs_max, max(seg.num_docs, 1))
                continue
            st = pf.stats
            doc_count = st.doc_count or 1
            avgdl = bm25.avg_field_length(st.sum_total_term_freq, doc_count)
            cache = bm25.norm_inverse_cache(avgdl, k1, b)
            self._weights.append(
                {
                    t: float(bm25.idf(doc_count, int(pf.term_df[i])))
                    for i, t in enumerate(pf.terms)
                }
            )
            self._inv_norms.append(cache[pf.norms.astype(np.int64)])
            n_tiles_max = max(n_tiles_max, pf.n_tiles)
            n_docs_max = max(n_docs_max, seg.num_docs)
        self.n_docs_max = n_docs_max
        self.n_tiles_max = n_tiles_max

        # ---- stacked, padded device arrays sharded over 'shards' ----
        S = self.n_stack
        doc_ids = np.full((S, n_tiles_max, TILE), INVALID_DOC, np.int32)
        tfs = np.zeros((S, n_tiles_max, TILE), np.int32)
        inv_norm = np.zeros((S, n_docs_max), np.float32)
        doc_base = np.zeros(S, np.int32)
        base = 0
        for si, seg in enumerate(self.segments):
            pf = seg.postings.get(field)
            if pf is not None and pf.n_tiles:
                doc_ids[si, : pf.n_tiles] = pf.doc_ids
                tfs[si, : pf.n_tiles] = pf.tfs
            inv_norm[si, : len(self._inv_norms[si])] = self._inv_norms[si]
            doc_base[si] = base
            base += seg.num_docs
        self.total_docs = base

        shard3 = NamedSharding(mesh, P(SHARD_AXIS, None, None))
        shard2 = NamedSharding(mesh, P(SHARD_AXIS, None))
        shard1 = NamedSharding(mesh, P(SHARD_AXIS))
        self.doc_ids = jax.device_put(doc_ids, shard3)
        self.tfs = jax.device_put(tfs, shard3)
        self.inv_norm = jax.device_put(inv_norm, shard2)
        self.doc_base = jax.device_put(doc_base, shard1)

        # ---- optional dense-vector shard stack ----
        self.vector_field = vector_field
        self.vectors = None
        self.vec_exists = None
        if vector_field is not None:
            dims = None
            for seg in self.segments:
                vf = seg.vectors.get(vector_field)
                if vf is not None:
                    dims = vf.vectors.shape[1]
                    break
            if dims is not None:
                vecs = np.zeros((S, n_docs_max, dims), np.float32)
                exists = np.zeros((S, n_docs_max), bool)
                for si, seg in enumerate(self.segments):
                    vf = seg.vectors.get(vector_field)
                    if vf is None:
                        continue
                    mat = (
                        vf.unit_vectors
                        if vf.similarity == "cosine" and vf.unit_vectors is not None
                        else vf.vectors
                    )
                    vecs[si, : seg.num_docs] = mat
                    exists[si, : seg.num_docs] = vf.exists
                self.vectors = jax.device_put(vecs, shard3)
                self.vec_exists = jax.device_put(exists, shard2)

    # ---- host-side query compilation (the per-shard Weight creation) ----

    def compile_queries(
        self,
        term_lists: Sequence[Sequence[str]],
        operators: Optional[Sequence[str]] = None,
        bucket: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Queries → per-(shard, query) padded tile plans.

        Returns (tile_idx[S,B,T], tile_w[S,B,T], tile_v[S,B,T], msm[B]).
        Each shard resolves the same terms against its own dictionary and
        stats — the analog of per-shard `Weight` creation in
        `SearchService.executeQueryPhase`. S is the padded stack size
        (folded layouts score all-invalid padding rows to -inf).
        """
        B = len(term_lists)
        plans: List[List[Tuple[List[int], List[float]]]] = []
        t_max = 1
        for si, seg in enumerate(self.segments):
            pf = seg.postings.get(self.field)
            shard_plans: List[Tuple[List[int], List[float]]] = []
            for terms in term_lists:
                idxs: List[int] = []
                ws: List[float] = []
                if pf is not None:
                    for t in terms:
                        tid = pf.term_id(t)
                        if tid < 0:
                            continue
                        start = int(pf.term_tile_start[tid])
                        cnt = int(pf.term_tile_count[tid])
                        w = self._weights[si].get(t, 0.0)
                        idxs.extend(range(start, start + cnt))
                        ws.extend([w] * cnt)
                t_max = max(t_max, len(idxs))
                shard_plans.append((idxs, ws))
            plans.append(shard_plans)
        T = bucket or next_bucket(t_max)
        S = self.n_stack
        tile_idx = np.zeros((S, B, T), np.int32)
        tile_w = np.zeros((S, B, T), np.float32)
        tile_v = np.zeros((S, B, T), bool)
        for si in range(self.n_shards):
            for bi, (idxs, ws) in enumerate(plans[si]):
                t = len(idxs)
                tile_idx[si, bi, :t] = idxs
                tile_w[si, bi, :t] = ws
                tile_v[si, bi, :t] = True
        msm = np.ones(B, np.int32)
        if operators is not None:
            for bi, op in enumerate(operators):
                if op == "and":
                    msm[bi] = len(term_lists[bi])
        return tile_idx, tile_w, tile_v, msm


def _merge_gathered(gs, gd, k: int):
    """ICI merge epilogue shared by every step: gathered per-entry pages
    [G, F, Bd, kk] → (scores[Bd, K], entry[Bd, K], doc[Bd, K]). Slots
    are laid out entry-major (shard/segment asc) with per-entry ranks
    already doc-ascending among ties, and lax.top_k keeps the lowest
    slot among equals — the coordinator's (score desc, shard asc, rank
    asc) merge order, on device."""
    G, F, Bd, kk = gs.shape
    slots = G * F * kk
    gs2 = jnp.transpose(gs, (2, 0, 1, 3)).reshape(Bd, slots)
    gd2 = jnp.transpose(gd, (2, 0, 1, 3)).reshape(Bd, slots)
    K = min(k, slots)
    ms, mi = jax.lax.top_k(gs2, K)
    entry_of_slot = jnp.arange(slots, dtype=jnp.int32) // kk
    me = entry_of_slot[mi]
    md = jnp.take_along_axis(gd2, mi, axis=1)
    return ms, me, md


def build_sharded_bm25_step(index: ShardedIndex, k: int):
    """Jitted SPMD search step: per-shard score+top-k, ICI merge.

    fn(tile_idx[S,B,T], tile_w, tile_v, msm[B]) -> ShardedTopK with the
    query batch B sharded over the ``data`` axis and postings over
    ``shards``; the returned top-k is replicated over ``shards`` and
    sharded over ``data``. S is the padded stack (fold per device).
    """
    mesh = index.mesh
    n_docs = index.n_docs_max

    def body(doc_ids, tfs, inv_norm, doc_base, tile_idx, tile_w, tile_v, msm):
        # block shapes: doc_ids[F,T_all,128], tile_idx[F,Bd,T], msm[Bd]
        def entry(doc_ids_e, tfs_e, inv_e, base_e, ti_e, tw_e, tv_e):
            rows_doc = doc_ids_e[ti_e]  # [Bd, T, 128]
            rows_tf = tfs_e[ti_e]

            def one(rd, rt, w, v, m):
                scores, cnt = _score_tiles_inner(rd, rt, w, v, inv_e, n_docs)
                mask = cnt >= jnp.maximum(m, 1)
                masked = jnp.where(mask, scores, -jnp.inf)
                s, d = jax.lax.top_k(masked, min(k, n_docs))
                return s, d, mask.sum().astype(jnp.int32)

            s, d, t = jax.vmap(one)(rows_doc, rows_tf, tw_e, tv_e, msm)
            gdoc = jnp.where(s > -jnp.inf, d + base_e, -1)
            return s, gdoc, t

        s, gdoc, t = jax.vmap(entry)(
            doc_ids, tfs, inv_norm, doc_base, tile_idx, tile_w, tile_v
        )  # [F,Bd,k'] [F,Bd,k'] [F,Bd]
        # ---- shard merge over ICI (the coordinator reduce) ----
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, k']
        gd = jax.lax.all_gather(gdoc, SHARD_AXIS)
        ms, _, md = _merge_gathered(gs, gd, k)
        totals = jax.lax.psum(t.sum(axis=0), SHARD_AXIS)
        return ms, md, totals

    p_post3 = P(SHARD_AXIS, None, None)
    p_post2 = P(SHARD_AXIS, None)
    p_q = P(SHARD_AXIS, DATA_AXIS, None)
    p_out = P(DATA_AXIS, None)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(p_post3, p_post3, p_post2, P(SHARD_AXIS), p_q, p_q, p_q, P(DATA_AXIS)),
        out_specs=(p_out, p_out, P(DATA_AXIS)),
        check_vma=False,
    )

    @jax.jit
    def step(tile_idx, tile_w, tile_v, msm):
        s, d, t = fn(
            index.doc_ids,
            index.tfs,
            index.inv_norm,
            index.doc_base,
            tile_idx,
            tile_w,
            tile_v,
            msm,
        )
        return ShardedTopK(s, d, t)

    return step


def build_sharded_knn_step(index: ShardedIndex, k: int, similarity: str = "cosine"):
    """SPMD brute-force kNN: per-shard MXU matmul + top-k, ICI merge.

    fn(queries[B, d]) -> ShardedTopK. Queries sharded over ``data`` and
    replicated over ``shards``; one (B/d × d)·(d × N) matmul per chip —
    the reference's `KnnFloatVectorQuery` DFS round (SURVEY.md §3.4)
    without the graph walk.
    """
    if index.vectors is None:
        raise ValueError(f"index has no vector field [{index.vector_field}]")
    mesh = index.mesh

    def body(vectors, exists, doc_base, queries):
        q = queries
        if similarity == "cosine":
            qn = jnp.linalg.norm(q, axis=1, keepdims=True)
            q = q / jnp.where(qn == 0, 1.0, qn)

        def entry(vectors_e, exists_e, base_e):
            dots = q @ vectors_e.T  # [Bd, N] — MXU
            if similarity in ("cosine", "dot_product"):
                scores = (1.0 + dots) / 2.0
            elif similarity == "l2_norm":
                q2 = jnp.sum(q * q, axis=1, keepdims=True)
                v2 = jnp.sum(vectors_e * vectors_e, axis=1)[None, :]
                scores = 1.0 / (1.0 + jnp.maximum(q2 + v2 - 2.0 * dots, 0.0))
            elif similarity == "max_inner_product":
                scores = jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
            else:
                raise ValueError(f"unknown similarity [{similarity}]")
            scores = jnp.where(
                exists_e[None, :], scores.astype(jnp.float32), -jnp.inf
            )
            kk = min(k, scores.shape[1])
            s, d = jax.lax.top_k(scores, kk)
            gdoc = jnp.where(s > -jnp.inf, d + base_e, -1)
            t = jnp.sum(exists_e).astype(jnp.int32) * jnp.ones(
                s.shape[0], jnp.int32
            )
            return s, gdoc, t

        s, gdoc, t = jax.vmap(entry)(vectors, exists, doc_base)
        gs = jax.lax.all_gather(s, SHARD_AXIS)
        gd = jax.lax.all_gather(gdoc, SHARD_AXIS)
        ms, _, md = _merge_gathered(gs, gd, k)
        totals = jax.lax.psum(t.sum(axis=0), SHARD_AXIS)
        return ms, md, totals

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None),
            P(SHARD_AXIS),
            P(DATA_AXIS, None),
        ),
        out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None), P(DATA_AXIS)),
        check_vma=False,
    )

    @jax.jit
    def step(queries):
        s, d, t = fn(index.vectors, index.vec_exists, index.doc_base, queries)
        return ShardedTopK(s, d, t)

    return step


# ---------------------------------------------------------------------------
# Serving SPMD steps — the production mesh path (MeshExecutor).
#
# The stacked axis carries (shard, segment) ENTRIES, not whole shards:
# the sequential serving path scores per segment (ChunkedScorer /
# FusedScorer accumulate one segment's doc space), so keeping the
# per-entry granularity makes the mesh program reproduce the sequential
# kernels value-for-value — same tile plans in the same scatter order,
# same `w - w/(1 + tf·inv)` BM25 formula, same live/count masking — and
# only the cross-segment + cross-shard merge moves from S host round
# trips to one all_gather + top_k on the ICI. Entry order is (shard asc,
# segment asc), so the device merge's (score desc, slot asc) ordering is
# exactly the coordinator's (score desc, shard asc, segment asc, doc
# asc) tie-break.
# ---------------------------------------------------------------------------


def build_mesh_text_step(
    mesh: Mesh,
    doc_ids_f: Sequence[jax.Array],  # per field: [E, Tmax_f, TILE] stacked
    tfs_f: Sequence[jax.Array],
    inv_norm_f: Sequence[jax.Array],  # per field: [E, Nmax]
    live: jax.Array,  # bool[E, Nmax] (live docs ∧ in-range padding mask)
    k: int,
    *,
    with_cnt: bool,
    count_signed: bool,
    combine: str = "sum",
    tie: float = 0.0,
):
    """One SPMD text-scoring step over stacked (shard, segment) entries.

    fn(ti_f..., tw_f..., tv_f..., msm[B]) →
        (scores[B, K], entry[B, K], doc[B, K], totals[B])
    with per-field plans ti/tw/tv of shape [E, B, T_f] sharded
    (shards, data, None) and the outputs sharded over ``data`` only.

    * ``count_signed`` (the ServePlan families): |w| scores, w > 0
      counts toward msm — the MultiFusedScorer weight-sign convention.
    * ``with_cnt`` False (pure-disjunction match groups): the match mask
      is ``acc > 0`` exactly like ops/scoring._finalize with cnt=None.
    * ``combine``: "sum" (bool / most_fields) or "max_tie"
      (best_fields: max + tie·(sum − max)).
    """
    F_fields = len(doc_ids_f)
    n_docs = int(inv_norm_f[0].shape[1])
    tie_f = jnp.float32(tie)

    def body(*args):
        it = iter(args)
        d_f = [next(it) for _ in range(F_fields)]  # [F, Tmax, TILE] blocks
        t_f = [next(it) for _ in range(F_fields)]
        i_f = [next(it) for _ in range(F_fields)]
        live_b = next(it)  # [F, Nmax]
        ti_f = [next(it) for _ in range(F_fields)]  # [F, Bd, T]
        tw_f = [next(it) for _ in range(F_fields)]
        tv_f = [next(it) for _ in range(F_fields)]
        msm = next(it)  # [Bd]

        def entry(per_field, live_e):
            Bd = per_field[0][3].shape[0]
            cnt = (
                jnp.zeros((Bd, n_docs + 1), jnp.int32) if with_cnt else None
            )
            accs = []
            for dids, tfs_, inv, ti, tw, tv in per_field:
                nt = dids.shape[0]
                rows_d = dids[jnp.clip(ti, 0, nt - 1)]  # [Bd, T, 128]
                rows_t = tfs_[jnp.clip(ti, 0, nt - 1)]
                valid = (rows_d >= 0) & tv[:, :, None]
                w = (jnp.abs(tw) if count_signed else tw)[:, :, None]
                tgt, s = bm25_tile_contrib(
                    rows_d, rows_t, w, valid, inv, n_docs
                )
                acc = jnp.zeros((Bd, n_docs + 1), jnp.float32)
                acc = jax.vmap(
                    lambda a, d, v: a.at[d.ravel()].add(v.ravel())
                )(acc, tgt, s)
                accs.append(acc[:, :n_docs])
                if with_cnt:
                    counted = (
                        valid & (tw > 0)[:, :, None] if count_signed else valid
                    )
                    cnt = jax.vmap(
                        lambda c, d, v: c.at[d.ravel()].add(
                            v.ravel().astype(jnp.int32)
                        )
                    )(cnt, tgt, counted)
            if len(accs) == 1:
                combined = accs[0]
            elif combine == "sum":
                combined = accs[0]
                for a in accs[1:]:
                    combined = combined + a
            else:  # max_tie (DisjunctionMaxQuery)
                stack = jnp.stack(accs)
                best = stack.max(axis=0)
                combined = best + tie_f * (stack.sum(axis=0) - best)
            if with_cnt:
                mask = cnt[:, :n_docs] >= jnp.maximum(msm, 1)[:, None]
            else:
                mask = combined > 0
            mask = mask & live_e[None, :]
            masked = jnp.where(mask, combined, -jnp.inf)
            kk = min(k, n_docs)
            s, d = jax.lax.top_k(masked, kk)
            return s, d, mask.sum(axis=1, dtype=jnp.int32)

        per_entry = tuple(
            tuple(x[fi] for x in (d_f, t_f, i_f, ti_f, tw_f, tv_f))
            for fi in range(F_fields)
        )
        s, d, t = jax.vmap(
            lambda pf, le: entry(pf, le)
        )(per_entry, live_b)  # [F, Bd, kk] ×2, [F, Bd]
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, kk]
        gd = jax.lax.all_gather(d, SHARD_AXIS)
        ms, me, md = _merge_gathered(gs, gd, k)
        totals = jax.lax.psum(t.sum(axis=0), SHARD_AXIS)
        return ms, me, md, totals

    p3 = P(SHARD_AXIS, None, None)
    p2 = P(SHARD_AXIS, None)
    p_plan = P(SHARD_AXIS, DATA_AXIS, None)
    p_out = P(DATA_AXIS, None)
    in_specs = (
        tuple(p3 for _ in range(2 * F_fields))
        + tuple(p2 for _ in range(F_fields))
        + (p2,)
        + tuple(p_plan for _ in range(3 * F_fields))
        + (P(DATA_AXIS),)
    )
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(p_out, p_out, p_out, P(DATA_AXIS)),
        check_vma=False,
    )

    @jax.jit
    def step(ti_f, tw_f, tv_f, msm):
        args = (
            tuple(doc_ids_f) + tuple(tfs_f) + tuple(inv_norm_f) + (live,)
            + tuple(ti_f) + tuple(tw_f) + tuple(tv_f) + (msm,)
        )
        return fn(*args)

    return step


def build_mesh_sparse_step(
    mesh: Mesh,
    doc_ids: jax.Array,  # [E, Tmax, TILE] stacked impact-ordered tiles
    values: jax.Array,  # [E, Tmax, TILE] stored dtype (int8 or f32)
    live: jax.Array,  # bool[E, Nmax] (live docs ∧ in-range padding mask)
    k: int,
):
    """One SPMD learned-sparse scoring step over stacked (shard,
    segment) entries.

    fn(ti, tw, tv) → (scores[B, K], entry[B, K], doc[B, K], totals[B])
    with the per-(entry, job) tile plan ti/tw/tv of shape [E, B, T]
    sharded (shards, data, None) and outputs over ``data`` only.

    The contribution formula is ops/impact.impact_tile_contrib — the
    SAME jnp expression the sequential ImpactScorer launches — and the
    tile lists arrive term-ordered with every tile present (no pruning
    on the mesh path: theta would need a cross-device round-trip, and
    the full pass keeps the step float-identical to the per-shard
    serving path with the exact totals for free). `tw` carries the
    query weight with each ENTRY's per-term dequant scale pre-folded,
    so the one step serves int8 and fp32 columns alike."""
    from ..ops.impact import impact_tile_contrib

    n_docs = int(live.shape[1])

    def body(dids, vals, live_b, ti, tw, tv):
        def entry(d_e, v_e, live_e, ti_e, tw_e, tv_e):
            Bd = ti_e.shape[0]
            nt = d_e.shape[0]
            rows_d = d_e[jnp.clip(ti_e, 0, nt - 1)]  # [Bd, T, 128]
            rows_v = v_e[jnp.clip(ti_e, 0, nt - 1)]
            valid = (rows_d >= 0) & tv_e[:, :, None]
            tgt, s = impact_tile_contrib(
                rows_d, rows_v, tw_e[:, :, None], valid, n_docs
            )
            acc = jnp.zeros((Bd, n_docs + 1), jnp.float32)
            acc = jax.vmap(
                lambda a, d, v: a.at[d.ravel()].add(v.ravel())
            )(acc, tgt, s)
            cnt = jnp.zeros((Bd, n_docs + 1), jnp.int32)
            cnt = jax.vmap(
                lambda c, d, v: c.at[d.ravel()].add(
                    v.ravel().astype(jnp.int32)
                )
            )(cnt, tgt, valid)
            # every query term is optional: the sparse match mask is
            # cnt > 0, exactly ops/scoring._finalize at msm=1
            mask = (cnt[:, :n_docs] >= 1) & live_e[None, :]
            masked = jnp.where(mask, acc[:, :n_docs], -jnp.inf)
            kk = min(k, n_docs)
            s2, d2 = jax.lax.top_k(masked, kk)
            return s2, d2, mask.sum(axis=1, dtype=jnp.int32)

        s, d, t = jax.vmap(entry)(
            dids, vals, live_b, ti, tw, tv
        )  # [F, Bd, kk] ×2, [F, Bd]
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, kk]
        gd = jax.lax.all_gather(d, SHARD_AXIS)
        ms, me, md = _merge_gathered(gs, gd, k)
        totals = jax.lax.psum(t.sum(axis=0), SHARD_AXIS)
        return ms, me, md, totals

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None),
            P(SHARD_AXIS, DATA_AXIS, None),
            P(SHARD_AXIS, DATA_AXIS, None),
            P(SHARD_AXIS, DATA_AXIS, None),
        ),
        out_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS),
        ),
        check_vma=False,
    )

    @jax.jit
    def step(ti, tw, tv):
        return fn(doc_ids, values, live, ti, tw, tv)

    return step


def build_mesh_rerank_step(
    mesh: Mesh,
    doc_ids: jax.Array,  # [E, Tmax, TILE] stacked postings tiles
    tfs: jax.Array,
    inv_norm: jax.Array,  # [E, Nmax]
    live: jax.Array,  # bool[E, Nmax]
    rr_starts: jax.Array,  # i32 [E, Nmax] local doc → flat token row
    rr_counts: jax.Array,  # i32 [E, Nmax]
    rr_toks: jax.Array,  # [E, Fmax, d] f32 (or int8 with scales)
    rr_scales: Optional[jax.Array],  # f32 [E, Fmax] or None
    kb: int,  # local candidate page (compile bucket >= k_req)
    k_req: int,  # the request page (from + size): per-entry page cut
    window: int,  # rescore window, already clamped to k_req
    tmax: int,  # max tokens per doc (gather width)
    *,
    with_cnt: bool,
):
    """One SPMD first-stage + RERANK step: per-entry BM25 scoring and
    local top-k exactly like build_mesh_text_step (single field), then
    the maxsim rescore runs LOCALLY per entry — each entry's
    rank_vectors tokens are sharded with it — so the ICI all_gather
    carries already-reranked candidates. With one live segment per
    shard (the routing precondition), each entry's local stream equals
    the per-shard path's post-rescore page: positions < window are
    re-sorted by blended score, positions [window, k_req) keep first
    stage, positions >= k_req are dropped (the shard page cut).

    fn(ti, tw, tv, msm[B], qtoks[B, Qt, d], qvalid[B, Qt],
       weights[2]) →
        (scores[B, slots], entry[B, slots], doc[B, slots], totals[B])
    The merged stream comes back FULLY ordered (score desc, slot asc =
    (entry, post-rescore rank) asc — the coordinator's (-score, shard,
    rank) tie-break) rather than cut at a global k, mirroring
    build_mesh_knn_step.
    """
    from ..ops.rerank import blend_and_sort, maxsim_candidates

    n_docs = int(inv_norm.shape[1])
    kk = min(kb, n_docs)
    wc = min(window, k_req, kk)
    has_scales = rr_scales is not None

    def body(d_b, t_b, i_b, live_b, st_b, ct_b, tk_b, sc_b, ti, tw, tv,
             msm, qtoks, qvalid, weights):
        def entry(args):
            dids, tfs_, inv, live_e, st_e, ct_e, tk_e, sc_e, ti_e, tw_e, tv_e = args
            Bd = ti_e.shape[0]
            nt = dids.shape[0]
            rows_d = dids[jnp.clip(ti_e, 0, nt - 1)]  # [Bd, T, 128]
            rows_t = tfs_[jnp.clip(ti_e, 0, nt - 1)]
            valid = (rows_d >= 0) & tv_e[:, :, None]
            tgt, s = bm25_tile_contrib(
                rows_d, rows_t, tw_e[:, :, None], valid, inv, n_docs
            )
            acc = jnp.zeros((Bd, n_docs + 1), jnp.float32)
            acc = jax.vmap(
                lambda a, d, v: a.at[d.ravel()].add(v.ravel())
            )(acc, tgt, s)
            acc = acc[:, :n_docs]
            if with_cnt:
                cnt = jnp.zeros((Bd, n_docs + 1), jnp.int32)
                cnt = jax.vmap(
                    lambda c, d, v: c.at[d.ravel()].add(
                        v.ravel().astype(jnp.int32)
                    )
                )(cnt, tgt, valid)
                mask = cnt[:, :n_docs] >= jnp.maximum(msm, 1)[:, None]
            else:
                mask = acc > 0
            mask = mask & live_e[None, :]
            masked = jnp.where(mask, acc, -jnp.inf)
            s_e, d_e = jax.lax.top_k(masked, kk)
            # ---- local rescore, before the gather: page cut at k_req,
            # maxsim over this entry's token block, window re-sort ----
            pos = jnp.arange(kk, dtype=jnp.int32)
            keep = jnp.isfinite(s_e) & (pos[None, :] < k_req)
            msim = maxsim_candidates(
                qtoks, qvalid, st_e, ct_e, tk_e,
                sc_e if has_scales else None,
                jnp.where(keep, d_e, 0), tmax,
            )
            first = jnp.where(keep, s_e, -jnp.inf)
            scores, perm = blend_and_sort(msim, first, keep, weights, wc)
            d_sorted = jnp.take_along_axis(d_e, perm, axis=1)
            return scores, d_sorted, mask.sum(axis=1, dtype=jnp.int32)

        per_entry = (
            d_b, t_b, i_b, live_b, st_b, ct_b, tk_b, sc_b, ti, tw, tv,
        )
        s, d, t = jax.vmap(entry)(per_entry)  # [F, Bd, kk] ×2, [F, Bd]
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, kk]
        gd = jax.lax.all_gather(d, SHARD_AXIS)
        G, F, Bd, _ = gs.shape
        slots = G * F * kk
        gs2 = jnp.transpose(gs, (2, 0, 1, 3)).reshape(Bd, slots)
        gd2 = jnp.transpose(gd, (2, 0, 1, 3)).reshape(Bd, slots)
        entry_of_slot = jnp.arange(slots, dtype=jnp.int32) // kk
        ms, mi = jax.lax.top_k(gs2, slots)
        me = entry_of_slot[mi]
        md = jnp.take_along_axis(gd2, mi, axis=1)
        totals = jax.lax.psum(t.sum(axis=0), SHARD_AXIS)
        return ms, me, md, totals

    p3 = P(SHARD_AXIS, None, None)
    p2 = P(SHARD_AXIS, None)
    p_plan = P(SHARD_AXIS, DATA_AXIS, None)
    p_out = P(DATA_AXIS, None)
    in_specs = (
        p3, p3, p2, p2,  # text view + live
        p2, p2, p3,  # rerank starts/counts/toks
        p2,  # scales (per-entry dummy when the model is float)
        p_plan, p_plan, p_plan,  # tile plans
        P(DATA_AXIS),  # msm
        P(DATA_AXIS, None, None),  # qtoks
        P(DATA_AXIS, None),  # qvalid
        P(),  # weights (replicated)
    )
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(p_out, p_out, p_out, P(DATA_AXIS)),
        check_vma=False,
    )

    dummy_scales = (
        jnp.zeros((int(doc_ids.shape[0]), 1), jnp.float32)
        if rr_scales is None
        else rr_scales
    )

    @jax.jit
    def step(ti, tw, tv, msm, qtoks, qvalid, weights):
        return fn(
            doc_ids, tfs, inv_norm, live, rr_starts, rr_counts, rr_toks,
            dummy_scales, ti, tw, tv, msm, qtoks, qvalid, weights,
        )

    return step


def build_mesh_knn_step(
    mesh: Mesh,
    vectors: jax.Array,  # [E, Nmax, dims] stacked (original dtype)
    cand: jax.Array,  # bool[E, Nmax] exists ∧ live ∧ in-range
    similarity: str,
    kc: int,  # per-entry candidate page (≥ every job's num_candidates)
):
    """One SPMD brute-force kNN step over stacked (shard, segment)
    entries with the sequential path's per-(job, entry) num_candidates
    rank cut applied on device.

    fn(queries[B, d], nc[E, B]) →
        (scores[B, slots], entry[B, slots], doc[B, slots], counts[B, E])
    The merged stream comes back FULLY ordered (score desc, slot asc —
    slots = E_pad · kk) rather than cut at a global k, because the
    sequential coordinator's knn semantics cut at k PER SHARD before
    the global page: the collector walks the ordered stream applying
    per-shard rank caps, which a global top-k on device could starve
    (one dominant shard would evict other shards' in-page ranks).
    counts = surviving candidates PER ENTRY, for the per-shard totals
    (Σ_shards min(Σ_{entries∈shard} count, k)) of
    ops/scoring.knn_merge_segment_topk.
    """
    n_docs = int(vectors.shape[1])
    kk = min(kc, n_docs)

    def body(vectors_b, cand_b, queries, nc_b):
        q = queries
        if similarity == "cosine":
            qn = jnp.linalg.norm(q, axis=1, keepdims=True)
            q = q / jnp.where(qn == 0, 1.0, qn)

        def entry(vectors_e, cand_e):
            dots = q @ vectors_e.T  # [Bd, N] — MXU
            if similarity in ("cosine", "dot_product"):
                scores = (1.0 + dots) / 2.0
            elif similarity == "l2_norm":
                q2 = jnp.sum(q * q, axis=1, keepdims=True)
                v2 = jnp.sum(vectors_e * vectors_e, axis=1)[None, :]
                scores = 1.0 / (1.0 + jnp.maximum(q2 + v2 - 2.0 * dots, 0.0))
            elif similarity == "max_inner_product":
                scores = jnp.where(dots < 0, 1.0 / (1.0 - dots), dots + 1.0)
            else:
                raise ValueError(f"unknown similarity [{similarity}]")
            scores = jnp.where(
                cand_e[None, :], scores.astype(jnp.float32), -jnp.inf
            )
            return jax.lax.top_k(scores, kk)

        s, d = jax.vmap(entry)(vectors_b, cand_b)  # [F, Bd, kk] ×2
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, kk]
        gd = jax.lax.all_gather(d, SHARD_AXIS)
        gn = jax.lax.all_gather(nc_b, SHARD_AXIS)  # [G, F, Bd]
        G, F, Bd, _ = gs.shape
        slots = G * F * kk
        gs2 = jnp.transpose(gs, (2, 0, 1, 3)).reshape(Bd, slots)
        gd2 = jnp.transpose(gd, (2, 0, 1, 3)).reshape(Bd, slots)
        nc2 = jnp.transpose(gn, (2, 0, 1)).reshape(Bd, G * F)
        entry_of_slot = jnp.arange(slots, dtype=jnp.int32) // kk
        rank_of_slot = jnp.arange(slots, dtype=jnp.int32) % kk
        nc_slot = jnp.take(nc2, entry_of_slot, axis=1)  # [Bd, slots]
        valid = jnp.isfinite(gs2) & (rank_of_slot[None, :] < nc_slot)
        masked = jnp.where(valid, gs2, -jnp.inf)
        ms, mi = jax.lax.top_k(masked, slots)
        me = entry_of_slot[mi]
        md = jnp.take_along_axis(gd2, mi, axis=1)
        counts = valid.reshape(Bd, G * F, kk).sum(axis=2, dtype=jnp.int32)
        return ms, me, md, counts

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None),
            P(DATA_AXIS, None),
            P(SHARD_AXIS, DATA_AXIS),
        ),
        out_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
        ),
        check_vma=False,
    )

    @jax.jit
    def step(queries, nc):
        return fn(vectors, cand, queries, nc)

    return step


def build_mesh_ann_step(
    mesh: Mesh,
    centroids: jax.Array,  # f32 [E, nlist_max, d] (zero-padded entries)
    cvalid: jax.Array,  # bool [E, nlist_max] real clusters
    starts: jax.Array,  # i32 [E, nlist_max]
    counts: jax.Array,  # i32 [E, nlist_max]
    perm: jax.Array,  # i32 [E, Fmax] flat cluster-major slot → doc
    vecs: jax.Array,  # [E, Fmax, d] permuted block (f32/f16, or int8)
    scales: Optional[jax.Array],  # f32 [E, Fmax] int8 twin, or None
    v2: Optional[jax.Array],  # f32 [E, Fmax] (l2 only), or None
    cand: jax.Array,  # bool [E, Fmax] exists ∧ live in FLAT slot order
    similarity: str,
    nprobe: int,
    kc: int,
    cmax: int,
):
    """One SPMD IVF-probed kNN step: the centroid scan runs replicated
    per entry (each device scans only its own entries' centroids — tiny
    matmuls), cluster gathers stay device-local (clusters are sharded
    with their entries), and the merge is the SAME all_gather + per-
    (job, entry) num_candidates rank cut as build_mesh_knn_step, so the
    collector (MeshExecutor.collect_knn) is shared verbatim.

    fn(queries[B, d], nc[E, B]) →
        (scores[B, slots], entry[B, slots], doc[B, slots], counts[B, E])
    """
    from ..ops.ivf import QCHUNK, _similarity_transform

    kk = min(kc, nprobe * cmax)
    off = jnp.arange(cmax, dtype=jnp.int32)
    has_scales = scales is not None
    has_v2 = v2 is not None

    def body(cent_b, cv_b, st_b, ct_b, pm_b, vx_b, cd_b, queries, nc_b,
             *extra):
        ei = iter(extra)
        sc_b = next(ei) if has_scales else None
        v2_b = next(ei) if has_v2 else None
        q = queries
        if similarity == "cosine":
            qn = jnp.linalg.norm(q, axis=1, keepdims=True)
            q = q / jnp.where(qn == 0, 1.0, qn)

        def entry(args):
            cent_e, cv_e, st_e, ct_e, pm_e, vx_e, cd_e = args[:7]
            rest = args[7:]
            sc_e = rest[0] if has_scales else None
            v2_e = rest[-1] if has_v2 else None
            cdots = q @ cent_e.T  # [Bd, nlist_max]
            if similarity == "l2_norm":
                c2 = jnp.sum(cent_e * cent_e, axis=1)[None, :]
                csel = -(c2 - 2.0 * cdots)
            else:
                csel = cdots
            csel = jnp.where(cv_e[None, :], csel, -jnp.inf)
            p = min(nprobe, int(cent_e.shape[0]))
            _, cls = jax.lax.top_k(csel, p)  # [Bd, p]
            P_ = p * cmax

            def chunk(args):
                qc, clsc = args  # [C, d], [C, p]
                slot = (
                    jnp.take(st_e, clsc)[:, :, None] + off[None, None, :]
                ).reshape(qc.shape[0], P_)
                ok = (
                    off[None, None, :] < jnp.take(ct_e, clsc)[:, :, None]
                ).reshape(qc.shape[0], P_)
                docs = jnp.take(pm_e, slot)
                vv = jnp.take(vx_e, slot, axis=0).astype(jnp.float32)
                dots = jnp.einsum("cd,cpd->cp", qc, vv)
                if sc_e is not None:
                    dots = dots * jnp.take(sc_e, slot)
                if similarity == "l2_norm":
                    s = _similarity_transform(
                        dots, similarity, q=qc, v2=jnp.take(v2_e, slot)
                    )
                else:
                    s = _similarity_transform(dots, similarity)
                mask = ok & jnp.take(cd_e, slot)
                masked = jnp.where(mask, s.astype(jnp.float32), -jnp.inf)
                sk, ik = jax.lax.top_k(masked, min(kk, P_))
                dk = jnp.take_along_axis(docs, ik, axis=1)
                return sk, jnp.where(jnp.isfinite(sk), dk, 0)

            B = q.shape[0]
            C = min(QCHUNK, B)
            if B % C == 0 and B > C:
                sk, dk = jax.lax.map(
                    chunk,
                    (q.reshape(B // C, C, -1), cls.reshape(B // C, C, -1)),
                )
                sk = sk.reshape(B, -1)
                dk = dk.reshape(B, -1)
            else:
                sk, dk = chunk((q, cls))
            if sk.shape[1] < kk:  # P_ < kk: pad to the shared width
                padw = kk - sk.shape[1]
                sk = jnp.pad(sk, ((0, 0), (0, padw)),
                             constant_values=-jnp.inf)
                dk = jnp.pad(dk, ((0, 0), (0, padw)))
            return sk, dk

        ins = [cent_b, cv_b, st_b, ct_b, pm_b, vx_b, cd_b]
        if has_scales:
            ins.append(sc_b)
        if has_v2:
            ins.append(v2_b)
        s, d = jax.vmap(entry)(tuple(ins))  # [F, Bd, kk] ×2
        gs = jax.lax.all_gather(s, SHARD_AXIS)  # [G, F, Bd, kk]
        gd = jax.lax.all_gather(d, SHARD_AXIS)
        gn = jax.lax.all_gather(nc_b, SHARD_AXIS)  # [G, F, Bd]
        G, F, Bd, _ = gs.shape
        slots = G * F * kk
        gs2 = jnp.transpose(gs, (2, 0, 1, 3)).reshape(Bd, slots)
        gd2 = jnp.transpose(gd, (2, 0, 1, 3)).reshape(Bd, slots)
        nc2 = jnp.transpose(gn, (2, 0, 1)).reshape(Bd, G * F)
        entry_of_slot = jnp.arange(slots, dtype=jnp.int32) // kk
        rank_of_slot = jnp.arange(slots, dtype=jnp.int32) % kk
        nc_slot = jnp.take(nc2, entry_of_slot, axis=1)
        valid = jnp.isfinite(gs2) & (rank_of_slot[None, :] < nc_slot)
        masked = jnp.where(valid, gs2, -jnp.inf)
        ms, mi = jax.lax.top_k(masked, slots)
        me = entry_of_slot[mi]
        md = jnp.take_along_axis(gd2, mi, axis=1)
        cnt = valid.reshape(Bd, G * F, kk).sum(axis=2, dtype=jnp.int32)
        return ms, me, md, cnt

    sh2 = P(SHARD_AXIS, None)
    sh3 = P(SHARD_AXIS, None, None)
    in_specs = [sh3, sh2, sh2, sh2, sh2, sh3, sh2,
                P(DATA_AXIS, None), P(SHARD_AXIS, DATA_AXIS)]
    extras = []
    if has_scales:
        extras.append(scales)
        in_specs.append(sh2)
    if has_v2:
        extras.append(v2)
        in_specs.append(sh2)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
        ),
        check_vma=False,
    )

    @jax.jit
    def step(queries, nc):
        return fn(
            centroids, cvalid, starts, counts, perm, vecs, cand,
            queries, nc, *extras,
        )

    return step


def build_mesh_agg_step(
    mesh: Mesh,
    live: jax.Array,  # bool[E, Nmax] (live docs ∧ in-range padding mask)
    node_descs: Sequence[tuple],
    text: Optional[tuple],  # None | (doc_ids[E,T,128], tfs, inv[E,Nmax])
    with_cnt: bool,
):
    """One SPMD aggregation step over stacked (shard, segment) entries:
    per-entry bucket accumulators (segment-sum scatters over the stacked
    doc-value / ordinal columns) reduce across the ``shards`` axis with
    ``psum`` (counts, sums) / ``pmin`` / ``pmax`` — the coordinator's
    agg reduce collapsed onto the ICI, one launch for the whole index.

    ``node_descs`` (arrays stacked [E, …] and device-sharded on
    ``shards``): ("metric", values, exists) → per-row (count, sum, min,
    max); ("counts_doc", ids, exists, nbpad) → per-row int32[nbpad]
    bucket counts (histogram family — ids are host-precomputed exact
    relative bucket ids); ("counts_entry", gords, edocs, evalid, nbpad)
    → the same over the multi-value ordinal CSR mapped to a GLOBAL
    ordinal table (keyword terms — the table union happens host-side at
    snapshot build; the per-entry count vectors are what psum merges
    across the shards axis).

    ``text`` carries one match-plan field's stacked postings (the query
    mask is the same per-entry BM25 accumulation the serving text step
    runs — float-exact masks); None serves match_all (mask = live).

    fn(ti[E,B,T], tw, tv, msm[B]) →
        (totals[B], max_scores[B], per-node outputs…), everything
    replicated over ``shards`` and sharded over ``data`` only.
    """
    has_text = text is not None
    n_docs = int(live.shape[1])

    def body(*args):
        it = iter(args)
        if has_text:
            d_b = next(it)
            t_b = next(it)
            i_b = next(it)
        live_b = next(it)
        node_b = []
        for desc in node_descs:
            kind = desc[0]
            if kind == "metric":
                node_b.append((kind, next(it), next(it), next(it)))
            elif kind == "counts_doc":
                node_b.append((kind, next(it), next(it), desc[3]))
            else:  # counts_entry
                node_b.append((kind, next(it), next(it), next(it), desc[4]))
        ti_b = next(it)
        tw_b = next(it)
        tv_b = next(it)
        msm = next(it)
        Bd = msm.shape[0]

        def scatter_rows(ids_e, sel, nbpad):
            # [Bd, L] selection → [Bd, nbpad] counts; unselected slots
            # land in a trash bucket that psum never sees
            def one(sel_row):
                safe = jnp.where(sel_row, ids_e, nbpad)
                return (
                    jnp.zeros(nbpad + 1, jnp.int32).at[safe].add(1)[:nbpad]
                )

            return jax.vmap(one)(sel)

        def entry(e_args):
            it2 = iter(e_args)
            if has_text:
                dids = next(it2)
                tfs_ = next(it2)
                inv = next(it2)
            live_e = next(it2)
            nodes_e = []
            for desc in node_b:
                n_arr = len(desc) - (1 if desc[0] == "metric" else 2)
                arrs = tuple(next(it2) for _ in range(n_arr))
                nodes_e.append((desc[0], arrs, desc[-1]))
            ti_e = next(it2)
            tw_e = next(it2)
            tv_e = next(it2)
            if has_text:
                nt = dids.shape[0]
                rows_d = dids[jnp.clip(ti_e, 0, nt - 1)]
                rows_t = tfs_[jnp.clip(ti_e, 0, nt - 1)]
                valid = (rows_d >= 0) & tv_e[:, :, None]
                tgt, s = bm25_tile_contrib(
                    rows_d, rows_t, tw_e[:, :, None], valid, inv, n_docs
                )
                acc = jnp.zeros((Bd, n_docs + 1), jnp.float32)
                acc = jax.vmap(
                    lambda a, d2, v2: a.at[d2.ravel()].add(v2.ravel())
                )(acc, tgt, s)
                scores = acc[:, :n_docs]
                if with_cnt:
                    cnt = jnp.zeros((Bd, n_docs + 1), jnp.int32)
                    cnt = jax.vmap(
                        lambda c, d2, v2: c.at[d2.ravel()].add(
                            v2.ravel().astype(jnp.int32)
                        )
                    )(cnt, tgt, valid)
                    mask = cnt[:, :n_docs] >= jnp.maximum(msm, 1)[:, None]
                else:
                    mask = scores > 0
            else:
                mask = jnp.ones((Bd, n_docs), bool)
                scores = jnp.ones((Bd, n_docs), jnp.float32)
            mask = mask & live_e[None, :]
            total_e = mask.sum(axis=1, dtype=jnp.int32)
            max_e = jnp.where(mask, scores, -jnp.inf).max(axis=1)
            outs = []
            for kind, arrs, nbpad in nodes_e:
                if kind == "metric":
                    vals, ivals, exists = arrs
                    sel = mask & exists[None, :]
                    v = vals.astype(jnp.float32)
                    outs.append(
                        (
                            sel.sum(axis=1, dtype=jnp.int32),
                            jnp.where(sel, ivals, 0).sum(
                                axis=1, dtype=jnp.int32
                            ),
                            jnp.where(sel, v, jnp.inf).min(axis=1),
                            jnp.where(sel, v, -jnp.inf).max(axis=1),
                        )
                    )
                elif kind == "counts_doc":
                    ids_e, exists = arrs
                    sel = mask & exists[None, :]
                    outs.append(scatter_rows(ids_e, sel, nbpad))
                else:  # counts_entry
                    gords_e, edocs_e, evalid_e = arrs
                    sel = (
                        jnp.take(mask, edocs_e, axis=1)
                        & evalid_e[None, :]
                    )
                    outs.append(scatter_rows(gords_e, sel, nbpad))
            return (total_e, max_e, tuple(outs))

        per_entry = []
        if has_text:
            per_entry.extend([d_b, t_b, i_b])
        per_entry.append(live_b)
        for desc in node_b:
            per_entry.extend(desc[1:] if desc[0] == "metric" else desc[1:-1])
        per_entry.extend([ti_b, tw_b, tv_b])
        total_f, max_f, outs_f = jax.vmap(
            lambda *xs: entry(xs)
        )(*per_entry)
        totals = jax.lax.psum(
            total_f.sum(axis=0), SHARD_AXIS
        )
        maxs = jax.lax.pmax(max_f.max(axis=0), SHARD_AXIS)
        outs = []
        for desc, out_f in zip(node_descs, outs_f):
            if desc[0] == "metric":
                c_f, s_f, mn_f, mx_f = out_f
                outs.append(
                    (
                        jax.lax.psum(c_f.sum(axis=0), SHARD_AXIS),
                        jax.lax.psum(s_f.sum(axis=0), SHARD_AXIS),
                        jax.lax.pmin(mn_f.min(axis=0), SHARD_AXIS),
                        jax.lax.pmax(mx_f.max(axis=0), SHARD_AXIS),
                    )
                )
            else:
                outs.append(
                    jax.lax.psum(out_f.sum(axis=0), SHARD_AXIS)
                )
        return (totals, maxs) + tuple(
            x for o in outs for x in (o if isinstance(o, tuple) else (o,))
        )

    p3 = P(SHARD_AXIS, None, None)
    p2 = P(SHARD_AXIS, None)
    p_plan = P(SHARD_AXIS, DATA_AXIS, None)
    in_specs: list = []
    if has_text:
        in_specs.extend([p3, p3, p2])
    in_specs.append(p2)
    for desc in node_descs:
        in_specs.extend([p2] * (len(desc) - (2 if desc[0] != "metric" else 1)))
    in_specs.extend([p_plan, p_plan, p_plan, P(DATA_AXIS)])
    out_specs: list = [P(DATA_AXIS), P(DATA_AXIS)]
    for desc in node_descs:
        if desc[0] == "metric":
            out_specs.extend([P(DATA_AXIS)] * 4)
        else:
            out_specs.append(P(DATA_AXIS, None))
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )
    static_arrays: list = []
    if has_text:
        static_arrays.extend(list(text))
    static_arrays.append(live)
    for desc in node_descs:
        static_arrays.extend(desc[1:-1] if desc[0] != "metric" else desc[1:])

    @jax.jit
    def step(ti, tw, tv, msm):
        return fn(*static_arrays, ti, tw, tv, msm)

    return step


def rrf_fuse(
    lex: ShardedTopK, vec: ShardedTopK, k: int, rank_constant: int = 60
) -> Tuple[jax.Array, jax.Array]:
    """Reciprocal-rank fusion of two ranked lists (x-pack rank-rrf:
    `RRFQueryPhaseRankCoordinatorContext`, score = Σ 1/(rank_constant+rank)).

    Device-side via the shared ops/fusion kernel (also the serving
    path's fuser): exact-doc dedup over the union of both lists, top-k
    with ascending-global-doc tie-break. Returns (scores[B,k],
    global_docs[B,k])."""
    from ..ops.fusion import rrf_fuse_device

    return rrf_fuse_device(
        (lex.global_docs, vec.global_docs), k, rank_constant
    )
