"""Pipeline + processor implementations.

Reference analogs, per processor: modules/ingest-common's
SetProcessor, RemoveProcessor, RenameProcessor, ConvertProcessor,
LowercaseProcessor/UppercaseProcessor/TrimProcessor (AbstractString
Processor), SplitProcessor, JoinProcessor, GsubProcessor,
AppendProcessor, DateProcessor, JsonProcessor, KeyValueProcessor,
DotExpanderProcessor, HtmlStripProcessor, FailProcessor, DropProcessor,
ScriptProcessor, PipelineProcessor. Common config (`if`, `tag`,
`ignore_failure`, `on_failure`) mirrors ConfigurationUtils +
CompoundProcessor semantics: a failing processor runs its on_failure
chain (with error metadata bound) or aborts the document.
"""

from __future__ import annotations

import datetime as _dt
import json
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class IngestError(Exception):
    def __init__(self, reason: str, err_type: str = "illegal_argument_exception"):
        super().__init__(reason)
        self.reason = reason
        self.err_type = err_type


class DropDocument(Exception):
    """Raised by the drop processor: the doc is silently discarded."""


# ---------------------------------------------------------------------------
# dotted-path ctx access (IngestDocument.getFieldValue/setFieldValue)
# ---------------------------------------------------------------------------


def get_field(ctx: dict, path: str, default=None):
    node: Any = ctx
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return default
    return node


def has_field(ctx: dict, path: str) -> bool:
    sentinel = object()
    return get_field(ctx, path, sentinel) is not sentinel


def set_field(ctx: dict, path: str, value) -> None:
    parts = path.split(".")
    node = ctx
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            node[part] = nxt
        node = nxt
    node[parts[-1]] = value


def remove_field(ctx: dict, path: str) -> bool:
    parts = path.split(".")
    node = ctx
    for part in parts[:-1]:
        node = node.get(part) if isinstance(node, dict) else None
        if node is None:
            return False
    if isinstance(node, dict) and parts[-1] in node:
        del node[parts[-1]]
        return True
    return False


_TEMPLATE_RE = re.compile(r"\{\{\{?\s*([\w.@_]+)\s*\}?\}\}")


def render_template(value, ctx: dict):
    """Mustache-lite `{{field}}` substitution (ingest template snippets)."""
    if not isinstance(value, str) or "{{" not in value:
        return value
    def sub(m):
        v = get_field(ctx, m.group(1))
        return "" if v is None else str(v)
    return _TEMPLATE_RE.sub(sub, value)


# ---------------------------------------------------------------------------
# processors
# ---------------------------------------------------------------------------


class Processor:
    TYPE = "?"

    def __init__(self, cfg: dict):
        self.tag = cfg.get("tag")
        self.if_cond = cfg.get("if")
        self.ignore_failure = bool(cfg.get("ignore_failure", False))
        self.on_failure = [
            build_processor(p) for p in (cfg.get("on_failure") or [])
        ]
        self.description = cfg.get("description")

    def _required(self, cfg: dict, key: str):
        if key not in cfg:
            raise IngestError(
                f"[{self.TYPE}] [{key}] required property is missing"
            )
        return cfg[key]

    def should_run(self, ctx: dict) -> bool:
        if self.if_cond is None:
            return True
        from ..script import script_service

        return script_service.run_condition(self.if_cond, ctx)

    def process(self, ctx: dict) -> None:
        raise NotImplementedError


class SetProcessor(Processor):
    TYPE = "set"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        if "value" not in cfg and "copy_from" not in cfg:
            raise IngestError("[set] [value] required property is missing")
        self.value = cfg.get("value")
        self.copy_from = cfg.get("copy_from")
        self.override = bool(cfg.get("override", True))

    def process(self, ctx):
        if not self.override and has_field(ctx, self.field):
            return
        if self.copy_from is not None:
            if not has_field(ctx, self.copy_from):
                raise IngestError(f"field [{self.copy_from}] not present")
            value = get_field(ctx, self.copy_from)
        else:
            value = render_template(self.value, ctx)
        set_field(ctx, self.field, value)


class RemoveProcessor(Processor):
    TYPE = "remove"

    def __init__(self, cfg):
        super().__init__(cfg)
        field = self._required(cfg, "field")
        self.fields = field if isinstance(field, list) else [field]
        self.ignore_missing = bool(cfg.get("ignore_missing", False))

    def process(self, ctx):
        for f in self.fields:
            if not remove_field(ctx, f) and not self.ignore_missing:
                raise IngestError(f"field [{f}] not present as part of path [{f}]")


class RenameProcessor(Processor):
    TYPE = "rename"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.target_field = self._required(cfg, "target_field")
        self.ignore_missing = bool(cfg.get("ignore_missing", False))

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(
                f"field [{self.field}] doesn't exist"
            )
        if has_field(ctx, self.target_field):
            raise IngestError(
                f"field [{self.target_field}] already exists"
            )
        set_field(ctx, self.target_field, get_field(ctx, self.field))
        remove_field(ctx, self.field)


class ConvertProcessor(Processor):
    TYPE = "convert"

    _CASTS: Dict[str, Callable] = {
        "integer": int,
        "long": int,
        "float": float,
        "double": float,
        "string": str,
        "boolean": lambda v: (
            v if isinstance(v, bool)
            else {"true": True, "false": False}[str(v).lower()]
        ),
    }

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.type = self._required(cfg, "type")
        self.target_field = cfg.get("target_field", self.field)
        self.ignore_missing = bool(cfg.get("ignore_missing", False))
        if self.type not in (*self._CASTS, "auto"):
            raise IngestError(f"type [{self.type}] not supported")

    def _auto(self, v):
        s = str(v)
        for cast in (int, float):
            try:
                return cast(s)
            except ValueError:
                pass
        if s.lower() in ("true", "false"):
            return s.lower() == "true"
        return s

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(f"field [{self.field}] not present")
        v = get_field(ctx, self.field)
        cast = self._auto if self.type == "auto" else self._CASTS[self.type]
        try:
            out = [cast(x) for x in v] if isinstance(v, list) else cast(v)
        except (ValueError, KeyError, TypeError):
            raise IngestError(
                f"unable to convert [{v}] to {self.type}"
            )
        set_field(ctx, self.target_field, out)


class _StringProcessor(Processor):
    FN: Callable[[str], str] = staticmethod(lambda s: s)

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.target_field = cfg.get("target_field", self.field)
        self.ignore_missing = bool(cfg.get("ignore_missing", False))

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(f"field [{self.field}] not present")
        v = get_field(ctx, self.field)
        fn = type(self).FN
        if isinstance(v, list):
            out = [fn(str(x)) for x in v]
        elif not isinstance(v, str):
            raise IngestError(
                f"field [{self.field}] of type "
                f"[{type(v).__name__}] cannot be cast to string"
            )
        else:
            out = fn(v)
        set_field(ctx, self.target_field, out)


class LowercaseProcessor(_StringProcessor):
    TYPE = "lowercase"
    FN = staticmethod(str.lower)


class UppercaseProcessor(_StringProcessor):
    TYPE = "uppercase"
    FN = staticmethod(str.upper)


class TrimProcessor(_StringProcessor):
    TYPE = "trim"
    FN = staticmethod(str.strip)


class HtmlStripProcessor(_StringProcessor):
    TYPE = "html_strip"
    FN = staticmethod(lambda s: re.sub(r"<[^>]*>", "", s))


class SplitProcessor(Processor):
    TYPE = "split"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.separator = self._required(cfg, "separator")
        self.target_field = cfg.get("target_field", self.field)
        self.ignore_missing = bool(cfg.get("ignore_missing", False))
        self.preserve_trailing = bool(cfg.get("preserve_trailing", False))

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(f"field [{self.field}] not present")
        v = get_field(ctx, self.field)
        if not isinstance(v, str):
            raise IngestError(f"field [{self.field}] is not a string")
        parts = re.split(self.separator, v)
        if not self.preserve_trailing:
            while parts and parts[-1] == "":
                parts.pop()
        set_field(ctx, self.target_field, parts)


class JoinProcessor(Processor):
    TYPE = "join"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.separator = self._required(cfg, "separator")
        self.target_field = cfg.get("target_field", self.field)

    def process(self, ctx):
        v = get_field(ctx, self.field)
        if not isinstance(v, list):
            raise IngestError(f"field [{self.field}] is not a list")
        set_field(ctx, self.target_field, self.separator.join(str(x) for x in v))


class GsubProcessor(Processor):
    TYPE = "gsub"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.pattern = re.compile(self._required(cfg, "pattern"))
        self.replacement = self._required(cfg, "replacement")
        self.target_field = cfg.get("target_field", self.field)
        self.ignore_missing = bool(cfg.get("ignore_missing", False))

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(f"field [{self.field}] not present")
        v = get_field(ctx, self.field)
        if not isinstance(v, str):
            raise IngestError(f"field [{self.field}] is not a string")
        set_field(ctx, self.target_field, self.pattern.sub(self.replacement, v))


class AppendProcessor(Processor):
    TYPE = "append"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.value = self._required(cfg, "value")
        self.allow_duplicates = bool(cfg.get("allow_duplicates", True))

    def process(self, ctx):
        add = self.value if isinstance(self.value, list) else [self.value]
        add = [render_template(v, ctx) for v in add]
        cur = get_field(ctx, self.field)
        if cur is None:
            cur = []
        elif not isinstance(cur, list):
            cur = [cur]
        else:
            cur = list(cur)
        for v in add:
            if self.allow_duplicates or v not in cur:
                cur.append(v)
        set_field(ctx, self.field, cur)


class DateProcessor(Processor):
    TYPE = "date"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.formats = self._required(cfg, "formats")
        self.target_field = cfg.get("target_field", "@timestamp")
        self.output_format = cfg.get("output_format", "%Y-%m-%dT%H:%M:%S.%f")

    def _parse(self, v):
        for fmt in self.formats:
            if fmt == "ISO8601":
                try:
                    return _dt.datetime.fromisoformat(str(v).replace("Z", "+00:00"))
                except ValueError:
                    continue
            if fmt == "UNIX":
                try:
                    return _dt.datetime.fromtimestamp(float(v), _dt.timezone.utc)
                except (ValueError, TypeError):
                    continue
            if fmt == "UNIX_MS":
                try:
                    return _dt.datetime.fromtimestamp(
                        float(v) / 1000.0, _dt.timezone.utc
                    )
                except (ValueError, TypeError):
                    continue
            try:
                return _dt.datetime.strptime(str(v), fmt)
            except ValueError:
                continue
        raise IngestError(
            f"unable to parse date [{v}] using formats {self.formats}"
        )

    def process(self, ctx):
        v = get_field(ctx, self.field)
        if v is None:
            raise IngestError(f"field [{self.field}] not present")
        dt = self._parse(v)
        set_field(
            ctx, self.target_field, dt.strftime(self.output_format)[:-3]
            if self.output_format.endswith("%f")
            else dt.strftime(self.output_format),
        )


class JsonProcessor(Processor):
    TYPE = "json"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.target_field = cfg.get("target_field")
        self.add_to_root = bool(cfg.get("add_to_root", False))

    def process(self, ctx):
        v = get_field(ctx, self.field)
        try:
            parsed = json.loads(v)
        except (TypeError, json.JSONDecodeError) as e:
            raise IngestError(f"field [{self.field}] is not valid JSON: {e}")
        if self.add_to_root:
            if not isinstance(parsed, dict):
                raise IngestError("cannot add non-object JSON to root")
            ctx.update(parsed)
        else:
            set_field(ctx, self.target_field or self.field, parsed)


class KvProcessor(Processor):
    TYPE = "kv"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")
        self.field_split = self._required(cfg, "field_split")
        self.value_split = self._required(cfg, "value_split")
        self.target_field = cfg.get("target_field")
        self.ignore_missing = bool(cfg.get("ignore_missing", False))

    def process(self, ctx):
        if not has_field(ctx, self.field):
            if self.ignore_missing:
                return
            raise IngestError(f"field [{self.field}] not present")
        v = str(get_field(ctx, self.field))
        out = {}
        for pair in re.split(self.field_split, v):
            if not pair:
                continue
            kv = re.split(self.value_split, pair, maxsplit=1)
            if len(kv) == 2:
                out[kv[0]] = kv[1]
        if self.target_field:
            set_field(ctx, self.target_field, out)
        else:
            for k, val in out.items():
                set_field(ctx, k, val)


class DotExpanderProcessor(Processor):
    TYPE = "dot_expander"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.field = self._required(cfg, "field")

    def process(self, ctx):
        fields = (
            [k for k in list(ctx) if "." in k and not k.startswith("_")]
            if self.field == "*"
            else [self.field]
        )
        for f in fields:
            if f in ctx:
                v = ctx.pop(f)
                set_field(ctx, f, v)


class FailProcessor(Processor):
    TYPE = "fail"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.message = self._required(cfg, "message")

    def process(self, ctx):
        raise IngestError(render_template(self.message, ctx))


class DropProcessor(Processor):
    TYPE = "drop"

    def __init__(self, cfg):
        super().__init__(cfg)

    def process(self, ctx):
        raise DropDocument()


class ScriptProcessor(Processor):
    TYPE = "script"

    def __init__(self, cfg):
        super().__init__(cfg)
        if "source" in cfg or "id" in cfg:
            self.script = {
                k: cfg[k] for k in ("source", "id", "params") if k in cfg
            }
        else:
            self.script = self._required(cfg, "script")

    def process(self, ctx):
        from ..script import ScriptError, script_service

        try:
            script_service.run_ingest(self.script, ctx)
        except ScriptError as e:
            raise IngestError(str(e), "script_exception")


class PipelineProcessor(Processor):
    TYPE = "pipeline"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.name = self._required(cfg, "name")
        self.ignore_missing_pipeline = bool(
            cfg.get("ignore_missing_pipeline", False)
        )
        self._service: Optional["IngestService"] = None  # bound at exec

    def process(self, ctx):
        if self._service is None:
            raise IngestError("pipeline processor not bound to a service")
        pipeline = self._service.pipelines.get(self.name)
        if pipeline is None:
            if self.ignore_missing_pipeline:
                return
            raise IngestError(f"pipeline [{self.name}] does not exist")
        if pipeline.run(ctx, self._service) is None:
            # a drop inside the nested pipeline drops the outer doc too
            raise DropDocument()


PROCESSOR_TYPES: Dict[str, type] = {
    cls.TYPE: cls
    for cls in (
        SetProcessor, RemoveProcessor, RenameProcessor, ConvertProcessor,
        LowercaseProcessor, UppercaseProcessor, TrimProcessor,
        HtmlStripProcessor, SplitProcessor, JoinProcessor, GsubProcessor,
        AppendProcessor, DateProcessor, JsonProcessor, KvProcessor,
        DotExpanderProcessor, FailProcessor, DropProcessor,
        ScriptProcessor, PipelineProcessor,
    )
}


def build_processor(spec: dict) -> Processor:
    if not isinstance(spec, dict) or len(spec) != 1:
        raise IngestError(
            "processor definition must be a single-key object"
        )
    ptype, cfg = next(iter(spec.items()))
    cls = PROCESSOR_TYPES.get(ptype)
    if cls is None:
        raise IngestError(
            f"No processor type exists with name [{ptype}]",
            "parse_exception",
        )
    return cls(cfg if isinstance(cfg, dict) else {})


# ---------------------------------------------------------------------------
# pipeline + service
# ---------------------------------------------------------------------------


class Pipeline:
    def __init__(self, pid: str, body: dict):
        self.id = pid
        self.description = (body or {}).get("description")
        self.processors = [
            build_processor(p) for p in (body or {}).get("processors", [])
        ]
        self.on_failure = [
            build_processor(p) for p in (body or {}).get("on_failure", [])
        ]
        self.body = body or {}

    def run(self, ctx: dict, service: "IngestService") -> Optional[dict]:
        """Runs the chain on ctx in place; returns None if dropped.
        CompoundProcessor semantics: a processor failure runs its
        on_failure chain (with error metadata), else the pipeline's,
        else propagates."""
        try:
            for proc in self.processors:
                self._run_one(proc, ctx, service)
        except DropDocument:
            return None
        except IngestError:
            if not self.on_failure:
                raise
            try:
                for proc in self.on_failure:
                    self._run_one(proc, ctx, service)
            except DropDocument:
                return None
        return ctx

    def _run_one(self, proc: Processor, ctx: dict, service: "IngestService"):
        if isinstance(proc, PipelineProcessor):
            proc._service = service
        try:
            if not proc.should_run(ctx):
                return
            proc.process(ctx)
        except DropDocument:
            raise
        except IngestError as e:
            if proc.ignore_failure:
                return
            if proc.on_failure:
                ctx.setdefault("_ingest", {})["on_failure_message"] = str(e)
                ctx["_ingest"]["on_failure_processor_type"] = proc.TYPE
                if proc.tag:
                    ctx["_ingest"]["on_failure_processor_tag"] = proc.tag
                for handler in proc.on_failure:
                    self._run_one(handler, ctx, service)
                return
            raise


class IngestService:
    """Pipeline registry + bulk execution hook."""

    def __init__(self):
        self.pipelines: Dict[str, Pipeline] = {}
        self._lock = threading.Lock()
        self.stats = {"count": 0, "failed": 0}

    def put_pipeline(self, pid: str, body: dict) -> None:
        pipeline = Pipeline(pid, body)  # parse/validate first
        with self._lock:
            self.pipelines[pid] = pipeline

    def get_pipeline(self, pid: Optional[str] = None) -> Dict[str, dict]:
        if pid is None or pid in ("*", "_all"):
            return {p: pl.body for p, pl in self.pipelines.items()}
        pl = self.pipelines.get(pid)
        if pl is None:
            raise IngestError(
                f"pipeline [{pid}] is missing", "resource_not_found_exception"
            )
        return {pid: pl.body}

    def delete_pipeline(self, pid: str) -> None:
        with self._lock:
            if self.pipelines.pop(pid, None) is None:
                raise IngestError(
                    f"pipeline [{pid}] is missing",
                    "resource_not_found_exception",
                )

    def load(self, bodies: Dict[str, dict]) -> None:
        """Replaces the registry from persisted/published state."""
        with self._lock:
            self.pipelines = {
                pid: Pipeline(pid, body) for pid, body in bodies.items()
            }

    def bodies(self) -> Dict[str, dict]:
        return {pid: pl.body for pid, pl in self.pipelines.items()}

    def execute(
        self, pid: str, source: dict, index: str, doc_id: Optional[str]
    ) -> Optional[dict]:
        """Runs one document through a pipeline. Returns the transformed
        source, or None if dropped. Metadata fields ride the ctx and are
        stripped back out (IngestDocument's metadata handling)."""
        pl = self.pipelines.get(pid)
        if pl is None:
            raise IngestError(
                f"pipeline with id [{pid}] does not exist",
                "illegal_argument_exception",
            )
        ctx = dict(source)
        ctx["_index"] = index
        if doc_id is not None:
            ctx["_id"] = doc_id
        ctx["_ingest"] = {
            "timestamp": _dt.datetime.now(_dt.timezone.utc).isoformat()
        }
        self.stats["count"] += 1
        try:
            out = pl.run(ctx, self)
        except IngestError:
            self.stats["failed"] += 1
            raise
        if out is None:
            return None
        out.pop("_index", None)
        out.pop("_id", None)
        out.pop("_ingest", None)
        return out

    def simulate(self, pid: Optional[str], body: dict) -> dict:
        """_ingest/pipeline/_simulate: run sample docs, report per-doc
        results or errors."""
        if pid is not None:
            pipeline = self.pipelines.get(pid)
            if pipeline is None:
                raise IngestError(
                    f"pipeline [{pid}] is missing",
                    "resource_not_found_exception",
                )
        else:
            pipeline = Pipeline("_simulate_pipeline", body.get("pipeline") or {})
        docs_out = []
        for doc in body.get("docs", []):
            src = dict(doc.get("_source") or {})
            ctx = dict(src)
            ctx["_index"] = doc.get("_index", "_index")
            ctx["_id"] = doc.get("_id", "_id")
            ctx["_ingest"] = {
                "timestamp": _dt.datetime.now(_dt.timezone.utc).isoformat()
            }
            try:
                out = pipeline.run(ctx, self)
                if out is None:
                    docs_out.append(None)
                    continue
                ts = out.pop("_ingest", {}).get("timestamp")
                meta = {
                    "_index": out.pop("_index", "_index"),
                    "_id": out.pop("_id", "_id"),
                    "_source": out,
                    "_ingest": {"timestamp": ts},
                }
                docs_out.append({"doc": meta})
            except IngestError as e:
                docs_out.append(
                    {"error": {"type": e.err_type, "reason": str(e)}}
                )
        return {"docs": docs_out}
