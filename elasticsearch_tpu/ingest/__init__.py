"""Ingest pipelines: pre-index document processor chains.

Reference analogs: org.elasticsearch.ingest.IngestService
.executeBulkRequest, Pipeline/CompoundProcessor, the Processor SPI, and
the built-in processor pack in modules/ingest-common (SURVEY.md §2.1
Ingest row, §2.3 ingest-common, §3.2 "IngestService.executeBulkRequest
(if pipelines)").
"""

from .service import IngestError, IngestService, Pipeline, PROCESSOR_TYPES

__all__ = ["IngestError", "IngestService", "Pipeline", "PROCESSOR_TYPES"]
